"""TimeSequencePredictor: fit(df) -> best TimeSequencePipeline.

The analog of ``TimeSequencePredictor`` (ref: pyzoo/zoo/automl/
regression/time_sequence_predictor.py:24-220 -- builds the feature
transformer, compiles a recipe into the search engine, runs trials, and
wraps the best config into a pipeline).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np
import pandas as pd

from analytics_zoo_tpu.automl.feature import TimeSequenceFeatureTransformer
from analytics_zoo_tpu.automl.models import TimeSequenceModel
from analytics_zoo_tpu.automl.pipeline import TimeSequencePipeline
from analytics_zoo_tpu.automl.recipes import Recipe, SmokeRecipe
from analytics_zoo_tpu.automl.search import SearchEngine
from analytics_zoo_tpu.common.log import get_logger

logger = get_logger(__name__)


def _unscaler(ft: TimeSequenceFeatureTransformer):
    """[B, future*T] scaled -> data units (rewards must be comparable
    with pipeline.evaluate, and ratio metrics need real units)."""
    t = len(ft.target_col)

    def unscale(arr):
        arr = np.asarray(arr)
        return ft._unscale_y(
            arr.reshape(len(arr), ft.future_seq_len, t)
        ).reshape(len(arr), -1)

    return unscale


def time_sequence_trial(config: Dict[str, Any],
                        data: Dict[str, Any]) -> Dict[str, Any]:
    """One search trial; top-level so it pickles to pool workers
    (ref: ray_tune_search_engine.py train_func :282-346)."""
    spec = data["spec"]
    ft = TimeSequenceFeatureTransformer(**spec)
    x, y = ft.fit_transform(data["train_df"], **config)
    val = None
    if data.get("validation_df") is not None:
        val = ft.transform(data["validation_df"], is_train=True)
    model = TimeSequenceModel(
        future_seq_len=spec["future_seq_len"],
        n_targets=len(ft.target_col))
    reward = model.fit_eval(x, y, validation_data=val,
                            unscale_fn=_unscaler(ft), **config)
    return {"reward_metric": reward, "state": model.state_bytes(),
            "example_x": x[:1]}


class TimeSequencePredictor:
    def __init__(self, name: str = "automl",
                 logs_dir: Optional[str] = None, future_seq_len: int = 1,
                 dt_col: str = "datetime", target_col="value",
                 extra_features_col=None, drop_missing: bool = True,
                 executor: str = "sequential",
                 max_workers: Optional[int] = None,
                 scheduler: str = "fifo"):
        self.name = name
        self.logs_dir = logs_dir
        self.future_seq_len = future_seq_len
        self.dt_col = dt_col
        self.target_col = ([target_col] if isinstance(target_col, str)
                           else list(target_col))
        self.extra_features_col = extra_features_col
        self.drop_missing = drop_missing
        self.executor = executor
        self.max_workers = max_workers
        self.scheduler = scheduler
        self.pipeline: Optional[TimeSequencePipeline] = None

    def _spec(self) -> Dict[str, Any]:
        return {"future_seq_len": self.future_seq_len,
                "dt_col": self.dt_col, "target_col": self.target_col,
                "extra_features_col": self.extra_features_col,
                "drop_missing": self.drop_missing}

    def fit(self, input_df: pd.DataFrame,
            validation_df: Optional[pd.DataFrame] = None,
            recipe: Recipe = None, metric: str = "mse",
            seed: int = 0) -> TimeSequencePipeline:
        """Search over the recipe space; returns the best pipeline
        (ref: time_sequence_predictor.py fit)."""
        recipe = recipe or SmokeRecipe()
        probe_ft = TimeSequenceFeatureTransformer(**self._spec())
        feature_list = probe_ft.get_feature_list(input_df)

        engine = SearchEngine(executor=self.executor,
                              max_workers=self.max_workers,
                              logs_dir=self.logs_dir, name=self.name,
                              scheduler=self.scheduler)
        data = {"spec": self._spec(), "train_df": input_df,
                "validation_df": validation_df}
        engine.compile(data, time_sequence_trial, recipe=recipe,
                       feature_list=feature_list, metric=metric,
                       seed=seed)
        best = engine.run()
        logger.info("best config: %s (%s=%.6g)", best.config, metric,
                    best.reward)

        # rebuild the winner in this process from its serialized weights
        ft = TimeSequenceFeatureTransformer(**self._spec())
        x, _ = ft.fit_transform(input_df, **best.config)
        model = TimeSequenceModel(future_seq_len=self.future_seq_len,
                                  n_targets=len(ft.target_col))
        model.load_state_bytes(best.state, best.config, x[:1])
        self.pipeline = TimeSequencePipeline(ft, model,
                                             config=best.config,
                                             name=self.name)
        return self.pipeline

    def evaluate(self, input_df, metrics=("mse",)):
        self._need_fit()
        return self.pipeline.evaluate(input_df, metrics)

    def predict(self, input_df):
        self._need_fit()
        return self.pipeline.predict(input_df)

    def _need_fit(self):
        if self.pipeline is None:
            raise RuntimeError("call fit() first")
