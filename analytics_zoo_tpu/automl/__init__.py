"""AutoML for time series: search engine, recipes, feature pipeline.

The analog of the reference AutoML subsystem (ref: pyzoo/zoo/automl --
RayTuneSearchEngine + recipes + TimeSequenceFeatureTransformer + tunable
models + TimeSequencePipeline; SURVEY.md section 2.2). Search runs on
host CPUs (trials are small models); the TPU chip serves the final
refit/inference path.
"""

from analytics_zoo_tpu.automl.feature import (  # noqa: F401
    TimeSequenceFeatureTransformer,
)
from analytics_zoo_tpu.automl.models import (  # noqa: F401
    MTNet,
    Seq2SeqForecaster,
    TCN,
    TimeSequenceModel,
    VanillaLSTM,
    build_forecast_module,
)
from analytics_zoo_tpu.automl.pipeline import (  # noqa: F401
    TimeSequencePipeline,
    load_ts_pipeline,
)
from analytics_zoo_tpu.automl.predictor import (  # noqa: F401
    TimeSequencePredictor,
)
from analytics_zoo_tpu.automl.recipes import (  # noqa: F401
    GridRandomRecipe,
    LSTMGridRandomRecipe,
    MTNetGridRandomRecipe,
    Recipe,
    Seq2SeqRandomRecipe,
    SmokeRecipe,
    TCNGridRandomRecipe,
    XgbRegressorGridRandomRecipe,
)
from analytics_zoo_tpu.automl.xgboost import XGBoost  # noqa: F401
from analytics_zoo_tpu.automl.search import (  # noqa: F401
    SearchEngine,
    TrialOutput,
)
from analytics_zoo_tpu.automl import metrics  # noqa: F401
from analytics_zoo_tpu.automl import space  # noqa: F401
