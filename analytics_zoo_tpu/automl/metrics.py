"""Regression / forecasting metrics for AutoML model selection.

The analog of the reference's metric table (ref: pyzoo/zoo/automl/common/
metrics.py -- ME/MAE/MSE/RMSE/MSLE/R2/MPE/MAPE/sMAPE evaluated on numpy
arrays). These run on host numpy: they score whole validation sets once
per trial, not inner training steps, so there is nothing to jit.
"""

from __future__ import annotations

from typing import Dict, Sequence

import numpy as np

EPSILON = 1e-10


def _flatten(y_true, y_pred):
    y_true = np.asarray(y_true, np.float64).reshape(-1)
    y_pred = np.asarray(y_pred, np.float64).reshape(-1)
    if y_true.shape != y_pred.shape:
        raise ValueError(f"shape mismatch: {y_true.shape} vs "
                         f"{y_pred.shape}")
    return y_true, y_pred


def me(y_true, y_pred):
    y_true, y_pred = _flatten(y_true, y_pred)
    return float(np.mean(y_pred - y_true))


def mae(y_true, y_pred):
    y_true, y_pred = _flatten(y_true, y_pred)
    return float(np.mean(np.abs(y_pred - y_true)))


def mse(y_true, y_pred):
    y_true, y_pred = _flatten(y_true, y_pred)
    return float(np.mean((y_pred - y_true) ** 2))


def rmse(y_true, y_pred):
    return float(np.sqrt(mse(y_true, y_pred)))


def msle(y_true, y_pred):
    y_true, y_pred = _flatten(y_true, y_pred)
    if (y_true < 0).any() or (y_pred < 0).any():
        raise ValueError("msle needs non-negative values")
    return float(np.mean((np.log1p(y_pred) - np.log1p(y_true)) ** 2))


def r2(y_true, y_pred):
    y_true, y_pred = _flatten(y_true, y_pred)
    ss_res = np.sum((y_true - y_pred) ** 2)
    ss_tot = np.sum((y_true - np.mean(y_true)) ** 2)
    return float(1.0 - ss_res / (ss_tot + EPSILON))


def mpe(y_true, y_pred):
    y_true, y_pred = _flatten(y_true, y_pred)
    return float(np.mean((y_pred - y_true) / (y_true + EPSILON)) * 100)


def mape(y_true, y_pred):
    y_true, y_pred = _flatten(y_true, y_pred)
    return float(np.mean(np.abs((y_pred - y_true) /
                                (y_true + EPSILON))) * 100)


def smape(y_true, y_pred):
    y_true, y_pred = _flatten(y_true, y_pred)
    denom = (np.abs(y_true) + np.abs(y_pred)) / 2 + EPSILON
    return float(np.mean(np.abs(y_pred - y_true) / denom) * 100)


def accuracy(y_true, y_pred):
    """Classification accuracy over flattened predictions (the
    classifier counterpart of the regression metrics; the XGBoost
    classifier model scores with this)."""
    y_true, y_pred = _flatten(y_true, y_pred)
    return float(np.mean(np.round(y_pred) == np.round(y_true)))


def logloss(y_true, y_pred):
    """Cross-entropy on PROBABILITY predictions (ref: XGBoost.py
    classifier default metric). y_pred [N, C] class probabilities with
    integer labels, or [N] positive-class probabilities for binary.
    Class-id predictions are rejected: logloss on hard 0/1 ids is just
    a scaled error rate, not the documented metric."""
    y_pred = np.asarray(y_pred, np.float64)
    y_true = np.asarray(y_true)
    if y_pred.ndim == 2 and y_pred.shape[1] > 1:
        p = np.clip(y_pred, EPSILON, 1 - EPSILON)
        rows = np.arange(len(p))
        return float(-np.mean(np.log(
            p[rows, y_true.reshape(-1).astype(np.int64)])))
    y_true, y_pred = _flatten(y_true, y_pred)
    if y_true.max(initial=0) > 1:
        raise ValueError("multiclass logloss needs [N, C] probability "
                         "predictions")
    p = np.clip(y_pred, EPSILON, 1 - EPSILON)
    return float(-np.mean(y_true * np.log(p)
                          + (1 - y_true) * np.log(1 - p)))


_METRICS = {
    "me": me, "mae": mae, "mse": mse, "rmse": rmse, "msle": msle,
    "r2": r2, "mpe": mpe, "mape": mape, "smape": smape,
    "accuracy": accuracy, "logloss": logloss,
}

# metrics where larger is better (everything else minimizes)
MAXIMIZE = {"r2", "accuracy"}


def evaluate(metric: str, y_true, y_pred) -> float:
    name = metric.lower()
    if name not in _METRICS:
        raise ValueError(f"unknown metric {metric!r}; "
                         f"have {sorted(_METRICS)}")
    return _METRICS[name](y_true, y_pred)


def evaluate_all(metrics: Sequence[str], y_true, y_pred
                 ) -> Dict[str, float]:
    return {m: evaluate(m, y_true, y_pred) for m in metrics}


def mode_of(metric: str) -> str:
    """'max' if larger is better for this metric, else 'min'."""
    return "max" if metric.lower() in MAXIMIZE else "min"
