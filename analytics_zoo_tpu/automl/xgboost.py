"""XGBoost AutoML model (ref: pyzoo/zoo/automl/model/XGBoost.py).

Same hyper-parameter surface and fit_eval/predict/evaluate/save/restore
contract as the reference's XGBRegressor/XGBClassifier wrapper. The
engine is the real ``xgboost`` package when importable; this image
ships none, so the default is the framework's own histogram GBT
(``analytics_zoo_tpu.ml.gbt`` -- identical second-order training math,
host-side: tree growth is branchy sequential work that has no business
on the MXU).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.automl import metrics as automl_metrics
from analytics_zoo_tpu.ml.gbt import GradientBoostedTrees

_CONFIG_KEYS = ("n_estimators", "max_depth", "learning_rate",
                "min_child_weight", "subsample", "colsample_bytree",
                "gamma", "reg_lambda", "n_bins", "seed")
_DEFAULTS = {"n_estimators": 100, "max_depth": 5, "learning_rate": 0.1,
             "min_child_weight": 1.0, "subsample": 0.8,
             "colsample_bytree": 0.8, "gamma": 0.0, "reg_lambda": 1.0,
             "n_bins": 64, "seed": 0}


def _have_xgboost() -> bool:
    try:
        import xgboost  # noqa: F401

        return True
    except ImportError:
        return False


class XGBoost:
    """model_type: "regressor" or "classifier"
    (ref: XGBoost.py model_type switch)."""

    def __init__(self, model_type: str = "regressor",
                 config: Optional[Dict[str, Any]] = None):
        if model_type not in ("regressor", "classifier"):
            raise ValueError(f"unknown model_type {model_type!r}")
        self.model_type = model_type
        self.config = dict(_DEFAULTS)
        self.config.update({k: v for k, v in (config or {}).items()
                            if k in _CONFIG_KEYS})
        self.metric = (config or {}).get(
            "metric", "rmse" if model_type == "regressor" else "accuracy")
        self.models: list = []     # one per output column
        self._use_xgb = _have_xgboost()

    # ---------------------------------------------------------- build --
    def _new_model(self, num_class: Optional[int] = None):
        c = self.config
        if self._use_xgb:
            from xgboost.sklearn import XGBClassifier, XGBRegressor

            cls = (XGBRegressor if self.model_type == "regressor"
                   else XGBClassifier)
            kwargs = dict(n_estimators=c["n_estimators"],
                          max_depth=c["max_depth"],
                          learning_rate=c["learning_rate"],
                          min_child_weight=c["min_child_weight"],
                          subsample=c["subsample"],
                          colsample_bytree=c["colsample_bytree"],
                          gamma=c["gamma"], reg_lambda=c["reg_lambda"],
                          random_state=c["seed"], tree_method="hist")
            if (self.model_type == "classifier" and num_class
                    and num_class > 2):
                # the full-label-space class count must reach the real
                # engine too, or a validation-only class breaks scoring
                kwargs.update(objective="multi:softprob",
                              num_class=num_class)
            return cls(**kwargs)
        if self.model_type == "regressor":
            objective = "reg:squarederror"
        else:
            objective = ("binary:logistic" if (num_class or 2) <= 2
                         else "multi:softprob")
        return GradientBoostedTrees(
            objective=objective,
            num_class=(num_class if objective == "multi:softprob"
                       else None),
            **{k: c[k] for k in _CONFIG_KEYS if k != "n_bins"},
            n_bins=c["n_bins"])

    # ------------------------------------------------------------ fit --
    def _fit(self, x, y, validation_data=None, **config):
        """Shared training pass; returns the prepared (x, y2)."""
        self.config.update({k: v for k, v in config.items()
                            if k in _CONFIG_KEYS})
        self.metric = config.get("metric", self.metric)
        x = np.asarray(x, np.float32).reshape(len(x), -1)
        y2 = np.asarray(y).reshape(len(y), -1)
        self.models = []
        for j in range(y2.shape[1]):
            col = y2[:, j]
            num_class = None
            if self.model_type == "classifier":
                # class count over the FULL label space: a validation
                # fold can carry a class the training fold lacks, and
                # predict_proba/logloss must still cover it
                hi = int(col.max())
                if validation_data is not None:
                    vy_all = np.asarray(validation_data[1]).reshape(
                        len(validation_data[1]), -1)
                    hi = max(hi, int(vy_all[:, j].max()))
                num_class = int(config.get("num_class", hi + 1))
            m = self._new_model(num_class=num_class)
            m.fit(x, col)
            self.models.append(m)
        return x, y2

    def fit(self, x: np.ndarray, y: np.ndarray, **config) -> "XGBoost":
        """Train only (no scoring pass) -- callers that score
        separately (TimeSequenceModel) skip fit_eval's full-train-set
        predict."""
        self._fit(x, y, validation_data=None, **config)
        return self

    def fit_eval(self, x: np.ndarray, y: np.ndarray,
                 validation_data: Optional[Tuple] = None,
                 **config) -> float:
        """Fit and return the metric on validation (train if absent)
        (ref: XGBoost.fit_eval)."""
        x, y2 = self._fit(x, y, validation_data=validation_data,
                          **config)
        vx, vy = (x, y2) if validation_data is None else (
            np.asarray(validation_data[0], np.float32).reshape(
                len(validation_data[0]), -1),
            np.asarray(validation_data[1]).reshape(
                len(validation_data[1]), -1))
        if (self.metric == "logloss"
                and self.model_type == "classifier"):
            # logloss is defined on probabilities, not class ids
            if vy.shape[1] != 1:
                raise ValueError("logloss scoring supports a single "
                                 "label column")
            return automl_metrics.evaluate(
                "logloss", vy[:, 0], self.predict_proba(vx))
        pred = self.predict(vx)
        return automl_metrics.evaluate(self.metric, vy, pred)

    def predict(self, x: np.ndarray) -> np.ndarray:
        if not self.models:
            raise RuntimeError("model not fitted")
        x = np.asarray(x, np.float32).reshape(len(x), -1)
        cols = [np.asarray(m.predict(x)).reshape(-1)
                for m in self.models]
        return np.stack(cols, axis=1)

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        if self.model_type != "classifier":
            raise ValueError("predict_proba needs model_type=classifier")
        x = np.asarray(x, np.float32).reshape(len(x), -1)
        return np.asarray(self.models[0].predict_proba(x))

    def evaluate(self, x, y, metrics=("mse",)) -> Dict[str, float]:
        y2 = np.asarray(y).reshape(len(y), -1)
        pred = self.predict(np.asarray(x, np.float32))
        return automl_metrics.evaluate_all(metrics, y2, pred)

    # ----------------------------------------------------- persistence --
    def save(self, dir_path: str) -> None:
        os.makedirs(dir_path, exist_ok=True)
        meta = {"model_type": self.model_type, "config": self.config,
                "metric": self.metric, "engine":
                ("xgboost" if self._use_xgb else "gbt"),
                "n_outputs": len(self.models)}
        with open(os.path.join(dir_path, "xgb.json"), "w") as f:
            json.dump(meta, f)
        for j, m in enumerate(self.models):
            path = os.path.join(dir_path, f"model_{j}")
            if self._use_xgb:
                m.save_model(path + ".ubj")
            else:
                m.save(path + ".json")

    @classmethod
    def restore(cls, dir_path: str) -> "XGBoost":
        with open(os.path.join(dir_path, "xgb.json")) as f:
            meta = json.load(f)
        model = cls(model_type=meta["model_type"],
                    config=dict(meta["config"], metric=meta["metric"]))
        if meta["engine"] == "xgboost" and not model._use_xgb:
            raise RuntimeError(
                "checkpoint was written by the real xgboost engine, "
                "which is not importable here")
        model.models = []
        for j in range(meta["n_outputs"]):
            path = os.path.join(dir_path, f"model_{j}")
            if meta["engine"] == "xgboost":
                from xgboost.sklearn import XGBClassifier, XGBRegressor

                m = (XGBRegressor() if meta["model_type"] == "regressor"
                     else XGBClassifier())
                m.load_model(path + ".ubj")
            else:
                m = GradientBoostedTrees.load(path + ".json")
            model.models.append(m)
        return model
