"""Hyperparameter search engine over a local process pool.

The analog of ``RayTuneSearchEngine`` (ref: pyzoo/zoo/automl/search/
ray_tune_search_engine.py:32-471 -- tune.run over a Trainable that
fit_evals a model per sampled config). The TPU redesign schedules trials
itself: configs come from :mod:`space` expansion, each trial runs a
picklable ``trial_fn(config, data) -> {"reward_metric", "state"}`` either
in-process (``executor="sequential"``) or on a spawn-context process pool
(``executor="process"``). Trial processes are pinned to the CPU backend
via JAX_PLATFORMS so a fleet of small searches never contends for the
TPU chip -- the chip belongs to the final refit/serving path.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from analytics_zoo_tpu.automl import metrics as automl_metrics
from analytics_zoo_tpu.automl.space import expand_and_sample
from analytics_zoo_tpu.common.log import get_logger

logger = get_logger(__name__)


@dataclass
class TrialOutput:
    """(ref: search/abstract.py TrialOutput)."""

    config: Dict[str, Any]
    reward: Optional[float] = None
    state: Optional[bytes] = None
    error: Optional[str] = None
    extras: Dict[str, Any] = field(default_factory=dict)


_WORKER_DATA = None  # per-pool-worker dataset, set once by initializer


def _trial_entry(trial_fn, config, data):
    """Top-level so it pickles under the spawn start method. ``data`` is
    the sentinel ``_FROM_WORKER`` in pool workers (the dataset shipped
    once via the initializer, not re-pickled per trial)."""
    if data is _FROM_WORKER:
        data = _WORKER_DATA
    try:
        result = trial_fn(config, data)
        return TrialOutput(config=config,
                           reward=float(result["reward_metric"]),
                           state=result.get("state"),
                           extras={k: v for k, v in result.items()
                                   if k not in ("reward_metric", "state")})
    except Exception as e:  # a failed trial must not sink the search
        import traceback

        return TrialOutput(config=config,
                           error=f"{e}\n{traceback.format_exc()}")


class _FromWorker:
    def __reduce__(self):
        return (_get_sentinel, ())


def _get_sentinel():
    return _FROM_WORKER


_FROM_WORKER = _FromWorker()


def _init_cpu_worker(data=None):
    # trials run on host CPU; never grab the TPU from a pool worker
    os.environ["JAX_PLATFORMS"] = "cpu"
    global _WORKER_DATA
    _WORKER_DATA = data


class SearchEngine:
    """compile() -> run() -> get_best_trials(k).

    Args:
      executor: "sequential" (in-process) or "process" (spawn pool).
      max_workers: pool width for the process executor.
      logs_dir: when set, each trial's reward lands in a TensorBoard
        event file (ref: automl/logger/tensorboardxlogger.py).
    """

    def __init__(self, executor: str = "sequential",
                 max_workers: Optional[int] = None,
                 logs_dir: Optional[str] = None, name: str = "automl"):
        if executor not in ("sequential", "process"):
            raise ValueError("executor must be sequential|process")
        self.executor = executor
        self.max_workers = max_workers or min(4, os.cpu_count() or 1)
        self.logs_dir = logs_dir
        self.name = name
        self.trial_fn: Optional[Callable] = None
        self.data: Any = None
        self.configs: List[Dict[str, Any]] = []
        self.metric = "mse"
        self.mode = "min"
        self.trials: List[TrialOutput] = []

    # ----------------------------------------------------------- setup --
    def compile(self, data: Any, trial_fn: Callable, recipe=None,
                search_space: Optional[Dict[str, Any]] = None,
                feature_list: Optional[List[str]] = None,
                metric: str = "mse", seed: int = 0) -> None:
        """Freeze the trial plan (ref: RayTuneSearchEngine.compile).

        ``recipe`` supplies search_space(feature_list) + runtime params;
        alternatively pass an explicit ``search_space`` dict.
        """
        self.data = data
        self.trial_fn = trial_fn
        self.metric = metric
        self.mode = automl_metrics.mode_of(metric)
        num_samples = 1
        if recipe is not None:
            search_space = recipe.search_space(feature_list or [])
            runtime = recipe.runtime_params()
            num_samples = int(runtime.get("num_samples", 1))
            iters = int(runtime.get("training_iteration", 1))
            # reference semantics: tune reruns the trainable
            # training_iteration times, each pass training the space's
            # `epochs`; the flat total is epochs * training_iteration
            search_space["epochs"] = (
                int(search_space.get("epochs", 1)) * iters)
        if search_space is None:
            raise ValueError("need recipe or search_space")
        search_space.setdefault("metric", metric)
        self.configs = expand_and_sample(search_space,
                                         num_samples=num_samples,
                                         seed=seed)
        logger.info("search compiled: %d trials", len(self.configs))

    # ------------------------------------------------------------- run --
    def run(self) -> TrialOutput:
        if self.trial_fn is None:
            raise RuntimeError("compile() first")
        if self.executor == "process" and len(self.configs) > 1:
            self.trials = self._run_pool()
        else:
            self.trials = [_trial_entry(self.trial_fn, c, self.data)
                           for c in self.configs]
        self._log_trials()
        ok = [t for t in self.trials if t.error is None]
        if not ok:
            errors = "; ".join((t.error or "").splitlines()[0]
                               for t in self.trials[:3])
            raise RuntimeError(f"all {len(self.trials)} trials failed: "
                               f"{errors}")
        return self.get_best_trials(1)[0]

    def _run_pool(self) -> List[TrialOutput]:
        import multiprocessing as mp
        from concurrent.futures import ProcessPoolExecutor

        ctx = mp.get_context("spawn")
        with ProcessPoolExecutor(max_workers=self.max_workers,
                                 mp_context=ctx,
                                 initializer=_init_cpu_worker,
                                 initargs=(self.data,)) as pool:
            # dataset ships once per worker via the initializer; each
            # submit carries only the config + the sentinel
            futures = [pool.submit(_trial_entry, self.trial_fn, c,
                                   _FROM_WORKER)
                       for c in self.configs]
            return [f.result() for f in futures]

    def _log_trials(self) -> None:
        for i, t in enumerate(self.trials):
            if t.error is not None:
                logger.warning("trial %d failed: %s", i,
                               t.error.splitlines()[0])
            else:
                logger.info("trial %d: %s=%.6g", i, self.metric, t.reward)
        if self.logs_dir:
            from analytics_zoo_tpu.utils.summary import SummaryWriter

            writer = SummaryWriter(os.path.join(self.logs_dir, self.name))
            try:
                for i, t in enumerate(self.trials):
                    if t.error is None:
                        writer.add_scalar(f"search/{self.metric}",
                                          t.reward, i)
            finally:
                writer.close()

    def get_best_trials(self, k: int = 1) -> List[TrialOutput]:
        """(ref: RayTuneSearchEngine.get_best_trials)."""
        ok = [t for t in self.trials if t.error is None]
        reverse = self.mode == "max"
        return sorted(ok, key=lambda t: t.reward, reverse=reverse)[:k]
