"""Hyperparameter search engine over a local process pool.

The analog of ``RayTuneSearchEngine`` (ref: pyzoo/zoo/automl/search/
ray_tune_search_engine.py:32-471 -- tune.run over a Trainable that
fit_evals a model per sampled config). The TPU redesign schedules trials
itself: configs come from :mod:`space` expansion, each trial runs a
picklable ``trial_fn(config, data) -> {"reward_metric", "state"}`` either
in-process (``executor="sequential"``), on a spawn-context process pool
(``executor="process"``), or as lanes of a vmapped population cohort
(``executor="vectorized"``, :mod:`automl.vectorized` -- shape-compatible
configs train as ONE compiled program). Pool trial processes are pinned
to the CPU backend via JAX_PLATFORMS so a fleet of small searches never
contends for the TPU chip -- the chip belongs to the final refit/serving
path; the vectorized executor is the opposite bet, made for the chip.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from analytics_zoo_tpu.automl import metrics as automl_metrics
from analytics_zoo_tpu.automl.space import expand_and_sample
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.obs.events import emit
from analytics_zoo_tpu.obs.metrics import get_registry

logger = get_logger(__name__)

_M_TRIALS = get_registry().counter(
    "zoo_automl_trials_total",
    "Search trials completed, by outcome", labelnames=("outcome",))
_M_SEARCHES = get_registry().counter(
    "zoo_automl_searches_total",
    "Searches run, by stop reason", labelnames=("reason",))


@dataclass
class TrialOutput:
    """(ref: search/abstract.py TrialOutput)."""

    config: Dict[str, Any]
    reward: Optional[float] = None
    state: Optional[bytes] = None
    error: Optional[str] = None
    extras: Dict[str, Any] = field(default_factory=dict)


_WORKER_DATA = None  # per-pool-worker dataset, set once by initializer


def _trial_entry(trial_fn, config, data):
    """Top-level so it pickles under the spawn start method. ``data`` is
    the sentinel ``_FROM_WORKER`` in pool workers (the dataset shipped
    once via the initializer, not re-pickled per trial)."""
    if data is _FROM_WORKER:
        data = _WORKER_DATA
    try:
        result = trial_fn(config, data)
        return TrialOutput(config=config,
                           reward=float(result["reward_metric"]),
                           state=result.get("state"),
                           extras={k: v for k, v in result.items()
                                   if k not in ("reward_metric", "state")})
    except Exception as e:  # a failed trial must not sink the search
        import traceback

        return TrialOutput(config=config,
                           error=f"{e}\n{traceback.format_exc()}")


class _FromWorker:
    def __reduce__(self):
        return (_get_sentinel, ())


def _get_sentinel():
    return _FROM_WORKER


_FROM_WORKER = _FromWorker()


def _init_cpu_worker(data=None):
    # trials run on host CPU; never grab the TPU from a pool worker
    os.environ["JAX_PLATFORMS"] = "cpu"
    global _WORKER_DATA
    _WORKER_DATA = data


class SearchEngine:
    """compile() -> run() -> get_best_trials(k).

    Args:
      executor: "sequential" (in-process), "process" (spawn pool), or
        "vectorized" (shape-compatible configs train as lanes of one
        vmapped population -- :mod:`automl.vectorized`; requires a
        trial_fn with a cohort-runner form, e.g. the built-in
        ``time_sequence_trial``).
      max_workers: pool width for the process executor.
      logs_dir: when set, each trial's reward lands in a TensorBoard
        event file (ref: automl/logger/tensorboardxlogger.py).
      scheduler: "fifo" runs every trial to its full epoch budget;
        "asha" runs synchronous successive halving -- rung r gives every
        surviving config ``grace_epochs * reduction_factor**r`` epochs
        and promotes the top ``1/reduction_factor`` fraction, so the
        search budget concentrates on promising configs (the
        stop/scheduler role of the reference's Ray Tune path, ref:
        pyzoo/zoo/automl/search/ray_tune_search_engine.py:56-147).
      reduction_factor / grace_epochs: ASHA rung geometry.
    """

    def __init__(self, executor: str = "sequential",
                 max_workers: Optional[int] = None,
                 logs_dir: Optional[str] = None, name: str = "automl",
                 scheduler: str = "fifo", reduction_factor: int = 4,
                 grace_epochs: int = 1):
        if executor not in ("sequential", "process", "vectorized"):
            raise ValueError(
                "executor must be sequential|process|vectorized")
        if scheduler not in ("fifo", "asha"):
            raise ValueError("scheduler must be fifo|asha")
        if reduction_factor < 2:
            raise ValueError("reduction_factor must be >= 2")
        self.executor = executor
        self.max_workers = max_workers or min(4, os.cpu_count() or 1)
        self.logs_dir = logs_dir
        self.name = name
        self.scheduler = scheduler
        self.reduction_factor = reduction_factor
        self.grace_epochs = max(1, int(grace_epochs))
        self.trial_fn: Optional[Callable] = None
        self.data: Any = None
        self.configs: List[Dict[str, Any]] = []
        self.metric = "mse"
        self.mode = "min"
        self.trials: List[TrialOutput] = []
        self.stop: Optional[Dict[str, Any]] = None
        self.total_trial_epochs = 0
        # why the last run() ended: "reward" | "total_epochs" (a stop
        # criterion tripped) or "exhausted" (every config ran). The
        # total_epochs cap is checked BETWEEN work units, so the unit
        # in flight when it trips (one trial on fifo, one rung on
        # asha) completes -- the spend overshoots by up to that unit.
        self.stopped_reason: Optional[str] = None
        self._vec_runner = None

    # ----------------------------------------------------------- setup --
    def compile(self, data: Any, trial_fn: Callable, recipe=None,
                search_space: Optional[Dict[str, Any]] = None,
                feature_list: Optional[List[str]] = None,
                metric: str = "mse", seed: int = 0,
                stop: Optional[Dict[str, Any]] = None) -> None:
        """Freeze the trial plan (ref: RayTuneSearchEngine.compile).

        ``recipe`` supplies search_space(feature_list) + runtime params;
        alternatively pass an explicit ``search_space`` dict.

        ``stop`` gives early-stop criteria (the tune.run ``stop`` role),
        honored by BOTH schedulers: ``{"reward": x}`` ends the search
        once any trial reaches x (>= x for max-mode metrics, <= x for
        min-mode); ``{"total_epochs": n}`` stops launching work once
        the summed trial-epochs budget reaches n (the work unit in
        flight when the cap trips -- one trial on fifo, one rung on
        asha -- completes, so the spend can overshoot by that unit).
        """
        self.data = data
        self.trial_fn = trial_fn
        self.metric = metric
        self.stop = dict(stop) if stop else None
        self.mode = automl_metrics.mode_of(metric)
        num_samples = 1
        if recipe is not None:
            search_space = recipe.search_space(feature_list or [])
            runtime = recipe.runtime_params()
            num_samples = int(runtime.get("num_samples", 1))
            iters = int(runtime.get("training_iteration", 1))
            # reference semantics: tune reruns the trainable
            # training_iteration times, each pass training the space's
            # `epochs`; the flat total is epochs * training_iteration
            search_space["epochs"] = (
                int(search_space.get("epochs", 1)) * iters)
        if search_space is None:
            raise ValueError("need recipe or search_space")
        search_space.setdefault("metric", metric)
        self.configs = expand_and_sample(search_space,
                                         num_samples=num_samples,
                                         seed=seed)
        if self.executor == "vectorized":
            from analytics_zoo_tpu.automl.vectorized import make_runner

            self._vec_runner = make_runner(trial_fn, data)
            if self._vec_runner is None:
                raise ValueError(
                    "executor='vectorized' needs a trial_fn with a "
                    "cohort-runner form (time_sequence_trial, or a "
                    "trial_fn exposing .cohort_runner(data, trial_fn))")
        logger.info("search compiled: %d trials", len(self.configs))

    # ------------------------------------------------------------- run --
    def run(self) -> TrialOutput:
        if self.trial_fn is None:
            raise RuntimeError("compile() first")
        self.total_trial_epochs = 0
        self.stopped_reason = "exhausted"
        if self._vec_runner is not None:
            self._vec_runner.reset()
        emit("automl_search_start", "automl", name=self.name,
             trials=len(self.configs), executor=self.executor,
             scheduler=self.scheduler)
        if self.scheduler == "asha" and len(self.configs) > 1:
            self.trials = self._run_asha()
        else:
            self.trials = self._run_fifo()
        self._log_trials()
        for i, t in enumerate(self.trials):
            _M_TRIALS.labels(
                outcome="error" if t.error is not None else "ok").inc()
            emit("automl_search_trial", "automl", name=self.name,
                 index=i, ok=t.error is None, reward=t.reward,
                 rung=t.extras.get("rung"))
        ok = [t for t in self.trials if t.error is None]
        _M_SEARCHES.labels(reason=self.stopped_reason).inc()
        emit("automl_search_stop", "automl", name=self.name,
             reason=self.stopped_reason, trials=len(self.trials),
             failed=len(self.trials) - len(ok),
             total_epochs=self.total_trial_epochs)
        if not ok:
            errors = "; ".join((t.error or "").splitlines()[0]
                               for t in self.trials[:3])
            raise RuntimeError(f"all {len(self.trials)} trials failed: "
                               f"{errors}")
        return self.get_best_trials(1)[0]

    def _run_fifo(self) -> List[TrialOutput]:
        """Every config at its full budget; stop criteria between
        trials (sequential) or between submission waves (pool)."""
        if not self.stop:
            self.total_trial_epochs = sum(
                int(c.get("epochs", 1)) for c in self.configs)
            return self._run_trials(self.configs)
        outs: List[TrialOutput] = []
        wave = (self.max_workers if self.executor == "process" else 1)
        i = 0
        while i < len(self.configs):
            if self._epoch_cap_reached():
                self.stopped_reason = "total_epochs"
                logger.info("fifo: total_epochs cap reached after %d "
                            "trials", i)
                break
            chunk = self.configs[i:i + wave]
            outs.extend(self._run_trials(chunk))
            self.total_trial_epochs += sum(
                int(c.get("epochs", 1)) for c in chunk)
            i += len(chunk)
            if self._reward_reached(
                    [t.reward for t in outs if t.error is None]):
                self.stopped_reason = "reward"
                logger.info("fifo: reward target reached after %d "
                            "trials", i)
                break
        return outs

    def _run_asha(self) -> List[TrialOutput]:
        """Synchronous successive halving over cumulative epoch rungs.

        Configs re-train from scratch at each rung's (larger) budget --
        trials here are short CPU fits, so re-running beats carrying
        checkpoint state across a process pool; the asymptotic budget
        shape matches ASHA's (geometric rungs, top-1/rf promotion).
        Configs whose own epoch budget a rung already covered are NOT
        re-run; their previous result carries forward.
        """
        import math

        rf = self.reduction_factor
        n = len(self.configs)
        max_ep = max(int(c.get("epochs", 1)) for c in self.configs)
        budgets: List[int] = []
        b = self.grace_epochs
        while b < max_ep:
            budgets.append(b)
            b *= rf
        budgets.append(max_ep)
        # latest result per ORIGINAL config index (eliminated configs
        # keep their last-rung result so nothing drops out of trials/
        # logging/get_best_trials)
        results: List[Optional[TrialOutput]] = [None] * n
        ran_epochs = [0] * n  # effective epochs of the stored result
        alive = list(range(n))
        for rung, budget in enumerate(budgets):
            final = rung == len(budgets) - 1
            todo = []
            for i in alive:
                eff = min(budget, int(self.configs[i].get("epochs", 1)))
                if eff != ran_epochs[i]:  # budget already covered: skip
                    todo.append((i, eff))
            # the rung config (and hence TrialOutput.config) carries
            # the epochs the stored model state ACTUALLY trains (the
            # rung budget), not the requested full budget -- pipeline
            # metadata must match the trained state (ADVICE r4); the
            # original ask is reported in extras["requested_epochs"]
            rung_cfgs = [dict(self.configs[i], epochs=eff)
                         for i, eff in todo]
            outs = self._run_trials(rung_cfgs)
            self.total_trial_epochs += sum(eff for _, eff in todo)
            for (i, eff), t in zip(todo, outs):
                t.extras["rung"] = rung
                t.extras["rung_epochs"] = eff
                t.extras["requested_epochs"] = int(
                    self.configs[i].get("epochs", 1))
                results[i] = t
                ran_epochs[i] = eff
            scored = sorted(
                [(results[i].reward, i) for i in alive
                 if results[i] is not None
                 and results[i].error is None],
                key=lambda p: p[0], reverse=self.mode == "max")
            if not scored:
                return [r for r in results if r is not None]
            logger.info("asha rung %d (%d epochs): %d/%d trials, "
                        "best %s=%.6g", rung, budget, len(scored),
                        len(alive), self.metric, scored[0][0])
            if final:
                break
            if self._reward_reached([scored[0][0]]):
                self.stopped_reason = "reward"
                logger.info("asha: stop criteria met at rung %d", rung)
                break
            if self._epoch_cap_reached():
                self.stopped_reason = "total_epochs"
                logger.info("asha: stop criteria met at rung %d", rung)
                break
            keep = max(1, math.ceil(len(scored) / rf))
            alive = [i for _, i in scored[:keep]]
        return [r for r in results if r is not None]

    def _epoch_cap_reached(self) -> bool:
        cap = (self.stop or {}).get("total_epochs")
        return cap is not None and self.total_trial_epochs >= cap

    def _reward_reached(self, rewards: List[float]) -> bool:
        target = (self.stop or {}).get("reward")
        if target is None or not rewards:
            return False
        best = max(rewards) if self.mode == "max" else min(rewards)
        return best >= target if self.mode == "max" else best <= target

    def _run_trials(self, configs: List[Dict[str, Any]]
                    ) -> List[TrialOutput]:
        if not configs:
            return []
        if self.executor == "vectorized":
            return self._vec_runner.run_trials(configs)
        if self.executor == "process" and len(configs) > 1:
            return self._run_pool(configs)
        return [_trial_entry(self.trial_fn, c, self.data)
                for c in configs]

    def _run_pool(self, configs: List[Dict[str, Any]]
                  ) -> List[TrialOutput]:
        import multiprocessing as mp
        from concurrent.futures import ProcessPoolExecutor

        ctx = mp.get_context("spawn")
        with ProcessPoolExecutor(max_workers=self.max_workers,
                                 mp_context=ctx,
                                 initializer=_init_cpu_worker,
                                 initargs=(self.data,)) as pool:
            # dataset ships once per worker via the initializer; each
            # submit carries only the config + the sentinel. A config
            # the spawn pickler cannot serialize fails in the queue
            # feeder AFTER submit() returns -- the executor parks the
            # error on that one future (it never reaches _trial_entry's
            # in-worker catch), so both submit() and result() get a
            # per-trial catch: one poisoned config must not sink the
            # wave.
            outs: List[Optional[TrialOutput]] = [None] * len(configs)
            futures = []
            for i, c in enumerate(configs):
                try:
                    futures.append(
                        (i, pool.submit(_trial_entry, self.trial_fn, c,
                                        _FROM_WORKER)))
                except Exception as e:
                    outs[i] = TrialOutput(
                        config=c,
                        error=f"trial submission failed: "
                              f"{type(e).__name__}: {e}")
            for i, f in futures:
                try:
                    outs[i] = f.result()
                except Exception as e:
                    outs[i] = TrialOutput(
                        config=configs[i],
                        error=f"trial did not reach the worker "
                              f"({type(e).__name__}): {e}")
            return outs

    def _log_trials(self) -> None:
        for i, t in enumerate(self.trials):
            if t.error is not None:
                logger.warning("trial %d failed: %s", i,
                               t.error.splitlines()[0])
            else:
                logger.info("trial %d: %s=%.6g", i, self.metric, t.reward)
        if self.logs_dir:
            from analytics_zoo_tpu.utils.summary import SummaryWriter

            writer = SummaryWriter(os.path.join(self.logs_dir, self.name))
            try:
                for i, t in enumerate(self.trials):
                    if t.error is None:
                        writer.add_scalar(f"search/{self.metric}",
                                          t.reward, i)
            finally:
                writer.close()

    def get_best_trials(self, k: int = 1) -> List[TrialOutput]:
        """(ref: RayTuneSearchEngine.get_best_trials)."""
        ok = [t for t in self.trials if t.error is None]
        reverse = self.mode == "max"
        return sorted(ok, key=lambda t: t.reward, reverse=reverse)[:k]
