"""Tunable time-series models for AutoML / Zouwu.

TPU-native re-designs of the reference's searchable model set
(ref: pyzoo/zoo/automl/model/ -- VanillaLSTM.py, Seq2Seq.py,
MTNet_keras.py:614, tcn.py). Each is a plain flax module taking
``x [B, past_seq_len, F]`` and emitting ``[B, future_seq_len * T]``;
``TimeSequenceModel`` wraps one behind the fit_eval/evaluate/predict
contract the search engine drives (ref: model/abstract.py BaseModel),
training through the framework's own SPMD ``Estimator``.

MTNet (re-derived from the paper behind MTNet_keras.py): the history is
split into ``long_num`` memory blocks plus a short query window of
``time_step`` steps; a shared CNN+GRU encoder embeds each block; the
query attends over memory embeddings; [context; query] feeds the head,
with a parallel autoregressive linear term on the raw last steps --
the hot ops (conv, matmul attention, GRU) all map onto the MXU.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.automl import metrics as automl_metrics
from analytics_zoo_tpu.common.log import get_logger

logger = get_logger(__name__)


class VanillaLSTM(nn.Module):
    """(ref: model/VanillaLSTM.py -- two stacked LSTMs + dense head)."""

    lstm_1_units: int = 32
    lstm_2_units: int = 32
    dropout_1: float = 0.2
    dropout_2: float = 0.2
    output_dim: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.RNN(nn.OptimizedLSTMCell(self.lstm_1_units),
                   name="lstm_1")(x)
        h = nn.Dropout(self.dropout_1, deterministic=not train)(h)
        h = nn.RNN(nn.OptimizedLSTMCell(self.lstm_2_units),
                   name="lstm_2")(h)[:, -1]
        h = nn.Dropout(self.dropout_2, deterministic=not train)(h)
        return nn.Dense(self.output_dim, name="head")(h)


class Seq2SeqForecaster(nn.Module):
    """(ref: model/Seq2Seq.py -- LSTM encoder/decoder): the encoder's
    final carry seeds a decoder unrolled ``future_seq_len`` steps; each
    step's input is the previous step's prediction (autoregressive
    decoding without teacher forcing, matching inference-time use)."""

    latent_dim: int = 128
    future_seq_len: int = 1
    target_dim: int = 1
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        carry, _ = nn.RNN(nn.OptimizedLSTMCell(self.latent_dim),
                          return_carry=True, name="encoder")(x)
        cell = nn.OptimizedLSTMCell(self.latent_dim, name="decoder_cell")
        head = nn.Dense(self.target_dim, name="decoder_head")
        drop = nn.Dropout(self.dropout, deterministic=not train)
        # first decoder input: the last observed target values
        step_in = x[:, -1, :self.target_dim]
        outs = []
        for _ in range(self.future_seq_len):  # static unroll: short
            carry, h = cell(carry, step_in)   # horizon, XLA-friendly
            step_in = head(drop(h))
            outs.append(step_in)
        return jnp.stack(outs, axis=1).reshape(
            x.shape[0], self.future_seq_len * self.target_dim)


class _MTNetEncoder(nn.Module):
    """Shared block encoder: causal-free CNN over the window, GRU over
    the conv features, attention-pooled to one embedding."""

    cnn_hidden: int = 32
    rnn_hidden: int = 32
    cnn_height: int = 2
    cnn_dropout: float = 0.2
    rnn_dropout: float = 0.2

    @nn.compact
    def __call__(self, w, train: bool = False):
        # w: [B, time_step, D] -> conv over time with full-width kernel
        h = nn.Conv(self.cnn_hidden, kernel_size=(self.cnn_height,),
                    padding="VALID", name="conv")(w)
        h = nn.relu(h)
        h = nn.Dropout(self.cnn_dropout, deterministic=not train)(h)
        seq = nn.RNN(nn.GRUCell(self.rnn_hidden), name="gru")(h)
        seq = nn.Dropout(self.rnn_dropout, deterministic=not train)(seq)
        # attention pooling over the conv-time axis
        score = nn.Dense(1, name="attn")(nn.tanh(seq))
        alpha = jax.nn.softmax(score, axis=1)
        return jnp.sum(alpha * seq, axis=1)  # [B, rnn_hidden]


class MTNet(nn.Module):
    """Memory time-series network (ref: model/MTNet_keras.py:614).

    Input [B, (long_num + 1) * time_step, D]: the leading
    ``long_num * time_step`` steps form the long-term memory blocks, the
    final ``time_step`` steps the short-term query window.
    """

    time_step: int = 4
    long_num: int = 4
    ar_size: int = 2
    cnn_hidden: int = 32
    rnn_hidden: int = 32
    cnn_height: int = 2
    cnn_dropout: float = 0.2
    rnn_dropout: float = 0.2
    output_dim: int = 1
    # leading input columns holding the raw target series (the AR
    # highway reads these; output_dim = future_seq_len * target_dim)
    target_dim: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        b, total, d = x.shape
        expect = (self.long_num + 1) * self.time_step
        if total != expect:
            raise ValueError(f"MTNet wants seq len {expect}, got {total}")
        mem = x[:, :self.long_num * self.time_step].reshape(
            b * self.long_num, self.time_step, d)
        query = x[:, self.long_num * self.time_step:]

        encoder = _MTNetEncoder(self.cnn_hidden, self.rnn_hidden,
                                self.cnn_height, self.cnn_dropout,
                                self.rnn_dropout, name="encoder")
        m = encoder(mem, train).reshape(b, self.long_num, -1)
        u = encoder(query, train)  # [B, H] -- shared weights

        # attention of query over memory embeddings
        logits = jnp.einsum("blh,bh->bl", m, u) / jnp.sqrt(
            jnp.asarray(m.shape[-1], x.dtype))
        p = jax.nn.softmax(logits, axis=-1)
        context = jnp.einsum("bl,blh->bh", p, m)

        if self.target_dim > d:
            raise ValueError(f"MTNet target_dim={self.target_dim} "
                             f"exceeds input width {d}")
        nonlinear = nn.Dense(self.output_dim, name="head")(
            jnp.concatenate([context, u], axis=-1))
        # autoregressive highway on the raw last ar_size target values
        ar_in = x[:, -self.ar_size:, :self.target_dim].reshape(b, -1)
        linear = nn.Dense(self.output_dim, name="ar")(ar_in)
        return nonlinear + linear


class TCN(nn.Module):
    """Temporal convolutional network (ref: model/tcn.py -- stacked
    residual blocks of dilated causal convolutions)."""

    levels: int = 3
    hidden: int = 30
    kernel_size: int = 3
    dropout: float = 0.1
    output_dim: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = x
        for i in range(self.levels):
            dilation = 2 ** i
            pad = (self.kernel_size - 1) * dilation
            res = h
            for j in range(2):
                # left-pad for causality: output[t] sees input[<=t]
                hp = jnp.pad(h, ((0, 0), (pad, 0), (0, 0)))
                h = nn.Conv(self.hidden, (self.kernel_size,),
                            kernel_dilation=dilation, padding="VALID",
                            name=f"conv_{i}_{j}")(hp)
                h = nn.relu(h)
                h = nn.Dropout(self.dropout,
                               deterministic=not train)(h)
            if res.shape[-1] != self.hidden:
                res = nn.Dense(self.hidden, name=f"res_{i}")(res)
            h = nn.relu(h + res)
        return nn.Dense(self.output_dim, name="head")(h[:, -1])


# ---------------------------------------------------------------------- #
#                          TimeSequenceModel                             #
# ---------------------------------------------------------------------- #

def build_forecast_module(config: Dict[str, Any], future_seq_len: int,
                          n_targets: int) -> nn.Module:
    """Search-space config -> flax module (the 'model' key selects the
    family, mirroring the reference recipes' model field)."""
    out = future_seq_len * n_targets
    kind = str(config.get("model", "LSTM")).upper()
    if kind in ("LSTM", "VANILLALSTM"):
        return VanillaLSTM(
            lstm_1_units=int(config.get("lstm_1_units", 32)),
            lstm_2_units=int(config.get("lstm_2_units", 32)),
            dropout_1=float(config.get("dropout_1", 0.2)),
            dropout_2=float(config.get("dropout_2", 0.2)),
            output_dim=out)
    if kind == "SEQ2SEQ":
        return Seq2SeqForecaster(
            latent_dim=int(config.get("latent_dim", 64)),
            future_seq_len=future_seq_len, target_dim=n_targets,
            dropout=float(config.get("dropout", 0.2)))
    if kind == "MTNET":
        return MTNet(
            time_step=int(config.get("time_step", 4)),
            long_num=int(config.get("long_num", 4)),
            ar_size=int(config.get("ar_size", 2)),
            cnn_hidden=int(config.get("cnn_hidden", 32)),
            rnn_hidden=int(config.get("rnn_hidden", 32)),
            cnn_height=int(config.get("cnn_height", 2)),
            cnn_dropout=float(config.get("cnn_dropout", 0.2)),
            rnn_dropout=float(config.get("rnn_dropout", 0.2)),
            output_dim=out, target_dim=n_targets)
    if kind == "TCN":
        return TCN(levels=int(config.get("levels", 3)),
                   hidden=int(config.get("hidden", 30)),
                   kernel_size=int(config.get("kernel_size", 3)),
                   dropout=float(config.get("dropout", 0.1)),
                   output_dim=out)
    raise ValueError(f"unknown model kind {kind!r}")


class TimeSequenceModel:
    """fit_eval/evaluate/predict wrapper around one forecast module
    (ref: model/time_sequence.py TimeSequenceModel, model/abstract.py)."""

    def __init__(self, future_seq_len: int = 1, n_targets: int = 1):
        self.future_seq_len = future_seq_len
        self.n_targets = n_targets
        self.config: Dict[str, Any] = {}
        self.estimator = None
        self._xgb = None  # gradient-boosted-trees delegate (model: XGBoost)

    @staticmethod
    def _is_xgb(config: Dict[str, Any]) -> bool:
        return str(config.get("model", "")).upper() == "XGBOOST"

    # keys that tune the training loop, not the architecture: changing
    # them must NOT discard the trained estimator (fit_eval is called
    # repeatedly to continue training)
    _LOOP_KEYS = ("epochs", "batch_size", "metric")

    def _arch_of(self, config: Dict[str, Any]) -> Dict[str, Any]:
        return {k: v for k, v in config.items()
                if k not in self._LOOP_KEYS}

    def _ensure_estimator(self, config: Dict[str, Any]):
        from analytics_zoo_tpu.learn.estimator import Estimator
        from analytics_zoo_tpu.learn.optim import Adam

        if (self.estimator is None or
                self._arch_of(config) != self._arch_of(self.config)):
            self.config = dict(config)
            module = build_forecast_module(config, self.future_seq_len,
                                           self.n_targets)
            self.estimator = Estimator(
                module, loss="mse",
                optimizer=Adam(float(config.get("lr", 1e-3))))
        else:
            self.config = dict(config)  # refresh loop keys only
        return self.estimator

    def fit_eval(self, x: np.ndarray, y: np.ndarray,
                 validation_data: Optional[Tuple] = None,
                 unscale_fn=None, verbose: int = 0, **config) -> float:
        """Train ``config['epochs']`` epochs, return the reward metric on
        the validation set (train set when absent). Called repeatedly by
        the scheduler: the estimator persists, so successive calls
        continue training (ref: abstract.py fit_eval contract).

        ``unscale_fn`` maps [B, future*T] scaled targets back to data
        units before scoring -- ratio metrics (mape/smape) are
        meaningless on standardized values, and search rewards must be
        comparable with pipeline.evaluate's unscaled numbers.
        """
        if self._is_xgb(config):
            return self._fit_eval_xgb(x, y, validation_data, unscale_fn,
                                      config)
        self._xgb = None  # config switched family: drop a stale delegate
        est = self._ensure_estimator(config)
        y2 = y.reshape(len(y), -1)
        batch_size = int(config.get("batch_size", 32))
        batch_size = max(1, min(batch_size, len(x)))
        est.fit((x, y2), batch_size=batch_size,
                epochs=est.epoch + int(config.get("epochs", 1)))
        return self._score(x, y2, validation_data, unscale_fn, config)

    def _score(self, x, y2, validation_data, unscale_fn, config) -> float:
        """Reward on validation (train when absent), in DATA units when
        an unscale_fn is given -- shared by the neural and XGBoost
        fit_eval paths so search rewards stay comparable."""
        vx, vy = (x, y2) if validation_data is None else (
            validation_data[0],
            np.asarray(validation_data[1]).reshape(
                len(validation_data[1]), -1))
        metric = str(config.get("metric", "mse"))
        pred = self.predict(vx)
        if unscale_fn is not None:
            vy, pred = unscale_fn(vy), unscale_fn(pred)
        return automl_metrics.evaluate(metric, vy, pred)

    def _fit_eval_xgb(self, x, y, validation_data, unscale_fn,
                      config) -> float:
        """XGBoost in the same TimeSequenceModel slot (ref: the
        reference searches XGBoost through the identical fit_eval
        contract, automl/model/XGBoost.py); trees retrain from scratch
        each call (boosting has no warm continuation here)."""
        from analytics_zoo_tpu.automl.xgboost import XGBoost as XGBModel

        self.config = dict(config)
        self._xgb = XGBModel("regressor", config=config)
        y2 = np.asarray(y).reshape(len(y), -1)
        self._xgb.fit(np.asarray(x).reshape(len(x), -1), y2)
        return self._score(x, y2, validation_data, unscale_fn, config)

    def predict(self, x: np.ndarray, batch_size: int = 128) -> np.ndarray:
        if self._xgb is not None:
            return self._xgb.predict(
                np.asarray(x).reshape(len(x), -1))
        if self.estimator is None:
            raise RuntimeError("model not fitted")
        return np.asarray(self.estimator.predict(x, batch_size=batch_size))

    def predict_with_uncertainty(self, x: np.ndarray, n_iter: int = 10):
        """Monte-Carlo dropout: n_iter stochastic forwards -> (mean, std)
        (ref: model mc=True predict_with_uncertainty)."""
        est = self.estimator
        if est is None:
            raise RuntimeError("model not fitted")
        adapter = est.adapter

        @jax.jit
        def mc_forward(variables, xb, rng):
            preds, _ = adapter.apply(variables, xb, training=True, rng=rng)
            return preds

        rng = jax.random.PRNGKey(0)
        outs = []
        for i in range(n_iter):
            rng, sub = jax.random.split(rng)
            outs.append(np.asarray(
                mc_forward(est.variables, jnp.asarray(x), sub)))
        stack = np.stack(outs)
        return stack.mean(axis=0), stack.std(axis=0)

    def evaluate(self, x, y, metrics=("mse",)) -> Dict[str, float]:
        pred = self.predict(x)
        y2 = np.asarray(y).reshape(len(y), -1)
        return automl_metrics.evaluate_all(metrics, y2, pred)

    # ----------------------------------------------------- persistence --
    def save(self, dir_path: str) -> None:
        from analytics_zoo_tpu.automl.feature import _jsonable

        os.makedirs(dir_path, exist_ok=True)
        meta = {"future_seq_len": self.future_seq_len,
                "n_targets": self.n_targets,
                "config": _jsonable(self.config)}
        with open(os.path.join(dir_path, "ts_model.json"), "w") as f:
            json.dump(meta, f)
        if self._xgb is not None:
            self._xgb.save(os.path.join(dir_path, "xgb"))
        elif self.estimator is not None:
            self.estimator.save(os.path.join(dir_path, "ckpt"))

    @classmethod
    def restore(cls, dir_path: str) -> "TimeSequenceModel":
        with open(os.path.join(dir_path, "ts_model.json")) as f:
            meta = json.load(f)
        model = cls(future_seq_len=meta["future_seq_len"],
                    n_targets=meta["n_targets"])
        if cls._is_xgb(meta["config"]):
            from analytics_zoo_tpu.automl.xgboost import (
                XGBoost as XGBModel)

            model.config = dict(meta["config"])
            model._xgb = XGBModel.restore(os.path.join(dir_path, "xgb"))
            return model
        model._ensure_estimator(meta["config"])
        ckpt = os.path.join(dir_path, "ckpt")
        if os.path.isdir(ckpt):
            model.estimator.load(ckpt)
        return model

    # ------------------------------------------------- state (in-memory) --
    def state_bytes(self) -> bytes:
        """Serialized weights for cross-process trial results."""
        import io

        from flax.serialization import to_bytes

        if self._xgb is not None:
            import pickle

            return pickle.dumps(self._xgb)
        buf = io.BytesIO()
        est = self.estimator
        variables = jax.device_get(est.variables)
        buf.write(to_bytes(variables))
        return buf.getvalue()

    def load_state_bytes(self, blob: bytes, config: Dict[str, Any],
                         example_x: np.ndarray) -> None:
        from flax.serialization import from_bytes

        if self._is_xgb(config):
            import pickle

            self.config = dict(config)
            self._xgb = pickle.loads(blob)
            return
        self._xgb = None
        est = self._ensure_estimator(config)
        est._ensure_built(example_x)
        est.variables = from_bytes(jax.device_get(est.variables), blob)
        est._place_state()
