"""Time-series feature engineering for AutoML.

The analog of ``TimeSequenceFeatureTransformer`` (ref: pyzoo/zoo/automl/
feature/time_sequence.py:35-583 -- datetime feature generation via
featuretools, standard scaling, rolling past/future windows) rebuilt on
plain pandas/numpy: the generated calendar features are closed-form, so
no feature-synthesis library is needed, and the rolled windows come out
as dense [N, past_seq_len, F] float32 blocks ready for device upload.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

# calendar features derivable from the datetime column; the awake/busy
# bands mirror the reference's is_awake/is_busy_hours definitions
# (ref: feature/time_sequence.py:545-556)
_DT_FEATURES = ("month", "day", "hour", "minute", "weekday",
                "is_weekend", "is_awake", "is_busy_hours")


def _datetime_features(dt: pd.Series) -> pd.DataFrame:
    hour = dt.dt.hour
    weekday = dt.dt.weekday
    return pd.DataFrame({
        "month": dt.dt.month,
        "day": dt.dt.day,
        "hour": hour,
        "minute": dt.dt.minute,
        "weekday": weekday,
        "is_weekend": (weekday >= 5).astype(int),
        "is_awake": (((hour >= 6) & (hour <= 23)) | (hour == 0))
        .astype(int),
        "is_busy_hours": (((hour >= 7) & (hour <= 9)) |
                          ((hour >= 16) & (hour <= 19))).astype(int),
    }, index=dt.index)


def _as_list(v) -> List[str]:
    if v is None:
        return []
    if isinstance(v, str):
        return list(json.loads(v))
    return list(v)


class TimeSequenceFeatureTransformer:
    """df[(dt_col, target_col, extra...)] -> rolled (x, y) windows.

    Config keys consumed from the search space:
      ``selected_features``: subset of :meth:`get_feature_list` (JSON
      string or list); ``past_seq_len``: history window length.
    """

    def __init__(self, future_seq_len: int = 1, dt_col: str = "datetime",
                 target_col="value", extra_features_col=None,
                 drop_missing: bool = True):
        self.future_seq_len = future_seq_len
        self.dt_col = dt_col
        self.target_col = ([target_col] if isinstance(target_col, str)
                           else list(target_col))
        self.extra_features_col = _as_list(extra_features_col)
        self.drop_missing = drop_missing
        self.config: Dict[str, Any] = {}
        self.scale_mean: Optional[np.ndarray] = None
        self.scale_std: Optional[np.ndarray] = None

    # -------------------------------------------------------- features --
    def get_feature_list(self, input_df: pd.DataFrame = None) -> List[str]:
        return list(_DT_FEATURES) + list(self.extra_features_col)

    def _check_input(self, df: pd.DataFrame, mode: str) -> pd.DataFrame:
        need = [self.dt_col] + self.target_col + self.extra_features_col
        missing = set(need) - set(df.columns)
        if missing:
            raise ValueError(f"missing columns: {sorted(missing)}")
        df = df.copy()
        df[self.dt_col] = pd.to_datetime(df[self.dt_col])
        if df[self.dt_col].isna().any():
            raise ValueError("datetime column has missing values")
        value_cols = self.target_col + self.extra_features_col
        if df[value_cols].isna().any().any():
            if self.drop_missing:
                df = df.dropna(subset=value_cols)
            else:
                # last-observation fill, then backfill for a leading NaN
                # (ref: impute/impute.py LastFillImpute)
                df[value_cols] = df[value_cols].ffill().bfill()
        if len(df) == 0:
            raise ValueError("empty dataframe after dropping missing")
        return df.reset_index(drop=True)

    def _feature_matrix(self, df: pd.DataFrame,
                        selected: Sequence[str]) -> np.ndarray:
        """[N, n_targets + n_selected] in float32; targets lead."""
        dt_feats = _datetime_features(df[self.dt_col])
        cols = [df[c].to_numpy(np.float32) for c in self.target_col]
        for name in selected:
            if name in dt_feats.columns:
                cols.append(dt_feats[name].to_numpy(np.float32))
            elif name in df.columns:
                cols.append(df[name].to_numpy(np.float32))
            else:
                raise ValueError(f"unknown feature {name!r}")
        return np.stack(cols, axis=1)

    # --------------------------------------------------------- scaling --
    def _fit_scaler(self, mat: np.ndarray) -> None:
        self.scale_mean = mat.mean(axis=0)
        std = mat.std(axis=0)
        self.scale_std = np.where(std < 1e-8, 1.0, std)

    def _scale(self, mat: np.ndarray) -> np.ndarray:
        return (mat - self.scale_mean) / self.scale_std

    def _unscale_y(self, y: np.ndarray) -> np.ndarray:
        """y [..., n_targets]: invert scaling with the target stats."""
        t = len(self.target_col)
        return y * self.scale_std[:t] + self.scale_mean[:t]

    def unscale_uncertainty(self, y_std: np.ndarray) -> np.ndarray:
        t = len(self.target_col)
        return y_std * self.scale_std[:t]

    # --------------------------------------------------------- rolling --
    def _roll(self, mat: np.ndarray, past: int, future: int):
        """[N, F] -> x [M, past, F], y [M, future, T] (targets lead)."""
        t = len(self.target_col)
        n = len(mat) - past - future + 1
        if n <= 0:
            raise ValueError(
                f"series of {len(mat)} rows too short for past_seq_len="
                f"{past} + future_seq_len={future}")
        x = np.stack([mat[i:i + past] for i in range(n)])
        y = np.stack([mat[i + past:i + past + future, :t]
                      for i in range(n)])
        return x.astype(np.float32), y.astype(np.float32)

    def _roll_test(self, mat: np.ndarray, past: int) -> np.ndarray:
        n = len(mat) - past + 1
        if n <= 0:
            raise ValueError("series too short for past_seq_len")
        return np.stack([mat[i:i + past] for i in range(n)]
                        ).astype(np.float32)

    # ------------------------------------------------------- transform --
    def fit_transform(self, input_df: pd.DataFrame, **config):
        """Fit scaler + remember config, return rolled (x, y)
        (ref: time_sequence.py fit_transform)."""
        self.config = dict(config)
        selected = _as_list(config.get("selected_features", []))
        past = int(config.get("past_seq_len", 2))
        df = self._check_input(input_df, "train")
        mat = self._feature_matrix(df, selected)
        self._fit_scaler(mat)
        return self._roll(self._scale(mat), past, self.future_seq_len)

    def transform(self, input_df: pd.DataFrame, is_train: bool = False):
        """Transform with the fitted scaler/config. Train mode returns
        (x, y); test mode returns x covering every full history window."""
        if self.scale_mean is None:
            raise RuntimeError("call fit_transform first")
        selected = _as_list(self.config.get("selected_features", []))
        past = int(self.config.get("past_seq_len", 2))
        df = self._check_input(input_df, "train" if is_train else "test")
        mat = self._scale(self._feature_matrix(df, selected))
        if is_train:
            return self._roll(mat, past, self.future_seq_len)
        return self._roll_test(mat, past)

    def post_processing(self, input_df: pd.DataFrame, y_pred: np.ndarray,
                        is_train: bool):
        """Invert scaling. Train mode: (y_pred_unscaled, y_true_unscaled)
        for metric computation; test mode: a dataframe mapping each
        prediction window to the datetime it forecasts
        (ref: time_sequence.py post_processing)."""
        t = len(self.target_col)
        y_pred = y_pred.reshape(len(y_pred), self.future_seq_len, t)
        y_unscaled = self._unscale_y(y_pred)
        if is_train:
            df = self._check_input(input_df, "train")
            selected = _as_list(self.config.get("selected_features", []))
            past = int(self.config.get("past_seq_len", 2))
            mat = self._feature_matrix(df, selected)
            _, y_true = self._roll(mat, past, self.future_seq_len)
            return y_unscaled, y_true
        df = self._check_input(input_df, "test")
        past = int(self.config.get("past_seq_len", 2))
        dt = pd.to_datetime(df[self.dt_col])
        freq = dt.iloc[-1] - dt.iloc[-2] if len(dt) > 1 else pd.Timedelta(0)
        first_pred_dt = dt.iloc[past - 1:].reset_index(drop=True) + freq
        out = {self.dt_col: first_pred_dt}
        for j, col in enumerate(self.target_col):
            for h in range(self.future_seq_len):
                name = col if self.future_seq_len == 1 else f"{col}_{h}"
                out[name] = y_unscaled[:, h, j]
        return pd.DataFrame(out)

    # ----------------------------------------------------- persistence --
    def save(self, dir_path: str) -> None:
        os.makedirs(dir_path, exist_ok=True)
        meta = {
            "future_seq_len": self.future_seq_len,
            "dt_col": self.dt_col,
            "target_col": self.target_col,
            "extra_features_col": self.extra_features_col,
            "drop_missing": self.drop_missing,
            "config": _jsonable(self.config),
        }
        with open(os.path.join(dir_path, "feature_transformer.json"),
                  "w") as f:
            json.dump(meta, f)
        np.savez(os.path.join(dir_path, "feature_scaler.npz"),
                 mean=self.scale_mean, std=self.scale_std)

    @classmethod
    def restore(cls, dir_path: str) -> "TimeSequenceFeatureTransformer":
        with open(os.path.join(dir_path, "feature_transformer.json")) as f:
            meta = json.load(f)
        ft = cls(future_seq_len=meta["future_seq_len"],
                 dt_col=meta["dt_col"], target_col=meta["target_col"],
                 extra_features_col=meta["extra_features_col"],
                 drop_missing=meta["drop_missing"])
        ft.config = meta["config"]
        with np.load(os.path.join(dir_path, "feature_scaler.npz")) as z:
            ft.scale_mean, ft.scale_std = z["mean"], z["std"]
        return ft


def _jsonable(config: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in config.items():
        if isinstance(v, (np.integer,)):
            v = int(v)
        elif isinstance(v, (np.floating,)):
            v = float(v)
        elif isinstance(v, np.ndarray):
            v = v.tolist()
        out[k] = v
    return out
