"""Vectorized AutoML executor: trial cohorts as ONE compiled population.

The ``executor="vectorized"`` backend for :class:`SearchEngine`. Where
the process executor ships every sampled config to its own CPU worker
(the reference's one-trial-per-Ray-worker shape), this backend
partitions configs into *shape-compatible cohorts* -- same architecture
hyperparameters, same rolled feature shapes, same effective batch size
-- and trains each cohort as a single
:class:`~analytics_zoo_tpu.learn.population.PopulationEstimator`: one
jitted vmapped step, per-lane learning rates, per-lane epoch budgets.

ASHA integration is *masking in place*: rungs re-enter ``run_trials``
with the surviving configs at a larger epoch budget, and the runner
CONTINUES the cohort's population from its previous rung state with the
culled lanes frozen (zero effective lr, held optimizer state). Because
the per-lane trajectory is deterministic (same PRNG stream, same
epoch-seeded shuffle), continuing rung r's state to rung r+1's budget
produces exactly the model a from-scratch run at the larger budget
would -- the sequential scheduler's re-train-from-scratch semantics,
without the recompute, and with NO shape change across rungs (zero
recompiles -- the acceptance gate the recompile-storm detector checks).

Configs the cohort model cannot absorb -- XGBoost-family trials, or an
unknown model key -- fall back to the in-process sequential path per
config (``zoo.automl.vectorized.fallback``), so mixed search spaces
still complete.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.obs.events import emit
from analytics_zoo_tpu.obs.metrics import get_registry

logger = get_logger(__name__)

_M_COHORTS = get_registry().counter(
    "zoo_automl_cohorts_total",
    "Vectorized trial cohorts trained (one compiled population each)")
_M_VEC_TRIALS = get_registry().counter(
    "zoo_automl_vectorized_trials_total",
    "Trials answered by the vectorized executor, by path",
    labelnames=("path",))

# config keys that select the training loop / data rolling, not the
# stacked parameter tree: cohort membership must ignore them ("lr" is
# a traced per-lane scalar; "selected_features" changes which columns
# roll into x, which the data-shape part of the key already captures)
_NON_ARCH_KEYS = ("lr", "epochs", "batch_size", "metric",
                  "selected_features")

# model families build_forecast_module can turn into one flax module
_NEURAL_FAMILIES = ("LSTM", "VANILLALSTM", "SEQ2SEQ", "MTNET", "TCN")


def _identity(config: Dict[str, Any]) -> Tuple:
    """Stable identity of a trial config MINUS its epoch budget --
    the key that maps an ASHA rung's config back to the lane its
    earlier rung trained (rungs differ only in ``epochs``)."""
    return tuple(sorted((k, repr(v)) for k, v in config.items()
                        if k != "epochs"))


def _arch_key(config: Dict[str, Any]) -> Tuple:
    return tuple(sorted((k, repr(v)) for k, v in config.items()
                        if k not in _NON_ARCH_KEYS))


class _Cohort:
    """One live population + the per-lane data/scoring context."""

    def __init__(self, name: str):
        self.name = name
        self.pop = None                  # PopulationEstimator
        self.lanes: List[Tuple] = []     # lane -> config identity
        self.ran: List[int] = []         # lane -> epochs trained so far
        self.preps: List[Dict] = []      # lane -> prepared trial data
        self.x = None                    # stacked [N, B, T, F]
        self.y = None                    # stacked [N, B, out]
        self.batch_size = 0


class TimeSeriesCohortRunner:
    """Cohort execution for ``time_sequence_trial`` search spaces.

    Each prepared config carries its OWN feature transform (the
    sequential trial refits ``TimeSequenceFeatureTransformer`` per
    config -- ``selected_features``/``past_seq_len`` change the rolled
    arrays), so a cohort stacks per-member data lanes ``[N, B, T, F]``
    alongside the stacked parameters: members may read different
    columns as long as the shapes agree.
    """

    def __init__(self, data: Dict[str, Any], trial_fn=None):
        self.data = data
        self.trial_fn = trial_fn
        self._cohorts: Dict[Tuple, List[_Cohort]] = {}
        self._n_created = 0

    def reset(self) -> None:
        """Drop cached populations (a re-run() must start fresh)."""
        self._cohorts.clear()

    # ------------------------------------------------------- trial prep --
    @staticmethod
    def _vectorizable(config: Dict[str, Any]) -> bool:
        kind = str(config.get("model", "LSTM")).upper()
        return kind in _NEURAL_FAMILIES

    def _prepare(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """Per-config feature fit -- the exact data the sequential
        ``time_sequence_trial`` trains on (parity depends on it)."""
        from analytics_zoo_tpu.automl.feature import (
            TimeSequenceFeatureTransformer)
        from analytics_zoo_tpu.automl.predictor import _unscaler

        spec = self.data["spec"]
        ft = TimeSequenceFeatureTransformer(**spec)
        x, y = ft.fit_transform(self.data["train_df"], **config)
        y2 = np.asarray(y).reshape(len(y), -1)
        if self.data.get("validation_df") is not None:
            vx, vy = ft.transform(self.data["validation_df"],
                                  is_train=True)
            vy2 = np.asarray(vy).reshape(len(vy), -1)
        else:
            vx, vy2 = x, y2
        batch_size = max(1, min(int(config.get("batch_size", 32)),
                                len(x)))
        cohort_key = (_arch_key(config), x.shape, y2.shape, vx.shape,
                      batch_size)
        return {"config": dict(config), "ft": ft, "x": x, "y2": y2,
                "vx": vx, "vy2": vy2, "unscale": _unscaler(ft),
                "batch_size": batch_size, "cohort_key": cohort_key,
                "n_targets": len(ft.target_col),
                "future_seq_len": spec["future_seq_len"]}

    # ---------------------------------------------------------- cohorts --
    def _new_cohort(self, entries: List[Tuple[int, Dict]]) -> _Cohort:
        from analytics_zoo_tpu.automl.models import build_forecast_module
        from analytics_zoo_tpu.learn.population import PopulationEstimator

        self._n_created += 1
        cohort = _Cohort(f"cohort-{self._n_created}")
        preps = [p for _, p in entries]
        first = preps[0]
        module = build_forecast_module(first["config"],
                                       first["future_seq_len"],
                                       first["n_targets"])
        lrs = [float(p["config"].get("lr", 1e-3)) for p in preps]
        cohort.pop = PopulationEstimator(module, n_members=len(preps),
                                         loss="mse", lr=lrs)
        cohort.lanes = [_identity(p["config"]) for p in preps]
        cohort.ran = [0] * len(preps)
        cohort.preps = preps
        cohort.x = np.stack([p["x"] for p in preps])
        cohort.y = np.stack([p["y2"] for p in preps])
        cohort.batch_size = first["batch_size"]
        return cohort

    def _assign(self, group: List[Tuple[int, Dict]]
                ) -> List[Tuple[_Cohort, List[Tuple[int, Dict, int]]]]:
        """Map prepared configs onto existing cohort lanes (ASHA
        continuation) and gather the rest into new cohorts, capped at
        ``zoo.automl.vectorized.max_cohort`` lanes each."""
        key = group[0][1]["cohort_key"]
        cohorts = self._cohorts.setdefault(key, [])
        plan: Dict[int, List[Tuple[int, Dict, int]]] = {}
        leftover: List[Tuple[int, Dict]] = []
        used: Dict[int, set] = {id(c): set() for c in cohorts}
        for pos, prep in group:
            ident = _identity(prep["config"])
            target = int(prep["config"].get("epochs", 1))
            placed = False
            for ci, cohort in enumerate(cohorts):
                taken = used[id(cohort)]
                for lane, lid in enumerate(cohort.lanes):
                    # a lane continues only FORWARD (target epochs past
                    # what it already trained); an equal target re-scores
                    # the held state, which is what a from-scratch re-run
                    # at the same budget would produce anyway
                    if (lane not in taken and lid == ident
                            and target >= cohort.ran[lane]):
                        taken.add(lane)
                        plan.setdefault(ci, []).append(
                            (pos, prep, lane))
                        placed = True
                        break
                if placed:
                    break
            if not placed:
                leftover.append((pos, prep))
        out = [(cohorts[ci], entries) for ci, entries in plan.items()]
        if leftover:
            cap = int(get_config().get(
                "zoo.automl.vectorized.max_cohort", 64))
            for s in range(0, len(leftover), cap):
                chunk = leftover[s:s + cap]
                cohort = self._new_cohort(chunk)
                cohorts.append(cohort)
                out.append((cohort,
                            [(pos, prep, lane) for lane, (pos, prep)
                             in enumerate(chunk)]))
        return out

    def _run_cohort(self, cohort: _Cohort,
                    entries: List[Tuple[int, Dict, int]],
                    outputs: List) -> None:
        from analytics_zoo_tpu.automl import metrics as automl_metrics
        from analytics_zoo_tpu.automl.search import TrialOutput

        n = cohort.pop.n_members
        budgets = list(cohort.ran)
        for _, prep, lane in entries:
            budgets[lane] = int(prep["config"].get("epochs", 1))
            # continuation reuses the cohort's stored data lanes: the
            # feature transform is deterministic per config, so the
            # freshly prepared arrays equal the stored ones
            cohort.preps[lane] = prep
        top = max(budgets)
        continued = cohort.pop.epoch > 0
        if top > cohort.pop.epoch:
            cohort.pop.fit(cohort.x, cohort.y, cohort.batch_size,
                           epochs=top, budgets=budgets)
        _M_COHORTS.inc()
        emit("population_cohort", "automl", name=cohort.name,
             members=n, active=len(entries), epochs=top,
             continued=continued)
        vx = np.stack([p["vx"] for p in cohort.preps])
        preds = cohort.pop.predict(vx)
        for pos, prep, lane in entries:
            cfg = prep["config"]
            metric = str(cfg.get("metric", "mse"))
            vy, pred = prep["vy2"], preds[lane]
            unscale = prep["unscale"]
            vy, pred = unscale(vy), unscale(pred)
            reward = automl_metrics.evaluate(metric, vy, pred)
            cohort.ran[lane] = budgets[lane]
            _M_VEC_TRIALS.labels(path="cohort").inc()
            outputs[pos] = TrialOutput(
                config=cfg, reward=float(reward),
                state=cohort.pop.export_member_bytes(lane),
                extras={"example_x": prep["x"][:1],
                        "cohort": cohort.name, "lane": lane})

    # --------------------------------------------------------------- run --
    def run_trials(self, configs: List[Dict[str, Any]]) -> List:
        from analytics_zoo_tpu.automl.search import (
            TrialOutput, _trial_entry)

        outputs: List[Optional[TrialOutput]] = [None] * len(configs)
        fallback_ok = bool(get_config().get(
            "zoo.automl.vectorized.fallback", True))
        groups: Dict[Tuple, List[Tuple[int, Dict]]] = {}
        for pos, cfg in enumerate(configs):
            if not self._vectorizable(cfg):
                _M_VEC_TRIALS.labels(path="fallback").inc()
                outputs[pos] = _trial_entry(self.trial_fn, cfg,
                                            self.data)
                continue
            try:
                prep = self._prepare(cfg)
            except Exception as e:
                import traceback

                outputs[pos] = TrialOutput(
                    config=cfg,
                    error=f"{e}\n{traceback.format_exc()}")
                continue
            groups.setdefault(prep["cohort_key"], []).append(
                (pos, prep))
        for key, group in groups.items():
            try:
                for cohort, entries in self._assign(group):
                    self._run_cohort(cohort, entries, outputs)
            except Exception as e:
                # a cohort failure must not sink the search: answer its
                # trials through the sequential path (or as errors)
                logger.exception("vectorized cohort failed: %s", e)
                for pos, prep in group:
                    if outputs[pos] is not None:
                        continue
                    if fallback_ok:
                        _M_VEC_TRIALS.labels(path="fallback").inc()
                        outputs[pos] = _trial_entry(
                            self.trial_fn, prep["config"], self.data)
                    else:
                        import traceback

                        outputs[pos] = TrialOutput(
                            config=prep["config"],
                            error=f"{e}\n{traceback.format_exc()}")
        return outputs


def make_runner(trial_fn, data) -> Optional[TimeSeriesCohortRunner]:
    """Resolve the cohort runner for a trial function. A custom
    ``trial_fn`` opts in by exposing ``trial_fn.cohort_runner(data,
    trial_fn)``; the built-in ``time_sequence_trial`` maps to
    :class:`TimeSeriesCohortRunner`. Returns None when the trial
    function has no vectorized form."""
    factory = getattr(trial_fn, "cohort_runner", None)
    if factory is not None:
        return factory(data, trial_fn)
    from analytics_zoo_tpu.automl.predictor import time_sequence_trial

    if trial_fn is time_sequence_trial:
        return TimeSeriesCohortRunner(data, trial_fn)
    return None
