"""Search-space primitives for hyperparameter search.

The dependency-free analog of the ``ray.tune`` sampling API the reference
recipes are written against (ref: pyzoo/zoo/automl/config/recipe.py --
tune.choice / tune.uniform / tune.grid_search / tune.sample_from).
A space is a plain dict whose values are either literals or the sampler
objects below; ``expand_and_sample`` turns it into concrete trial
configs: grid axes expand cartesian-product style, random axes draw
``num_samples`` times per grid point, and ``SampleFrom`` values resolve
last against the partially-built config (dependent parameters, e.g.
MTNet's past_seq_len = (long_num + 1) * time_step).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List, Sequence

import numpy as np


class Sampler:
    """Base: draws one value from the distribution."""

    def sample(self, rng: np.random.RandomState) -> Any:
        raise NotImplementedError


class Choice(Sampler):
    def __init__(self, options: Sequence[Any]):
        self.options = list(options)

    def sample(self, rng):
        return self.options[rng.randint(len(self.options))]


class Uniform(Sampler):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return float(rng.uniform(self.low, self.high))


class QUniform(Sampler):
    """Uniform quantized to multiples of ``q``."""

    def __init__(self, low: float, high: float, q: float = 1.0):
        self.low, self.high, self.q = low, high, q

    def sample(self, rng):
        v = np.round(rng.uniform(self.low, self.high) / self.q) * self.q
        v = float(np.clip(v, self.low, self.high))
        return int(v) if float(self.q).is_integer() else v


class LogUniform(Sampler):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return float(np.exp(rng.uniform(np.log(self.low),
                                        np.log(self.high))))


class RandInt(Sampler):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return int(rng.randint(self.low, self.high))


class FeatureSubset(Sampler):
    """A random subset of the available feature names (the reference's
    GridRandomRecipe draws random feature combinations)."""

    def __init__(self, features: Sequence[str], min_size: int = 0,
                 max_size: int = None):
        self.features = list(features)
        self.min_size = min_size
        self.max_size = (len(self.features) if max_size is None
                         else max_size)

    def sample(self, rng):
        hi = min(self.max_size, len(self.features))
        k = rng.randint(self.min_size, hi + 1)
        idx = rng.choice(len(self.features), size=k, replace=False)
        return [self.features[i] for i in sorted(idx)]


class Grid:
    """Exhaustive axis (ref: tune.grid_search)."""

    def __init__(self, options: Sequence[Any]):
        self.options = list(options)


class SampleFrom:
    """Computed parameter: ``fn(config) -> value`` resolved after every
    sampled/grid parameter is in place (ref: tune.sample_from)."""

    def __init__(self, fn: Callable[[Dict[str, Any]], Any]):
        self.fn = fn


def expand_and_sample(space: Dict[str, Any], num_samples: int = 1,
                      seed: int = 0) -> List[Dict[str, Any]]:
    """Space dict -> list of concrete configs.

    total trials = (product of Grid axis sizes) * num_samples.
    """
    rng = np.random.RandomState(seed)
    grid_keys = [k for k, v in space.items() if isinstance(v, Grid)]
    grid_values = [space[k].options for k in grid_keys]
    configs: List[Dict[str, Any]] = []
    for point in itertools.product(*grid_values) if grid_keys else [()]:
        for _ in range(num_samples):
            config: Dict[str, Any] = dict(zip(grid_keys, point))
            deferred = {}
            for k, v in space.items():
                if isinstance(v, Grid):
                    continue
                if isinstance(v, SampleFrom):
                    deferred[k] = v
                elif isinstance(v, Sampler):
                    config[k] = v.sample(rng)
                else:
                    config[k] = v
            for k, v in deferred.items():
                config[k] = v.fn(config)
            configs.append(config)
    return configs
