"""autograd: symbolic math on KTensors + custom losses.

The analog of the reference's autograd package
(ref: zoo/src/main/scala/com/intel/analytics/zoo/pipeline/api/autograd/
math.scala, Lambda.scala, CustomLoss.scala; python surface
pyzoo/zoo/pipeline/api/autograd.py). Where the reference builds BigDL
graphs from ``Variable`` nodes, here every op is dual-mode: applied to
a symbolic ``KTensor`` it records a ``Lambda`` graph node; applied to a
concrete array it runs eagerly as jnp -- the same function object works
in model definitions and in custom losses (jax IS the autograd, so
``CustomLoss`` is just a named wrapper the Estimator accepts).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

_uid = itertools.count()


def _is_symbolic(*xs) -> bool:
    from analytics_zoo_tpu.keras.engine import KTensor

    return any(isinstance(x, KTensor) for x in xs)


def _apply(name: str, fn: Callable, *xs):
    """Dual-mode dispatch: Lambda node on KTensors, jnp eagerly else."""
    if not _is_symbolic(*xs):
        return fn(*xs)
    from analytics_zoo_tpu.keras.engine import KTensor
    from analytics_zoo_tpu.keras.layers.core import Lambda

    tensors = [x for x in xs if isinstance(x, KTensor)]
    consts = [None if isinstance(x, KTensor) else x for x in xs]

    def wrapped(inputs):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        it = iter(inputs)
        args = [next(it) if c is None else c for c in consts]
        return fn(*args)

    lam = Lambda(wrapped, name=f"autograd_{name}_{next(_uid)}")
    return lam(tensors if len(tensors) > 1 else tensors[0])


# ---------------------------------------------------------- elementwise --
def exp(x):
    return _apply("exp", jnp.exp, x)


def log(x):
    return _apply("log", jnp.log, x)


def sqrt(x):
    return _apply("sqrt", jnp.sqrt, x)


def square(x):
    return _apply("square", jnp.square, x)


def abs(x):  # noqa: A001 (reference API name)
    return _apply("abs", jnp.abs, x)


def neg(x):
    return _apply("neg", jnp.negative, x)


def pow(x, a: float):  # noqa: A001
    return _apply("pow", lambda t: jnp.power(t, a), x)


def clip(x, min_v: float, max_v: float):
    return _apply("clip", lambda t: jnp.clip(t, min_v, max_v), x)


def softsign(x):
    return _apply("softsign", jax.nn.soft_sign, x)


def softplus(x):
    return _apply("softplus", jax.nn.softplus, x)


def erf(x):
    return _apply("erf", jax.scipy.special.erf, x)


# ----------------------------------------------------------- reductions --
def sum(x, axis: int = 0, keep_dims: bool = False):  # noqa: A001
    """Reduction over a non-batch axis; ``axis`` is 0-based EXCLUDING
    batch (reference convention, autograd.py sum)."""
    return _apply("sum", lambda t: jnp.sum(t, axis=axis + 1,
                                           keepdims=keep_dims), x)


def mean(x, axis: int = 0, keep_dims: bool = False):
    return _apply("mean", lambda t: jnp.mean(t, axis=axis + 1,
                                             keepdims=keep_dims), x)


def max(x, axis: int = 0, keep_dims: bool = False):  # noqa: A001
    return _apply("max", lambda t: jnp.max(t, axis=axis + 1,
                                           keepdims=keep_dims), x)


def min(x, axis: int = 0, keep_dims: bool = False):  # noqa: A001
    return _apply("min", lambda t: jnp.min(t, axis=axis + 1,
                                           keepdims=keep_dims), x)


def l2_normalize(x, axis: int = 0):
    def fn(t):
        n = jnp.sqrt(jnp.sum(t * t, axis=axis + 1, keepdims=True))
        return t / jnp.maximum(n, 1e-12)

    return _apply("l2_normalize", fn, x)


# --------------------------------------------------------------- binary --
def maximum(x, y):
    return _apply("maximum", jnp.maximum, x, y)


def minimum(x, y):
    return _apply("minimum", jnp.minimum, x, y)


def dot(x, y, axes=None):
    """Batched contraction of the last axis of x with the first
    non-batch axis of y (reference autograd ``dot``/``mm``):
    [B, ..., K] x [B, K, ...] -> [B, ..., ...]; two 2-D inputs give the
    per-row inner product [B, 1]."""
    def fn(a, b):
        if a.ndim == 2 and b.ndim == 2:
            return jnp.einsum("bi,bi->b", a, b)[:, None]
        return jax.vmap(
            lambda u, v: jnp.tensordot(u, v, axes=(-1, 0)))(a, b)

    return _apply("dot", fn, x, y)


def batch_dot(x, y, axes=(2, 2)):
    """Batched matmul contracting the given 0-based (incl. batch) axes
    (reference autograd ``batch_dot``, matching keras.backend)."""
    ax, ay = axes

    def fn(a, b):
        return jnp.matmul(jnp.moveaxis(a, ax, -1) if ax != a.ndim - 1
                          else a,
                          jnp.moveaxis(b, ay, -2) if ay != b.ndim - 2
                          else b)

    return _apply("batch_dot", fn, x, y)


# ---------------------------------------------------------------- shape --
def expand_dims(x, axis: int):
    return _apply("expand_dims",
                  lambda t: jnp.expand_dims(t, axis=axis), x)


def squeeze(x, axis: int):
    return _apply("squeeze", lambda t: jnp.squeeze(t, axis=axis), x)


def stack(inputs, axis: int = 1):
    return _apply("stack", lambda *ts: jnp.stack(ts, axis=axis), *inputs)


def concat(inputs, axis: int = -1):
    return _apply("concat",
                  lambda *ts: jnp.concatenate(ts, axis=axis), *inputs)


# ---------------------------------------------------------- custom loss --
class CustomLoss:
    """A named loss built from a plain function of (y_pred, y_true)
    using the autograd ops above (ref: CustomLoss.scala /
    autograd.py CustomLoss -- where the reference compiles a Variable
    graph into a BigDL criterion, jax traces the function directly).

    Accepted anywhere the Estimator takes a loss::

        def my_loss(y_pred, y_true):
            return A.mean(A.abs(y_pred - y_true), axis=0)
        model.compile(optimizer="adam", loss=CustomLoss(my_loss))
    """

    def __init__(self, loss_fn: Callable, name: Optional[str] = None):
        self.loss_fn = loss_fn
        self.name = name or getattr(loss_fn, "__name__", "custom_loss")

    def __call__(self, preds, labels):
        out = self.loss_fn(preds, labels)
        return jnp.mean(out)


def mean_absolute_error(y_pred, y_true):
    """Reference autograd example loss (autograd.py doc example)."""
    return jnp.mean(jnp.abs(y_pred - y_true))
