"""analytics_zoo_tpu: a TPU-native unified analytics + AI platform.

A from-scratch, JAX/XLA/Pallas-first rebuild of the capabilities of
Analytics Zoo (reference: /root/reference, jiechenghan/analytics-zoo).
Where the reference stacks Python -> Py4J -> Scala -> JNI over
Spark/Flink/Ray with five data-parallel communication backends, this
framework is one SPMD runtime: ``pjit``/``shard_map`` over a
``jax.sharding.Mesh`` with XLA collectives on ICI/DCN.

Top-level subpackages (reference analog in parens):

- ``common``   -- context/config/logging/triggers  (NNContext, ZooContext, ZooTrigger)
- ``utils``    -- nest, tensorboard writer, io     (util/nest.py, zoo/tensorboard)
- ``parallel`` -- mesh, shardings, collectives, ring attention, pipeline
                  (the five comm backends of SURVEY.md section 2.3, unified)
- ``data``     -- XShards, sharded datasets, feature preprocessing
                  (TFDataset, FeatureSet, XShards)
- ``keras``    -- Keras-style layer library + Sequential/Model
                  (zoo/pipeline/api/keras)
- ``keras2``   -- Keras-2 argument-name API surface
                  (zoo/pipeline/api/keras2)
- ``autograd`` -- dual-mode symbolic/eager math ops + CustomLoss
                  (zoo/pipeline/api/autograd)
- ``learn``    -- Estimator: distributed fit/evaluate/predict
                  (InternalDistriOptimizer, zoo Estimator, Orca Estimator)
- ``ops``      -- Pallas TPU kernels (flash attention, ...)
- ``inference``-- InferenceModel multi-format inference runtime
- ``serving``  -- streaming model serving: queue + batcher + HTTP frontend
- ``nnframes`` -- DataFrame fit/transform pipelines + Preprocessing
                  (zoo/pipeline/nnframes Spark-ML integration)
- ``feature``  -- TextSet/ImageSet preprocessing op libraries
                  (zoo/feature text + image transformers, Relations)
- ``models``   -- model zoo: recommendation, NLP, vision, time series
- ``automl``   -- hyperparameter search engine + recipes
- ``zouwu``    -- time series: forecasters, AutoTS, anomaly detection
"""

from analytics_zoo_tpu.version import __version__  # noqa: F401

from analytics_zoo_tpu.common.context import (  # noqa: F401
    ZooContext,
    init_zoo_context,
    init_orca_context,
    stop_orca_context,
)
