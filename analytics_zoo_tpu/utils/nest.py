"""Nested-structure flatten/pack utilities.

The analog of the reference's ``zoo.util.nest`` (ref:
pyzoo/zoo/util/nest.py), which TFPark uses to marshal arbitrarily nested
(feature, label) structures. Here jax pytrees already provide the
machinery; these wrappers keep the reference's API names and add
deterministic dict ordering.
"""

from __future__ import annotations

from typing import Any, List, Sequence

import jax


def flatten(structure: Any) -> List[Any]:
    """Flatten a nested structure (dicts sorted by key, like pytrees)."""
    leaves, _ = jax.tree_util.tree_flatten(structure)
    return leaves


def pack_sequence_as(structure: Any, flat_sequence: Sequence[Any]) -> Any:
    """Inverse of :func:`flatten` given a template ``structure``."""
    _, treedef = jax.tree_util.tree_flatten(structure)
    return jax.tree_util.tree_unflatten(treedef, list(flat_sequence))


def map_structure(fn, structure: Any) -> Any:
    return jax.tree_util.tree_map(fn, structure)


def assert_same_structure(a: Any, b: Any) -> None:
    ta = jax.tree_util.tree_structure(a)
    tb = jax.tree_util.tree_structure(b)
    if ta != tb:
        raise ValueError(f"structures differ: {ta} vs {tb}")
