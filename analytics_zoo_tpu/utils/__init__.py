from analytics_zoo_tpu.utils import nest  # noqa: F401
from analytics_zoo_tpu.utils.summary import SummaryWriter, read_events  # noqa: F401
