"""TensorBoard-compatible event files, written from scratch.

The reference implements TF-format event writing without depending on
TensorFlow (ref: zoo/.../tensorboard/EventWriter.scala:32-80,
FileWriter.scala:32-60, Summary.scala); this module does the same in pure
Python: hand-encoded protobuf wire format for the ``Event``/``Summary``
messages plus the TFRecord framing (length + masked CRC32C records).

Wire facts used (stable public TF format):
  Event:   double wall_time = 1; int64 step = 2;
           string file_version = 3; Summary summary = 5;
  Summary: repeated Value value = 1;
  Value:   string tag = 1; float simple_value = 2; HistogramProto histo = 5;
  HistogramProto: double min=1,max=2,num=3,sum=4,sum_squares=5;
           repeated double bucket_limit=6 [packed]; repeated double bucket=7.
Record framing: uint64le(len) crc(len) payload crc(payload), where
crc = masked crc32c as in TFRecord.

Readback (``read_events``) supports the Estimator's
``get_train_summary``/``get_validation_summary`` analog
(ref: Topology.scala:1390-1404).
"""

from __future__ import annotations

import io
import itertools
import os
import socket
import struct
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

_WRITER_COUNTER = itertools.count()

# ---------------------------------------------------------------- crc32c ---

_CRC_TABLE: List[int] = []


def _make_table() -> None:
    poly = 0x82F63B78  # Castagnoli, reflected
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC_TABLE.append(crc)


_make_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    # the native slicing-by-8 kernel is ~200x the python table loop;
    # crc32c_if_ready never blocks on the one-time background build
    # (lazy import avoids a cycle)
    from analytics_zoo_tpu import native

    crc = native.crc32c_if_ready(data)
    if crc is None:
        crc = crc32c(data)
    return ((((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


# ------------------------------------------------------- proto wire enc ---


def _varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _tag(field: int, wire_type: int) -> bytes:
    return _varint((field << 3) | wire_type)


def _enc_double(field: int, v: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", v)


def _enc_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


def _enc_int64(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _enc_bytes(field: int, v: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(v)) + v


def _enc_string(field: int, v: str) -> bytes:
    return _enc_bytes(field, v.encode("utf-8"))


def _enc_packed_doubles(field: int, vs) -> bytes:
    payload = b"".join(struct.pack("<d", float(v)) for v in vs)
    return _enc_bytes(field, payload)


def _make_bucket_limits() -> np.ndarray:
    limits: List[float] = []
    v = 1e-12
    while v < 1e20:
        limits.append(v)
        v *= 1.1
    limits = sorted([-x for x in limits]) + limits + [1.7976931348623157e308]
    return np.asarray(limits)


_BUCKET_LIMITS = _make_bucket_limits()


def _encode_histogram(values: np.ndarray) -> bytes:
    """HistogramProto from raw values, TF-style exponential buckets."""
    values = np.asarray(values, dtype=np.float64).ravel()
    if values.size == 0:
        values = np.zeros(1)
    bucket_limit = _BUCKET_LIMITS
    counts, _ = np.histogram(values, bins=np.concatenate(
        [[-1.7976931348623157e308], bucket_limit]))
    nz = counts.nonzero()[0]
    if nz.size:  # trim empty tail/head buckets but keep alignment
        lo, hi = nz[0], nz[-1] + 1
    else:
        lo, hi = 0, 1
    msg = b"".join([
        _enc_double(1, float(values.min())),
        _enc_double(2, float(values.max())),
        _enc_double(3, float(values.size)),
        _enc_double(4, float(values.sum())),
        _enc_double(5, float(np.square(values).sum())),
        _enc_packed_doubles(6, bucket_limit[lo:hi]),
        _enc_packed_doubles(7, counts[lo:hi]),
    ])
    return msg


def encode_scalar_event(tag: str, value: float, step: int,
                        wall_time: Optional[float] = None) -> bytes:
    value_msg = _enc_string(1, tag) + _enc_float(2, float(value))
    summary = _enc_bytes(1, value_msg)
    return b"".join([
        _enc_double(1, wall_time if wall_time is not None else time.time()),
        _enc_int64(2, step),
        _enc_bytes(5, summary),
    ])


def encode_histogram_event(tag: str, values, step: int,
                           wall_time: Optional[float] = None) -> bytes:
    histo = _encode_histogram(np.asarray(values))
    value_msg = _enc_string(1, tag) + _enc_bytes(5, histo)
    summary = _enc_bytes(1, value_msg)
    return b"".join([
        _enc_double(1, wall_time if wall_time is not None else time.time()),
        _enc_int64(2, step),
        _enc_bytes(5, summary),
    ])


def _file_version_event() -> bytes:
    return _enc_double(1, time.time()) + _enc_string(3, "brain.Event:2")


# ------------------------------------------------------------- records ---


def _write_record(f, payload: bytes) -> None:
    header = struct.pack("<Q", len(payload))
    f.write(header)
    f.write(struct.pack("<I", _masked_crc(header)))
    f.write(payload)
    f.write(struct.pack("<I", _masked_crc(payload)))


def _read_records(path: str) -> Iterator[bytes]:
    """Yield records, stopping at the first truncated or CRC-corrupt one."""
    from analytics_zoo_tpu.utils import fileio

    with fileio.open_file(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return
            len_crc = f.read(4)
            if len(len_crc) < 4 or \
                    struct.unpack("<I", len_crc)[0] != _masked_crc(header):
                return  # corrupted length field: cannot trust framing
            (length,) = struct.unpack("<Q", header)
            payload = f.read(length)
            if len(payload) < length:
                return
            payload_crc = f.read(4)
            if len(payload_crc) < 4 or \
                    struct.unpack("<I", payload_crc)[0] != _masked_crc(payload):
                return  # corrupted payload
            yield payload


# -------------------------------------------------------------- writer ---


class _RewriteOnFlushFile:
    """File-like sink for object stores: buffers writes and publishes
    the full object on flush/close (append does not exist there, and
    fsspec's buffered 'wb' streams only become visible at close)."""

    def __init__(self, path: str):
        self._path = path
        self._buf = io.BytesIO()
        self._dirty = False

    def write(self, data: bytes) -> int:
        self._dirty = True
        return self._buf.write(data)

    def flush(self) -> None:
        if not self._dirty:
            return
        from analytics_zoo_tpu.utils import fileio

        fileio.write_bytes(self._path, self._buf.getvalue())
        self._dirty = False

    def close(self) -> None:
        self.flush()


class SummaryWriter:
    """Append-only TB event writer for one log dir.

    The analog of ``FileWriter`` + ``EventWriter`` (buffered, background
    flush) -- here synchronous-with-flush-interval for simplicity.
    """

    def __init__(self, log_dir: str, flush_every: int = 20):
        from analytics_zoo_tpu.utils import fileio

        fileio.makedirs(log_dir, exist_ok=True)
        self.log_dir = log_dir
        # hostname+pid uniquify the file so concurrent writers (train +
        # validation, or multiple worker processes) never interleave
        # partial records in one append-mode file.
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}.{os.getpid()}"
                 f".{next(_WRITER_COUNTER)}.analytics-zoo-tpu")
        self._path = fileio.join(log_dir, fname)
        # remote event files (gs://...): object stores have no append
        # and fsspec buffered streams only publish at close(), so the
        # writer accumulates records in memory and rewrites the whole
        # object on flush -- events stay readable mid-run and a crash
        # loses at most one flush interval (event files are KBs/run)
        self._file = (_RewriteOnFlushFile(self._path)
                      if fileio.is_remote(self._path)
                      else fileio.open_file(self._path, "ab"))
        self._lock = threading.Lock()
        self._pending = 0
        self._flush_every = flush_every
        with self._lock:
            _write_record(self._file, _file_version_event())
            self._file.flush()

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        with self._lock:
            _write_record(self._file, encode_scalar_event(tag, value, step))
            self._maybe_flush()

    def add_histogram(self, tag: str, values, step: int) -> None:
        with self._lock:
            _write_record(self._file,
                          encode_histogram_event(tag, values, step))
            self._maybe_flush()

    def _maybe_flush(self) -> None:
        self._pending += 1
        if self._pending >= self._flush_every:
            self._file.flush()
            self._pending = 0

    def flush(self) -> None:
        with self._lock:
            self._file.flush()
            self._pending = 0

    def close(self) -> None:
        with self._lock:
            self._file.flush()
            self._file.close()


# -------------------------------------------------------------- reader ---


def _decode_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(buf: bytes) -> Iterator[Tuple[int, int, bytes]]:
    pos = 0
    while pos < len(buf):
        key, pos = _decode_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, pos = _decode_varint(buf, pos)
            yield field, wt, _varint(v)
        elif wt == 1:
            yield field, wt, buf[pos:pos + 8]
            pos += 8
        elif wt == 5:
            yield field, wt, buf[pos:pos + 4]
            pos += 4
        elif wt == 2:
            ln, pos = _decode_varint(buf, pos)
            yield field, wt, buf[pos:pos + ln]
            pos += ln
        else:
            raise ValueError(f"unsupported wire type {wt}")


def read_events(log_dir_or_file: str) -> Dict[str, List[Tuple[int, float]]]:
    """Read scalar events back: {tag: [(step, value), ...]}.

    Supports ``get_train_summary``-style readback
    (ref: Topology.scala:1390-1404).
    """
    from analytics_zoo_tpu.utils import fileio

    if fileio.is_remote(log_dir_or_file):
        fs = fileio.get_filesystem(log_dir_or_file)
        bare = str(log_dir_or_file).split("://", 1)[1]
        if fs.isdir(bare):
            files = [u for u in fileio.listdir_uris(log_dir_or_file,
                                                    kind="file")
                     if "tfevents" in os.path.basename(u)]
        else:
            files = [log_dir_or_file]
    elif os.path.isdir(log_dir_or_file):
        files = sorted(
            os.path.join(log_dir_or_file, f)
            for f in os.listdir(log_dir_or_file)
            if "tfevents" in f
        )
    else:
        files = [log_dir_or_file]
    out: Dict[str, List[Tuple[int, float]]] = {}
    for path in files:
        for record in _read_records(path):
            step = 0
            summary = None
            for field, wt, data in _iter_fields(record):
                if field == 2 and wt == 0:
                    step, _ = _decode_varint(data, 0)
                elif field == 5 and wt == 2:
                    summary = data
            if summary is None:
                continue
            for field, wt, data in _iter_fields(summary):
                if field != 1 or wt != 2:
                    continue
                tag, sval = None, None
                for f2, w2, d2 in _iter_fields(data):
                    if f2 == 1 and w2 == 2:
                        tag = d2.decode("utf-8")
                    elif f2 == 2 and w2 == 5:
                        (sval,) = struct.unpack("<f", d2)
                if tag is not None and sval is not None:
                    out.setdefault(tag, []).append((step, sval))
    return out
