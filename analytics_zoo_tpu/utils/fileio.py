"""URI-based filesystem layer: one IO surface for local paths and
remote schemes (gs://, s3://, hdfs://, memory://...).

The analog of the reference's transparent local/HDFS/S3 file utilities
(ref: zoo/src/main/scala/com/intel/analytics/zoo/common/Utils.scala --
``saveBytes``/``readBytes`` dispatch on the Hadoop FileSystem of the
URI). On a TPU pod, datasets, checkpoints and TB event files live in
GCS; every framework IO path (data/sources.py, learn/checkpoint.py,
utils/summary.py) routes through here so any fsspec scheme works.

Local paths (no scheme) use plain ``os``/``open`` -- no dependency and
no behavior change. Scheme'd paths use ``fsspec`` when available;
without fsspec a clear error names the missing capability instead of
silently writing a local file literally named "gs:/...".
"""

from __future__ import annotations

import os
from typing import IO, List, Optional

__all__ = ["is_remote", "open_file", "read_bytes", "write_bytes",
           "exists", "makedirs", "listdir", "listdir_uris", "remove",
           "rename", "get_filesystem"]


def is_remote(path: str) -> bool:
    """True for scheme'd URIs (``gs://...``); ``file://`` counts as
    remote so it also routes through fsspec's normalization."""
    return "://" in str(path)


def get_filesystem(path: str):
    """The fsspec filesystem owning ``path`` (remote paths only)."""
    try:
        import fsspec
    except ImportError as e:  # pragma: no cover - fsspec is baked in
        raise RuntimeError(
            f"path {path!r} needs fsspec for scheme'd URIs; install "
            "fsspec or use a local path") from e
    fs, _ = fsspec.core.url_to_fs(str(path))
    return fs


def _strip(path: str) -> str:
    """fsspec methods want the path without the scheme for some
    filesystems; url_to_fs returns the normalized form."""
    import fsspec

    _, p = fsspec.core.url_to_fs(str(path))
    return p


def open_file(path: str, mode: str = "rb") -> IO:
    if is_remote(path):
        import fsspec

        return fsspec.open(str(path), mode).open()
    if any(m in mode for m in ("w", "a", "x")):
        parent = os.path.dirname(str(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
    return open(path, mode)


def read_bytes(path: str) -> bytes:
    with open_file(path, "rb") as f:
        return f.read()


def write_bytes(path: str, data: bytes) -> None:
    with open_file(path, "wb") as f:
        f.write(data)


def exists(path: str) -> bool:
    if is_remote(path):
        return get_filesystem(path).exists(_strip(path))
    return os.path.exists(path)


def makedirs(path: str, exist_ok: bool = True) -> None:
    if is_remote(path):
        get_filesystem(path).makedirs(_strip(path), exist_ok=exist_ok)
    else:
        os.makedirs(path, exist_ok=exist_ok)


def listdir(path: str) -> List[str]:
    """Base names of entries under ``path`` (non-recursive)."""
    if is_remote(path):
        fs = get_filesystem(path)
        return sorted(os.path.basename(p.rstrip("/"))
                      for p in fs.ls(_strip(path), detail=False))
    return sorted(os.listdir(path))


def listdir_uris(path: str, kind: Optional[str] = None) -> List[str]:
    """Full-URI entries under a remote directory, from ONE listing call.

    ``ls(detail=True)`` already carries each entry's type, so filtering
    by ``kind`` ("file" / "directory" / None for all) costs no extra
    RPCs -- per-entry ``isfile``/``isdir`` probes would issue one
    metadata request each, which on an object store with 10k shards
    means 10k sequential HTTP round-trips before any data is read.
    The scheme is re-attached so results feed straight back into this
    module (and into fsspec-aware readers like pandas)."""
    fs = get_filesystem(path)
    scheme = str(path).split("://", 1)[0]
    out = []
    for e in fs.ls(_strip(path), detail=True):
        if kind is not None and e.get("type") != kind:
            continue
        out.append(f"{scheme}://{e['name']}")
    return sorted(out)


def remove(path: str, recursive: bool = False) -> None:
    if is_remote(path):
        get_filesystem(path).rm(_strip(path), recursive=recursive)
    elif recursive and os.path.isdir(path):
        import shutil

        shutil.rmtree(path)
    else:
        os.remove(path)


def rename(src: str, dst: str) -> None:
    """Atomic for local paths; copy-delete semantics on object stores
    (fsspec mv), which is the same guarantee the reference's HDFS/S3
    rename gives."""
    if is_remote(src) or is_remote(dst):
        if not (is_remote(src) and is_remote(dst)):
            raise ValueError("rename across local/remote is not "
                             "supported; copy explicitly")
        get_filesystem(src).mv(_strip(src), _strip(dst), recursive=True)
    else:
        os.replace(src, dst)


def join(base: str, *parts: str) -> str:
    """Path join that preserves URI schemes (os.path.join would eat
    the double slash on some platforms)."""
    if is_remote(base):
        out = str(base).rstrip("/")
        for p in parts:
            out += "/" + str(p).strip("/")
        return out
    return os.path.join(base, *parts)
