"""Classical-ML utilities (host-side): gradient-boosted trees."""

from analytics_zoo_tpu.ml.gbt import (  # noqa: F401
    GBTClassifier,
    GBTRegressor,
    GradientBoostedTrees,
)
