"""Histogram-based gradient-boosted trees (xgboost-style).

The reference integrates XGBoost twice -- as an AutoML model
(ref: pyzoo/zoo/automl/model/XGBoost.py wrapping XGBRegressor/
XGBClassifier) and as a Spark-ML helper
(ref: zoo/src/main/scala/com/intel/analytics/zoo/pipeline/nnframes/
XGBoostHelper.scala). This image ships no xgboost wheel, so the
framework carries its own engine with the same training math
(second-order boosting: gain = 1/2 [G_L^2/(H_L+l) + G_R^2/(H_R+l) -
G^2/(H+l)] - gamma, leaf weight -G/(H+l)) behind an xgboost-compatible
parameter surface; callers (automl.xgboost, nnframes.xgb) prefer the
real ``xgboost`` package when importable and fall back here.

Trees are host-side numpy -- boosting is sequential and branchy, the
one workload class the MXU does not want; inference over the fitted
ensemble is vectorized numpy as well.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["GradientBoostedTrees", "GBTRegressor", "GBTClassifier"]


class _Tree:
    """Flat-array binary tree: internal nodes carry (feature, bin
    threshold); leaves carry weights."""

    __slots__ = ("feature", "threshold", "left", "right", "value")

    def __init__(self):
        self.feature: List[int] = []
        self.threshold: List[float] = []
        self.left: List[int] = []
        self.right: List[int] = []
        self.value: List[float] = []

    def add(self, feature=-1, threshold=0.0, value=0.0) -> int:
        self.feature.append(feature)
        self.threshold.append(threshold)
        self.left.append(-1)
        self.right.append(-1)
        self.value.append(value)
        return len(self.feature) - 1

    def predict(self, x: np.ndarray) -> np.ndarray:
        feature = np.asarray(self.feature)
        thresh = np.asarray(self.threshold, np.float32)
        left = np.asarray(self.left)
        right = np.asarray(self.right)
        value = np.asarray(self.value, np.float32)
        idx = np.zeros(len(x), np.int64)
        # levels are bounded by max_depth; loop until every row parked
        # on a leaf (feature == -1)
        while True:
            at_leaf = feature[idx] < 0
            if at_leaf.all():
                return value[idx]
            go_left = x[np.arange(len(x)), np.maximum(feature[idx], 0)] \
                <= thresh[idx]
            nxt = np.where(go_left, left[idx], right[idx])
            idx = np.where(at_leaf, idx, nxt)

    def to_dict(self) -> Dict[str, list]:
        return {"feature": [int(v) for v in self.feature],
                "threshold": [float(v) for v in self.threshold],
                "left": [int(v) for v in self.left],
                "right": [int(v) for v in self.right],
                "value": [float(v) for v in self.value]}

    @classmethod
    def from_dict(cls, d: Dict[str, list]) -> "_Tree":
        t = cls()
        t.feature = list(d["feature"])
        t.threshold = [float(v) for v in d["threshold"]]
        t.left = list(d["left"])
        t.right = list(d["right"])
        t.value = [float(v) for v in d["value"]]
        return t


class GradientBoostedTrees:
    """Second-order boosting with quantile-binned histogram splits.

    Parameters mirror xgboost: ``n_estimators``, ``max_depth``,
    ``learning_rate``, ``reg_lambda``, ``gamma`` (min split gain),
    ``min_child_weight``, ``subsample``, ``colsample_bytree``,
    ``n_bins``. ``objective``: "reg:squarederror", "binary:logistic" or
    "multi:softprob" (set ``num_class``).
    """

    def __init__(self, objective: str = "reg:squarederror",
                 n_estimators: int = 50, max_depth: int = 4,
                 learning_rate: float = 0.2, reg_lambda: float = 1.0,
                 gamma: float = 0.0, min_child_weight: float = 1.0,
                 subsample: float = 1.0, colsample_bytree: float = 1.0,
                 n_bins: int = 64, num_class: Optional[int] = None,
                 seed: int = 0):
        self.objective = objective
        self.n_estimators = int(n_estimators)
        self.max_depth = int(max_depth)
        self.learning_rate = float(learning_rate)
        self.reg_lambda = float(reg_lambda)
        self.gamma = float(gamma)
        self.min_child_weight = float(min_child_weight)
        self.subsample = float(subsample)
        self.colsample_bytree = float(colsample_bytree)
        self.n_bins = int(n_bins)
        self.num_class = num_class
        self.seed = seed
        self.trees_: List[List[_Tree]] = []   # [round][output]
        self.base_score_: Optional[np.ndarray] = None
        self._bin_edges: Optional[List[np.ndarray]] = None

    # ------------------------------------------------------- internals --
    def _n_outputs(self) -> int:
        if self.objective == "multi:softprob":
            if not self.num_class or self.num_class < 2:
                raise ValueError("multi:softprob needs num_class >= 2")
            return int(self.num_class)
        return 1

    def _grad_hess(self, y: np.ndarray, margin: np.ndarray):
        if self.objective == "reg:squarederror":
            return margin - y[:, None], np.ones_like(margin)
        if self.objective == "binary:logistic":
            p = 1.0 / (1.0 + np.exp(-margin))
            return p - y[:, None], np.maximum(p * (1 - p), 1e-6)
        if self.objective == "multi:softprob":
            m = margin - margin.max(axis=1, keepdims=True)
            e = np.exp(m)
            p = e / e.sum(axis=1, keepdims=True)
            onehot = np.eye(self._n_outputs(), dtype=np.float32)[
                y.astype(np.int64)]
            return p - onehot, np.maximum(p * (1 - p), 1e-6)
        raise ValueError(f"unknown objective {self.objective!r}")

    def _bin(self, x: np.ndarray):
        """Quantile bin edges per feature; returns binned uint16 codes."""
        edges = []
        codes = np.empty(x.shape, np.uint16)
        qs = np.linspace(0, 100, self.n_bins + 1)[1:-1]
        for j in range(x.shape[1]):
            e = np.unique(np.percentile(x[:, j], qs))
            edges.append(e.astype(np.float32))
            codes[:, j] = np.searchsorted(e, x[:, j], side="left")
        self._bin_edges = edges
        return codes

    def _build_tree(self, codes, x, grad, hess, rows, cols) -> _Tree:
        tree = _Tree()

        def grow(node_rows, depth) -> int:
            g, h = grad[node_rows].sum(), hess[node_rows].sum()
            if depth >= self.max_depth or len(node_rows) < 2:
                return tree.add(value=float(
                    -g / (h + self.reg_lambda) * self.learning_rate))
            best = None
            for j in cols:
                nb = len(self._bin_edges[j]) + 1
                if nb < 2:
                    continue
                c = codes[node_rows, j]
                gh = np.zeros((nb, 2), np.float64)
                np.add.at(gh, c, np.stack(
                    [grad[node_rows], hess[node_rows]], axis=1))
                gl = np.cumsum(gh[:-1, 0])
                hl = np.cumsum(gh[:-1, 1])
                gr, hr = g - gl, h - hl
                ok = (np.minimum(hl, hr) >= self.min_child_weight)
                gain = 0.5 * (gl ** 2 / (hl + self.reg_lambda)
                              + gr ** 2 / (hr + self.reg_lambda)
                              - g ** 2 / (h + self.reg_lambda)) \
                    - self.gamma
                gain = np.where(ok, gain, -np.inf)
                b = int(np.argmax(gain))
                if gain[b] > 0 and (best is None or gain[b] > best[0]):
                    best = (float(gain[b]), j, b)
            if best is None:
                return tree.add(value=float(
                    -g / (h + self.reg_lambda) * self.learning_rate))
            _, j, b = best
            node = tree.add(feature=j,
                            threshold=float(self._bin_edges[j][b])
                            if b < len(self._bin_edges[j])
                            else float("inf"))
            go_left = codes[node_rows, j] <= b
            tree.left[node] = grow(node_rows[go_left], depth + 1)
            tree.right[node] = grow(node_rows[~go_left], depth + 1)
            return node

        grow(rows, 0)
        return tree

    # --------------------------------------------------------- fitting --
    def fit(self, x: np.ndarray, y: np.ndarray
            ) -> "GradientBoostedTrees":
        x = np.asarray(x, np.float32)
        y = np.asarray(y, np.float32).reshape(len(x))
        k = self._n_outputs()
        rng = np.random.RandomState(self.seed)
        codes = self._bin(x)
        if self.objective == "reg:squarederror":
            self.base_score_ = np.asarray([float(y.mean())] * k,
                                          np.float32)
        else:
            self.base_score_ = np.zeros((k,), np.float32)
        margin = np.broadcast_to(self.base_score_,
                                 (len(x), k)).astype(np.float64).copy()
        self.trees_ = []
        n_cols = max(1, int(round(self.colsample_bytree * x.shape[1])))
        n_rows = max(2, int(round(self.subsample * len(x))))
        for _ in range(self.n_estimators):
            grad, hess = self._grad_hess(y, margin)
            round_trees: List[_Tree] = []
            for out in range(k):
                rows = (np.arange(len(x)) if n_rows >= len(x) else
                        rng.choice(len(x), n_rows, replace=False))
                cols = (np.arange(x.shape[1]) if n_cols >= x.shape[1]
                        else np.sort(rng.choice(x.shape[1], n_cols,
                                                replace=False)))
                tree = self._build_tree(codes, x, grad[:, out],
                                        hess[:, out], rows, cols)
                margin[:, out] += tree.predict(x)
                round_trees.append(tree)
            self.trees_.append(round_trees)
        return self

    # ------------------------------------------------------- inference --
    def margin(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float32)
        k = self._n_outputs()
        out = np.broadcast_to(self.base_score_,
                              (len(x), k)).astype(np.float64).copy()
        for round_trees in self.trees_:
            for j, tree in enumerate(round_trees):
                out[:, j] += tree.predict(x)
        return out

    def predict(self, x: np.ndarray) -> np.ndarray:
        m = self.margin(x)
        if self.objective == "reg:squarederror":
            return m[:, 0].astype(np.float32)
        if self.objective == "binary:logistic":
            return (m[:, 0] > 0).astype(np.int32)
        return m.argmax(axis=1).astype(np.int32)

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        m = self.margin(x)
        if self.objective == "binary:logistic":
            p = 1.0 / (1.0 + np.exp(-m[:, 0]))
            return np.stack([1 - p, p], axis=1).astype(np.float32)
        if self.objective == "multi:softprob":
            m = m - m.max(axis=1, keepdims=True)
            e = np.exp(m)
            return (e / e.sum(axis=1, keepdims=True)).astype(np.float32)
        raise ValueError("predict_proba needs a classification objective")

    # ----------------------------------------------------- persistence --
    def save(self, path: str) -> None:
        meta = {k: getattr(self, k) for k in (
            "objective", "n_estimators", "max_depth", "learning_rate",
            "reg_lambda", "gamma", "min_child_weight", "subsample",
            "colsample_bytree", "n_bins", "num_class", "seed")}
        blob = {
            "meta": meta,
            "base_score": (None if self.base_score_ is None
                           else self.base_score_.tolist()),
            "bin_edges": (None if self._bin_edges is None
                          else [e.tolist() for e in self._bin_edges]),
            "trees": [[t.to_dict() for t in r] for r in self.trees_],
        }
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(blob, f)

    @classmethod
    def load(cls, path: str) -> "GradientBoostedTrees":
        with open(path) as f:
            blob = json.load(f)
        model = cls(**blob["meta"])
        if blob["base_score"] is not None:
            model.base_score_ = np.asarray(blob["base_score"], np.float32)
        if blob["bin_edges"] is not None:
            model._bin_edges = [np.asarray(e, np.float32)
                                for e in blob["bin_edges"]]
        model.trees_ = [[_Tree.from_dict(t) for t in r]
                        for r in blob["trees"]]
        return model


def GBTRegressor(**params) -> GradientBoostedTrees:
    params.setdefault("objective", "reg:squarederror")
    return GradientBoostedTrees(**params)


def GBTClassifier(num_class: int = 2, **params) -> GradientBoostedTrees:
    params.setdefault(
        "objective",
        "binary:logistic" if num_class == 2 else "multi:softprob")
    if params["objective"] == "multi:softprob":
        params.setdefault("num_class", num_class)
    return GradientBoostedTrees(**params)
