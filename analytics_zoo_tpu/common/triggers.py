"""Composable training triggers.

The analog of ``ZooTrigger`` and BigDL triggers
(ref: zoo/.../common/ZooTrigger.scala:135-170 for And/Or composition;
EveryEpoch/SeveralIteration/MaxEpoch/MaxIteration/MaxScore/MinLoss mirror
the BigDL trigger family the Keras API exposes through
``setCheckpoint``/``setValidation``).

A trigger is a callable over :class:`TriggerState`; the Estimator evaluates
triggers after every optimization step (end-of-epoch triggers fire on the
step that completes an epoch).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional, Sequence


@dataclass
class TriggerState:
    """Snapshot of training progress the Estimator feeds to triggers."""

    epoch: int = 0                 # completed epochs
    iteration: int = 0             # completed optimization steps (global)
    epoch_finished: bool = False   # did this step complete an epoch?
    loss: Optional[float] = None   # last training loss
    score: Optional[float] = None  # last validation score (higher=better)
    wall_time: float = field(default_factory=time.time)
    start_time: float = field(default_factory=time.time)


class Trigger:
    def __call__(self, state: TriggerState) -> bool:
        raise NotImplementedError

    def __and__(self, other: "Trigger") -> "And":
        return And(self, other)

    def __or__(self, other: "Trigger") -> "Or":
        return Or(self, other)


class EveryEpoch(Trigger):
    """Fires on steps that complete an epoch."""

    def __call__(self, state: TriggerState) -> bool:
        return state.epoch_finished


class SeveralIteration(Trigger):
    """Fires every ``interval`` optimization steps."""

    def __init__(self, interval: int):
        if interval <= 0:
            raise ValueError("interval must be positive")
        self.interval = interval

    def __call__(self, state: TriggerState) -> bool:
        return state.iteration > 0 and state.iteration % self.interval == 0


class MaxEpoch(Trigger):
    """End-trigger: fires once ``max_epoch`` epochs have completed."""

    def __init__(self, max_epoch: int):
        self.max_epoch = max_epoch

    def __call__(self, state: TriggerState) -> bool:
        return state.epoch >= self.max_epoch


class MaxIteration(Trigger):
    def __init__(self, max_iteration: int):
        self.max_iteration = max_iteration

    def __call__(self, state: TriggerState) -> bool:
        return state.iteration >= self.max_iteration


class MaxScore(Trigger):
    """Fires when validation score exceeds ``max_score``."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def __call__(self, state: TriggerState) -> bool:
        return state.score is not None and state.score > self.max_score


class MinLoss(Trigger):
    """Fires when training loss drops below ``min_loss``.

    The Estimator materializes loss on host only at its logging cadence
    (``zoo.train.log_every_n_steps``), so this trigger observes the loss
    at that granularity -- keeping the train loop free of per-step
    device->host syncs."""

    def __init__(self, min_loss: float):
        self.min_loss = min_loss

    def __call__(self, state: TriggerState) -> bool:
        return state.loss is not None and state.loss < self.min_loss


class TimeLimit(Trigger):
    """Fires after ``max_seconds`` of wall-clock training time."""

    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds

    def __call__(self, state: TriggerState) -> bool:
        return (state.wall_time - state.start_time) >= self.max_seconds


class And(Trigger):
    """Fires iff every child trigger fires (ref: ZooTrigger.scala:135-151)."""

    def __init__(self, *triggers: Trigger):
        if not triggers:
            raise ValueError("And needs at least one trigger")
        self.triggers: Sequence[Trigger] = triggers

    def __call__(self, state: TriggerState) -> bool:
        return all(t(state) for t in self.triggers)


class Or(Trigger):
    """Fires iff any child trigger fires (ref: ZooTrigger.scala:152-170)."""

    def __init__(self, *triggers: Trigger):
        if not triggers:
            raise ValueError("Or needs at least one trigger")
        self.triggers: Sequence[Trigger] = triggers

    def __call__(self, state: TriggerState) -> bool:
        return any(t(state) for t in self.triggers)
