"""Cluster/runtime context initialization.

The TPU-native analog of the reference's ``NNContext.initNNContext`` +
``init_orca_context`` (ref: zoo/.../common/NNContext.scala:134-150,
pyzoo/zoo/common/nncontext.py:319-392, pyzoo/zoo/orca/common.py:21-218).

Where the reference creates a SparkContext, pins MKL/OMP env, initializes the
BigDL engine, and optionally boots a Ray cluster inside Spark executors
(RayOnSpark), here one call:

- optionally initializes ``jax.distributed`` for multi-host (DCN) runs
  (the analog of the cluster bootstrap in init_spark_on_yarn/k8s),
- discovers local + global devices,
- builds the default device mesh (data-parallel unless told otherwise),
- installs the global config.

There is exactly ONE runtime to initialize -- JAX SPMD -- instead of five
(Spark+BigDL, Ray, Flink, Horovod, MXNet PS); see SURVEY.md section 2.3.
"""

from __future__ import annotations

import atexit
import threading
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np

from analytics_zoo_tpu.common.config import ZooConfig, get_config
from analytics_zoo_tpu.common.log import get_logger

logger = get_logger(__name__)

_cache_dir_applied: Optional[str] = None
_cache_lock = threading.Lock()


def enable_compilation_cache(cache_dir: Optional[str] = None) -> None:
    """Point XLA's persistent compilation cache at a durable directory so
    the first-compile tax (200 s for BERT-base, ~30 s for NCF on v5e) is
    paid once per machine, not once per process. Serving restarts and
    preemption-resumes then start at steady-state speed.

    Idempotent per directory; called automatically by
    ``init_zoo_context``, the Estimator, and ``InferenceModel``. A later
    call with a DIFFERENT directory (explicit argument or a changed
    ``zoo.compile_cache.dir``) re-points the cache -- entries compiled
    from then on land there. Configure with ``zoo.compile_cache.dir``
    ("" disables) and ``zoo.compile_cache.min_compile_secs``. The dir
    accepts any fileio URI (``gs://...`` via fsspec) -- on a pod, point
    every host at the same bucket."""
    global _cache_dir_applied
    with _cache_lock:
        import os

        cfg = get_config()
        cache_dir = cache_dir or cfg.get("zoo.compile_cache.dir")
        if not cache_dir:
            return
        cache_dir = os.path.expanduser(str(cache_dir))
        if cache_dir == _cache_dir_applied:
            return
        try:
            if "://" not in cache_dir:
                os.makedirs(cache_dir, exist_ok=True)
            if _cache_dir_applied is not None:
                # jax memoizes the cache object at first use; re-pointing
                # the dir requires dropping it or the update is silent
                from jax.experimental.compilation_cache import (
                    compilation_cache)

                compilation_cache.reset_cache()
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                float(cfg.get("zoo.compile_cache.min_compile_secs", 2.0)))
            _cache_dir_applied = cache_dir
            logger.info("XLA persistent compilation cache: %s", cache_dir)
        except Exception as e:  # cache is an optimization, never fatal
            logger.warning("compilation cache unavailable: %s", e)


class ZooContext:
    """Singleton runtime context.

    Attributes:
      config: the layered ZooConfig.
      devices: global (across hosts) jax devices.
      local_devices: devices attached to this host/process.
      mesh: the default ``jax.sharding.Mesh`` (data-parallel over all
        devices unless ``mesh_shape`` was given at init).
    """

    _instance: Optional["ZooContext"] = None
    _lock = threading.Lock()

    # class-level feature flags, the analog of the reference ZooContext
    # metaclass properties (ref: pyzoo/zoo/common/nncontext.py:269-316)
    log_output: bool = True

    def __init__(
        self,
        cluster_mode: str = "local",
        mesh_shape: Optional[Dict[str, int]] = None,
        config: Optional[ZooConfig] = None,
    ):
        self.cluster_mode = cluster_mode
        self.config = config or get_config()
        self.devices = jax.devices()
        self.local_devices = jax.local_devices()
        self.num_processes = jax.process_count()
        self.process_id = jax.process_index()
        self._mesh_shape = mesh_shape
        self.mesh = self._build_mesh(mesh_shape)

    def _build_mesh(self, mesh_shape: Optional[Dict[str, int]]):
        # delegate to the canonical builder: hybrid ICI x DCN layout on
        # multi-host, -1 axis inference, validation.
        from analytics_zoo_tpu.parallel.mesh import create_mesh

        if not mesh_shape:
            axis = self.config.get("zoo.mesh.axis.data")
            return create_mesh({axis: len(self.devices)})
        return create_mesh(mesh_shape)

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    def barrier(self, name: str = "zoo_barrier") -> None:
        """Block until all processes reach this point (no-op single-host)."""
        if self.num_processes > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(name)

    def stop(self) -> None:
        with ZooContext._lock:
            if ZooContext._instance is not self:
                return  # stale handle; don't tear down a newer context
            ZooContext._instance = None
        if self.cluster_mode == "multihost":
            try:
                jax.distributed.shutdown()
            except RuntimeError:
                pass

    @classmethod
    def get(cls) -> Optional["ZooContext"]:
        with cls._lock:
            return cls._instance


def init_zoo_context(
    cluster_mode: str = "local",
    mesh_shape: Optional[Dict[str, int]] = None,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    conf: Optional[Dict[str, Any]] = None,
) -> ZooContext:
    """Initialize (or fetch) the global runtime context.

    Args:
      cluster_mode: "local" (single host, all local chips) or "multihost"
        (jax.distributed over DCN; the analog of init_spark_on_yarn/k8s,
        ref: pyzoo/zoo/common/nncontext.py:31-244).
      mesh_shape: optional ordered {axis_name: size} for the default mesh,
        e.g. {"data": 8} or {"data": 2, "model": 4}. Defaults to pure
        data parallelism over every visible device.
      coordinator_address / num_processes / process_id: multihost rendezvous
        parameters, forwarded to ``jax.distributed.initialize``.
      conf: extra config overrides, applied to the global ZooConfig
        (the analog of extra spark conf dict).
    """
    if cluster_mode not in ("local", "multihost"):
        raise ValueError(
            f"unknown cluster_mode {cluster_mode!r}; use 'local' or 'multihost'"
        )

    with ZooContext._lock:
        if ZooContext._instance is not None:
            existing = ZooContext._instance
            if (mesh_shape is not None and mesh_shape != existing._mesh_shape) \
                    or cluster_mode != existing.cluster_mode or conf:
                logger.warning(
                    "init_zoo_context called with new arguments but a context "
                    "already exists; returning the existing context "
                    "(mode=%s, mesh=%s). Call stop_orca_context() first to "
                    "re-initialize.", existing.cluster_mode,
                    dict(zip(existing.mesh.axis_names,
                             existing.mesh.devices.shape)))
            return existing

        dist_started_here = False
        if cluster_mode == "multihost":
            kwargs: Dict[str, Any] = {}
            if coordinator_address is not None:
                kwargs["coordinator_address"] = coordinator_address
            if num_processes is not None:
                kwargs["num_processes"] = num_processes
            if process_id is not None:
                kwargs["process_id"] = process_id
            # a previous init attempt may have failed *after* this point;
            # reuse the live distributed runtime rather than poisoning every
            # future init (jax raises on double-initialize).
            if not jax.distributed.is_initialized():
                jax.distributed.initialize(**kwargs)
                dist_started_here = True

        config = get_config()
        if conf:
            for k, v in conf.items():
                config.set(k, v)
        enable_compilation_cache()

        try:
            ctx = ZooContext(cluster_mode=cluster_mode, mesh_shape=mesh_shape,
                             config=config)
        except Exception:
            if dist_started_here:
                try:
                    jax.distributed.shutdown()
                except RuntimeError:
                    pass
            raise
        ZooContext._instance = ctx
    logger.info(
        "initialized ZooContext: mode=%s processes=%d devices=%d mesh=%s",
        cluster_mode, ctx.num_processes, ctx.num_devices,
        dict(zip(ctx.mesh.axis_names, ctx.mesh.devices.shape)),
    )
    return ctx


# Orca-compatible aliases (ref: pyzoo/zoo/orca/common.py init_orca_context /
# stop_orca_context): one unified entry point for users of the reference API.
def init_orca_context(cluster_mode: str = "local", **kwargs) -> ZooContext:
    return init_zoo_context(cluster_mode=cluster_mode, **kwargs)


def stop_orca_context() -> None:
    ctx = ZooContext.get()
    if ctx is not None:
        ctx.stop()


atexit.register(stop_orca_context)
