"""Layered configuration system.

The reference layers Spark properties (packaged defaults file +
``spark.analytics.zoo.*`` overrides), JVM system properties, and env vars
(ref: zoo/.../common/NNContext.scala:189-247, SURVEY.md section 5 "Config").
Here the layers are, lowest to highest precedence:

1. built-in defaults (``_DEFAULTS``)
2. an optional config file (``analytics-zoo-tpu.conf``, ``key value`` lines,
   the analog of ``spark-analytics-zoo.conf``)
3. environment variables ``AZT_<KEY>`` (dots -> underscores, uppercased)
4. programmatic ``set()`` calls
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

_DEFAULTS: Dict[str, Any] = {
    # training
    "zoo.train.failure.retry_times": 5,          # ref: bigdl.failure.retryTimes (Topology.scala:1256)
    "zoo.train.failure.retry_interval_s": 120,   # ref: bigdl.failure.retryTimeInterval
    "zoo.train.log_every_n_steps": 50,
    "zoo.train.donate_buffers": True,
    # training PRNG stream (dropout masks, epoch shuffles): "auto" uses
    # the hardware RBG generator on TPU -- threefry2x32 dropout costs
    # ~23 ms/step on BERT-base b32/L384 v5e (MFU 0.35 -> 0.42 measured)
    # -- and threefry elsewhere; set explicitly to pin an impl
    "zoo.train.prng_impl": "auto",
    # mesh / parallelism axis names -- read through
    # parallel.mesh.config_axis("<role>") (a prefix-built key, so
    # grep for the wrapper, not the literal)
    "zoo.mesh.axis.data": "data",
    "zoo.mesh.axis.model": "model",
    "zoo.mesh.axis.sequence": "seq",
    "zoo.mesh.axis.pipeline": "pipe",
    "zoo.mesh.axis.expert": "expert",
    # ops
    # attention kernel dispatch: "auto" (flash on TPU when shapes
    # allow, einsum otherwise), "flash", or "einsum". At short seqs
    # (<=512) the materialized einsum path is often faster on TPU than
    # a flash kernel at head_dim 64; auto picks per shape.
    "zoo.ops.attention_impl": "auto",
    # seq length at/below which auto prefers the einsum path (scores
    # fit HBM comfortably and XLA's batched matmuls beat the blockwise
    # kernel's VPU overhead at these sizes)
    "zoo.ops.attention_flash_min_seq": 512,
    # causal ring-attention schedule: "zigzag" balances causal load
    # over the ring (~2x less compute), "contiguous" is the classic
    # layout; "auto" picks zigzag for causal when shapes divide
    "zoo.ops.ring_schedule": "auto",
    # data layer
    # image-backbone BN statistics rows: 0 = exact full-batch stats;
    # K > 0 computes train-time BN stats over the first K batch rows
    # (the stat reduce is a pure HBM-bandwidth pass -- 31% of the r4
    # ResNet-50 step; see SampledBatchNorm)
    "zoo.models.bn_stat_rows": 0,

    "zoo.data.prefetch_buffer": 2,
    "zoo.data.check_batch_divisible": True,      # ref: tf_dataset.py:142-147 batch % cores == 0
    # serving
    "zoo.serving.batch_size": 8,
    "zoo.serving.batch_timeout_ms": 5,
    # adaptive micro-batching (AdaptiveBatcher): the linger floor the
    # deadline tightens toward when the input queue is shallow, and the
    # cap the batch may grow to under backlog (0 = auto: the power-of-
    # two bucket of 4x batch_size). Growth is snapped to the bucket
    # ladder so it never introduces a new XLA shape.
    "zoo.serving.batch_timeout_min_ms": 1.0,
    "zoo.serving.batch_max_size": 0,
    # pipelined serving engine: decode -> assemble/dispatch -> finalize
    # run as overlapped stages with up to pipeline.depth dispatched
    # batches in flight; false restores the synchronous per-batch loop
    "zoo.serving.pipeline.enabled": True,
    "zoo.serving.pipeline.depth": 2,
    # launcher default when the YAML omits http.port; 0 = pick a free
    # port (the reference FrontEndApp pinned 10020 -- set that here to
    # reproduce its behavior)
    "zoo.serving.http_port": 0,
    # resilience (serving/resilience.py): the launcher wraps the
    # worker in a Supervisor that restarts it on death (thread crash)
    # or wedge (stale heartbeat), with capped exponential backoff +
    # jitter, re-queuing that run's in-flight requests exactly once
    "zoo.serving.supervisor.enabled": True,
    "zoo.serving.supervisor.poll_interval_s": 0.5,
    "zoo.serving.supervisor.heartbeat_timeout_s": 30.0,
    "zoo.serving.supervisor.backoff_base_s": 0.1,
    "zoo.serving.supervisor.backoff_max_s": 30.0,
    "zoo.serving.supervisor.max_restarts": 0,    # 0 = unlimited
    # circuit breaker around backend dispatch: open after `threshold`
    # consecutive predict failures, half-open probe after cooldown_s
    "zoo.serving.breaker.enabled": False,
    "zoo.serving.breaker.threshold": 5,
    "zoo.serving.breaker.cooldown_s": 5.0,
    # per-request deadline budget stamped at enqueue (0 = off): the
    # worker rejects expired requests with a structured
    # deadline_exceeded error at decode/dispatch/finalize instead of
    # burning a device slot on an answer nobody is waiting for
    "zoo.serving.deadline_ms": 0.0,
    # load shedding (0 = off): InputQueue.enqueue refuses new work
    # once queue depth reaches this, and the HTTP frontend turns the
    # refusal into 503 + Retry-After instead of letting p99 explode.
    # ISSUE-15 turns the single threshold into a brownout LADDER:
    # queue_depth is the interactive (highest-class) threshold, and
    # batch/background admit only below batch_fraction/
    # background_fraction of it -- lowest class sheds first, and a
    # class is never refused while a lower one is admitted.
    # retry_after_s stays the Retry-After FLOOR; the advertised value
    # scales with an EWMA of the shed rate (ewma_alpha per-second
    # smoothing) up to retry_after_max_s. gen_cost_tokens converts a
    # generate request's max_tokens budget into admission cost
    # (ceil(max_tokens / gen_cost_tokens) queue slots) so one long
    # stream can't starve interactive traffic.
    "zoo.serving.shed.queue_depth": 0,
    "zoo.serving.shed.retry_after_s": 1.0,
    "zoo.serving.shed.batch_fraction": 0.6,
    "zoo.serving.shed.background_fraction": 0.3,
    "zoo.serving.shed.retry_after_max_s": 30.0,
    "zoo.serving.shed.ewma_alpha": 0.2,
    "zoo.serving.shed.gen_cost_tokens": 16,
    # priority classes (ISSUE-15): the admission class a request
    # without __priority__ is treated as (interactive outranks batch
    # outranks background)
    "zoo.serving.priority.default_class": "interactive",
    # sharded serving (inference/sharded.py): route predict_async
    # through a device mesh. mode: off (single-chip, byte-identical to
    # the pre-mesh engine incl. compile-cache keys) | tp (params
    # sharded by the recipe over zoo.mesh.axis.model, batch
    # replicated) | dp (params replicated, batch sharded) | auto
    # (tp when param bytes exceed auto_hbm_fraction of one chip's HBM,
    # else dp). quantized_collectives opts the tp engine into the
    # EQuARX-idiom int8 shard re-assembly (approximate; exact GSPMD is
    # the default). devices: 0 = the whole backend, N = first N.
    # auto_hbm_bytes: 0 = probe device memory_stats.
    "zoo.serving.shard.mode": "off",
    "zoo.serving.shard.recipe": "transformer_tp",
    "zoo.serving.shard.quantized_collectives": False,
    "zoo.serving.shard.devices": 0,
    "zoo.serving.shard.auto_hbm_bytes": 0,
    "zoo.serving.shard.auto_hbm_fraction": 0.6,
    # chaos harness (serving/chaos.py): seeded, deterministic fault
    # injection behind the same seams the Supervisor watches; spec
    # grammar "kind:seam[:k=v]*;..." (see docs/serving.md)
    "zoo.serving.chaos.enabled": False,
    "zoo.serving.chaos.seed": 0,
    "zoo.serving.chaos.spec": "",
    # graceful drain (ISSUE-9): on SIGTERM (and each rolling-restart
    # step) the deployment stops pulling new work and finishes its
    # in-flight requests for up to this budget before exiting
    # (0 = the old stop-immediately behavior)
    "zoo.serving.drain.deadline_ms": 10000.0,
    # serving fleet (serving/fleet.py): N replica launcher processes
    # sharing one consumer-group stream, front-tier HTTP router, and
    # an optional metrics-driven autoscaler within
    # [min_replicas, max_replicas]
    "zoo.serving.fleet.replicas": 2,
    "zoo.serving.fleet.min_replicas": 1,
    "zoo.serving.fleet.max_replicas": 8,
    "zoo.serving.fleet.poll_interval_s": 0.5,
    "zoo.serving.fleet.health_interval_s": 1.0,
    # pending stream entries idle beyond this are reclaimable by any
    # surviving consumer (XAUTOCLAIM semantics): how long a SIGKILLed
    # replica's claimed-but-unanswered requests wait before another
    # replica re-serves them
    "zoo.serving.fleet.reclaim_idle_ms": 5000.0,
    "zoo.serving.fleet.router_retries": 1,
    "zoo.serving.fleet.autoscale.enabled": False,
    "zoo.serving.fleet.autoscale.backlog_high": 64,
    "zoo.serving.fleet.autoscale.backlog_low": 4,
    "zoo.serving.fleet.autoscale.p99_high_ms": 500.0,
    "zoo.serving.fleet.autoscale.up_consecutive": 3,
    "zoo.serving.fleet.autoscale.down_consecutive": 10,
    "zoo.serving.fleet.autoscale.cooldown_s": 10.0,
    # SLO-driven control (ISSUE-15): latency targets in ms (0 = that
    # target off). With slo.enabled the autoscaler scales on SLO
    # attainment -- worst observed service p99 vs p99_ms, generation
    # time-to-first-token p99 vs ttft_ms, inter-token gap p99 vs
    # inter_token_ms -- instead of raw backlog, and rolling_restart
    # refuses to take a replica down while the interactive class is
    # out of SLO
    "zoo.serving.slo.enabled": False,
    "zoo.serving.slo.p99_ms": 500.0,
    "zoo.serving.slo.ttft_ms": 0.0,
    "zoo.serving.slo.inter_token_ms": 0.0,
    # router unhealthy-replica re-probe (ISSUE-15): capped-exponential
    # + jittered schedule on which the controller re-probes a replica
    # the router marked unhealthy, so a recovered replica rejoins
    # rotation without waiting a full health sweep
    "zoo.serving.fleet.reprobe_base_s": 0.05,
    "zoo.serving.fleet.reprobe_max_s": 2.0,
    # replica spawn backend (ISSUE-15): local = subprocess.Popen on
    # this host (the historical behavior); manifest = no processes,
    # the controller records per-replica configs and emits
    # docker-compose / k8s YAML -- the multi-host seam; remote =
    # launch through a command-runner prefix (ssh/exec style, ISSUE-20)
    # so replicas run as separate containers/hosts
    "zoo.serving.fleet.spawn_backend": "local",
    # command-runner prefix for the remote spawn backend, e.g.
    # "ssh worker-3" or "docker exec zoo-fleet". Tokens are
    # whitespace-split and prepended to the replica argv; empty = run
    # the argv directly on this host (the degenerate remote target)
    "zoo.serving.fleet.remote_runner": "",
    # cross-host addressing (ISSUE-20): bind_host is the interface the
    # broker / router / replica HTTP frontends listen on (loopback by
    # default so single-host behavior is unchanged; 0.0.0.0 for
    # multi-host). advertise_host is the address OTHER hosts should
    # use to reach services bound on this host -- it rides the ready
    # file and broker_address instead of the bind address; empty =
    # advertise the bind address
    "zoo.serving.fleet.bind_host": "127.0.0.1",
    "zoo.serving.fleet.advertise_host": "",
    # broker liveness probe (ISSUE-20): a PING round trip replicas and
    # the router use for readiness, retried with capped exponential
    # backoff before a broker_unreachable event is emitted
    "zoo.serving.fleet.broker_probe_retries": 6,
    "zoo.serving.fleet.broker_probe_base_s": 0.05,
    "zoo.serving.fleet.broker_probe_max_s": 2.0,
    # disaggregated prefill/decode pools (ISSUE-20): when both are
    # > 0 the controller spawns role-typed replicas instead of
    # `replicas` unified ones -- prefill replicas admit + prefill and
    # hand streams (KV pages + slot state) to the decode pool over the
    # broker's handoff stream; each pool autoscales independently
    # within its [min, max]
    "zoo.serving.fleet.prefill_replicas": 0,
    "zoo.serving.fleet.decode_replicas": 0,
    "zoo.serving.fleet.prefill_min_replicas": 1,
    "zoo.serving.fleet.prefill_max_replicas": 8,
    "zoo.serving.fleet.decode_min_replicas": 1,
    "zoo.serving.fleet.decode_max_replicas": 8,
    # KV snapshots larger than this many bytes are dropped from the
    # handoff blob (the decode side then re-prefills
    # deterministically); 0 = always inline the snapshot
    "zoo.serving.fleet.handoff_max_bytes": 8388608,
    # generation serving (serving/generation, ISSUE-10): the decode
    # slot table size (concurrent streams per worker; ALSO the fixed
    # device batch of every decode step), the paged KV cache geometry
    # (page_size tokens per page; num_pages 0 = auto-size so every
    # slot can reach max_len), the per-request length bounds
    # (max_len = prompt + generated tokens a slot may span;
    # max_tokens = default new-token budget when the request omits
    # __max_tokens__), the idle poll interval of a decode loop with no
    # active slots, and how many tokens ride each streamed reply chunk
    "zoo.generation.slots": 8,
    "zoo.generation.page_size": 16,
    "zoo.generation.num_pages": 0,
    "zoo.generation.max_len": 256,
    "zoo.generation.max_tokens": 64,
    "zoo.generation.step_idle_ms": 5.0,
    "zoo.generation.stream_chunk_tokens": 1,
    # observability (analytics_zoo_tpu.obs): per-request tracing gate
    # (spans ride queue blobs as __trace__ and export as Chrome trace
    # JSON; off by default -- the disabled path must cost nothing),
    # span ring size, and the background rollup reporter cadence in
    # seconds (0 disables the thread)
    "zoo.obs.trace.enabled": False,
    "zoo.obs.trace.max_spans": 8192,
    "zoo.obs.report.interval": 0.0,
    # flight recorder (analytics_zoo_tpu.obs.flight / events): the
    # always-on structured event ring, the crash postmortem bundle
    # directory, and the recompile-storm detector (>= threshold
    # distinct shapes for one jitted fn inside window_s seconds ->
    # recompile_storm warning + zoo_obs_recompile_storms_total)
    "zoo.obs.events.max_events": 2048,
    "zoo.obs.flight.enabled": True,
    "zoo.obs.postmortem.dir": "~/.cache/analytics-zoo-tpu/postmortems",
    "zoo.obs.postmortem.max_events": 512,
    "zoo.obs.recompile.window_s": 60.0,
    "zoo.obs.recompile.threshold": 8,
    # vectorized population engine (learn/population.py, ISSUE-13):
    # hard cap on stacked member lanes in one PopulationEstimator (the
    # whole population is ONE executable; too many lanes silently
    # multiplies every buffer by N)
    "zoo.population.max_members": 1024,
    # vectorized AutoML executor (automl/vectorized.py): max lanes per
    # cohort (a larger sampled wave splits into several populations),
    # and whether a failed cohort falls back to answering its trials
    # through the sequential in-process path (False = surface the
    # cohort error on every member trial)
    "zoo.automl.vectorized.max_cohort": 64,
    "zoo.automl.vectorized.fallback": True,
    # per-tenant serving lanes (inference/population.py): the lane a
    # request without __tenant__ uses, unless strict, in which case
    # tenant-less requests to a population model are rejected with a
    # structured invalid-request error
    "zoo.serving.tenant.default_lane": 0,
    "zoo.serving.tenant.strict": False,
    # inference
    "zoo.inference.default_dtype": "bfloat16",
    # XLA persistent compilation cache (see common.context.
    # enable_compilation_cache); "" disables
    "zoo.compile_cache.dir": "~/.cache/analytics-zoo-tpu/xla-cache",
    "zoo.compile_cache.min_compile_secs": 2.0,
}

# Per-key type/range metadata (the glossary's machine-readable half,
# docs/runtime.md "Config-key glossary"). Shapes:
#
#   ("int", lo, hi)      integer; lo/hi are inclusive bounds, None =
#                        unbounded on that side
#   ("float", lo, hi)    float (an int literal is acceptable)
#   ("bool",)            strict boolean
#   ("str",)             free-form string
#   ("enum", a, b, ...)  one of the listed strings
#
# Consumed two ways: ``validate_config_value`` at runtime (opt-in;
# ``set()`` stays permissive so tests can probe edge values) and the
# zoolint ``config-type`` rule statically -- a ``get``/``set`` call
# site whose cast or literal default contradicts the declared
# type/range is a finding before it ships.
_SPECS: Dict[str, tuple] = {
    "zoo.train.failure.retry_times": ("int", 0, None),
    "zoo.train.failure.retry_interval_s": ("float", 0, None),
    "zoo.train.log_every_n_steps": ("int", 1, None),
    "zoo.train.donate_buffers": ("bool",),
    "zoo.train.prng_impl": ("str",),   # "auto"/"rbg"/"threefry2x32"/
                                       # any jax.random.key impl name
    "zoo.mesh.axis.data": ("str",),
    "zoo.mesh.axis.model": ("str",),
    "zoo.mesh.axis.sequence": ("str",),
    "zoo.mesh.axis.pipeline": ("str",),
    "zoo.mesh.axis.expert": ("str",),
    "zoo.ops.attention_impl": ("enum", "auto", "flash", "einsum"),
    "zoo.ops.attention_flash_min_seq": ("int", 0, None),
    "zoo.ops.ring_schedule": ("enum", "auto", "zigzag", "contiguous"),
    "zoo.models.bn_stat_rows": ("int", 0, None),
    "zoo.data.prefetch_buffer": ("int", 0, None),
    "zoo.data.check_batch_divisible": ("bool",),
    "zoo.serving.batch_size": ("int", 1, None),
    "zoo.serving.batch_timeout_ms": ("float", 0, None),
    "zoo.serving.batch_timeout_min_ms": ("float", 0, None),
    "zoo.serving.batch_max_size": ("int", 0, None),
    "zoo.serving.pipeline.enabled": ("bool",),
    "zoo.serving.pipeline.depth": ("int", 1, None),
    "zoo.serving.http_port": ("int", 0, 65535),
    "zoo.serving.supervisor.enabled": ("bool",),
    "zoo.serving.supervisor.poll_interval_s": ("float", 0, None),
    "zoo.serving.supervisor.heartbeat_timeout_s": ("float", 0, None),
    "zoo.serving.supervisor.backoff_base_s": ("float", 0, None),
    "zoo.serving.supervisor.backoff_max_s": ("float", 0, None),
    "zoo.serving.supervisor.max_restarts": ("int", 0, None),
    "zoo.serving.breaker.enabled": ("bool",),
    "zoo.serving.breaker.threshold": ("int", 1, None),
    "zoo.serving.breaker.cooldown_s": ("float", 0, None),
    "zoo.serving.deadline_ms": ("float", 0, None),
    "zoo.serving.shed.queue_depth": ("int", 0, None),
    "zoo.serving.shed.retry_after_s": ("float", 0, None),
    "zoo.serving.shed.batch_fraction": ("float", 0, 1),
    "zoo.serving.shed.background_fraction": ("float", 0, 1),
    "zoo.serving.shed.retry_after_max_s": ("float", 0, None),
    "zoo.serving.shed.ewma_alpha": ("float", 0, 1),
    "zoo.serving.shed.gen_cost_tokens": ("int", 1, None),
    "zoo.serving.priority.default_class": ("enum", "interactive",
                                           "batch", "background"),
    "zoo.serving.shard.mode": ("enum", "off", "tp", "dp", "auto"),
    "zoo.serving.shard.recipe": ("enum", "transformer_tp",
                                 "embedding_tp"),
    "zoo.serving.shard.quantized_collectives": ("bool",),
    "zoo.serving.shard.devices": ("int", 0, None),
    "zoo.serving.shard.auto_hbm_bytes": ("int", 0, None),
    "zoo.serving.shard.auto_hbm_fraction": ("float", 0, 1),
    "zoo.serving.chaos.enabled": ("bool",),
    "zoo.serving.chaos.seed": ("int", None, None),
    "zoo.serving.chaos.spec": ("str",),
    "zoo.serving.drain.deadline_ms": ("float", 0, None),
    "zoo.serving.fleet.replicas": ("int", 1, None),
    "zoo.serving.fleet.min_replicas": ("int", 1, None),
    "zoo.serving.fleet.max_replicas": ("int", 1, None),
    "zoo.serving.fleet.poll_interval_s": ("float", 0, None),
    "zoo.serving.fleet.health_interval_s": ("float", 0, None),
    "zoo.serving.fleet.reclaim_idle_ms": ("float", 0, None),
    "zoo.serving.fleet.router_retries": ("int", 0, None),
    "zoo.serving.fleet.autoscale.enabled": ("bool",),
    "zoo.serving.fleet.autoscale.backlog_high": ("int", 1, None),
    "zoo.serving.fleet.autoscale.backlog_low": ("int", 0, None),
    "zoo.serving.fleet.autoscale.p99_high_ms": ("float", 0, None),
    "zoo.serving.fleet.autoscale.up_consecutive": ("int", 1, None),
    "zoo.serving.fleet.autoscale.down_consecutive": ("int", 1, None),
    "zoo.serving.fleet.autoscale.cooldown_s": ("float", 0, None),
    "zoo.serving.slo.enabled": ("bool",),
    "zoo.serving.slo.p99_ms": ("float", 0, None),
    "zoo.serving.slo.ttft_ms": ("float", 0, None),
    "zoo.serving.slo.inter_token_ms": ("float", 0, None),
    "zoo.serving.fleet.reprobe_base_s": ("float", 0, None),
    "zoo.serving.fleet.reprobe_max_s": ("float", 0, None),
    "zoo.serving.fleet.spawn_backend": ("enum", "local", "manifest",
                                        "remote"),
    "zoo.serving.fleet.remote_runner": ("str",),
    "zoo.serving.fleet.bind_host": ("str",),
    "zoo.serving.fleet.advertise_host": ("str",),
    "zoo.serving.fleet.broker_probe_retries": ("int", 0, None),
    "zoo.serving.fleet.broker_probe_base_s": ("float", 0, None),
    "zoo.serving.fleet.broker_probe_max_s": ("float", 0, None),
    "zoo.serving.fleet.prefill_replicas": ("int", 0, None),
    "zoo.serving.fleet.decode_replicas": ("int", 0, None),
    "zoo.serving.fleet.prefill_min_replicas": ("int", 1, None),
    "zoo.serving.fleet.prefill_max_replicas": ("int", 1, None),
    "zoo.serving.fleet.decode_min_replicas": ("int", 1, None),
    "zoo.serving.fleet.decode_max_replicas": ("int", 1, None),
    "zoo.serving.fleet.handoff_max_bytes": ("int", 0, None),
    "zoo.generation.slots": ("int", 1, None),
    "zoo.generation.page_size": ("int", 1, None),
    "zoo.generation.num_pages": ("int", 0, None),
    "zoo.generation.max_len": ("int", 2, None),
    "zoo.generation.max_tokens": ("int", 1, None),
    "zoo.generation.step_idle_ms": ("float", 0, None),
    "zoo.generation.stream_chunk_tokens": ("int", 1, None),
    "zoo.population.max_members": ("int", 1, None),
    "zoo.automl.vectorized.max_cohort": ("int", 1, None),
    "zoo.automl.vectorized.fallback": ("bool",),
    "zoo.serving.tenant.default_lane": ("int", 0, None),
    "zoo.serving.tenant.strict": ("bool",),
    "zoo.obs.trace.enabled": ("bool",),
    "zoo.obs.trace.max_spans": ("int", 1, None),
    "zoo.obs.report.interval": ("float", 0, None),
    "zoo.obs.events.max_events": ("int", 1, None),
    "zoo.obs.flight.enabled": ("bool",),
    "zoo.obs.postmortem.dir": ("str",),
    "zoo.obs.postmortem.max_events": ("int", 1, None),
    "zoo.obs.recompile.window_s": ("float", 0, None),
    "zoo.obs.recompile.threshold": ("int", 1, None),
    "zoo.inference.default_dtype": ("str",),
    "zoo.compile_cache.dir": ("str",),
    "zoo.compile_cache.min_compile_secs": ("float", 0, None),
}


def config_spec(key: str) -> Optional[tuple]:
    """The declared (type, *constraints) spec for ``key``, or None."""
    return _SPECS.get(key)


def spec_violation(spec: tuple, value: Any) -> Optional[str]:
    """Why ``value`` violates ``spec``, or None when it satisfies it.

    THE single implementation of the spec semantics: the runtime
    validators below and zoolint's ``config-type`` rule both call
    this, so lint and launch-time validation cannot drift apart."""
    kind = spec[0]
    if kind == "bool":
        if not isinstance(value, bool):
            return f"wants bool, got {value!r}"
    elif kind in ("int", "float"):
        ok_types = (int,) if kind == "int" else (int, float)
        if isinstance(value, bool) or not isinstance(value, ok_types):
            return f"wants {kind}, got {value!r}"
        lo = spec[1] if len(spec) > 1 else None
        hi = spec[2] if len(spec) > 2 else None
        if lo is not None and value < lo:
            return f"wants >= {lo}, got {value!r}"
        if hi is not None and value > hi:
            return f"wants <= {hi}, got {value!r}"
    elif kind == "str":
        if not isinstance(value, str):
            return f"wants str, got {value!r}"
    elif kind == "enum":
        if value not in spec[1:]:
            return f"wants one of {spec[1:]}, got {value!r}"
    return None


def validate_config_value(key: str, value: Any) -> Any:
    """Check ``value`` against the key's declared spec; returns the
    value unchanged, raising ValueError on a violation. Keys without
    a spec pass through (unknown keys are ``config-undeclared``'s
    business, not this helper's)."""
    spec = _SPECS.get(key)
    if spec is not None:
        why = spec_violation(spec, value)
        if why:
            raise ValueError(f"{key} {why}")
    return value


def validate_config(config: Optional["ZooConfig"] = None) -> None:
    """Validate every spec'd key's *resolved* value (defaults + file +
    env + overrides). Call at launch to fail fast on a bad conf file
    or AZT_* env var instead of mid-serve."""
    cfg = config if config is not None else get_config()
    for key in _SPECS:
        validate_config_value(key, cfg.get(key))


_ENV_PREFIX = "AZT_"


def _coerce(value: str) -> Any:
    low = value.strip()
    if low.lower() in ("true", "false"):
        return low.lower() == "true"
    for conv in (int, float):
        try:
            return conv(low)
        except ValueError:
            pass
    return low


class ZooConfig:
    """Thread-safe layered key/value config."""

    def __init__(self, conf_file: Optional[str] = None):
        self._lock = threading.Lock()
        self._overrides: Dict[str, Any] = {}
        self._file_layer: Dict[str, Any] = {}
        if conf_file is None:
            conf_file = os.environ.get("AZT_CONF_FILE", "analytics-zoo-tpu.conf")
        if conf_file and os.path.isfile(conf_file):
            self._file_layer = self._parse_conf_file(conf_file)

    @staticmethod
    def _parse_conf_file(path: str) -> Dict[str, Any]:
        layer: Dict[str, Any] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(None, 1)
                if len(parts) == 2:
                    layer[parts[0]] = _coerce(parts[1])
        return layer

    def _env_lookup(self, key: str) -> Optional[str]:
        env_key = _ENV_PREFIX + key.replace(".", "_").upper()
        return os.environ.get(env_key)

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            if key in self._overrides:
                return self._overrides[key]
        env_val = self._env_lookup(key)
        if env_val is not None:
            return _coerce(env_val)
        if key in self._file_layer:
            return self._file_layer[key]
        return _DEFAULTS.get(key, default)

    def set(self, key: str, value: Any) -> "ZooConfig":
        with self._lock:
            self._overrides[key] = value
        return self

    def unset(self, key: str) -> "ZooConfig":
        with self._lock:
            self._overrides.pop(key, None)
        return self

    def as_dict(self) -> Dict[str, Any]:
        merged = dict(_DEFAULTS)
        merged.update(self._file_layer)
        # env-only keys: AZT_FOO_BAR -> foo.bar (lossy for keys whose
        # canonical form contains underscores; get() remains authoritative)
        for env_key, env_val in os.environ.items():
            if env_key.startswith(_ENV_PREFIX) and env_key != "AZT_CONF_FILE":
                key = env_key[len(_ENV_PREFIX):].lower().replace("_", ".")
                if key not in merged:
                    merged[key] = _coerce(env_val)
        for key in list(merged):
            env_val = self._env_lookup(key)
            if env_val is not None:
                merged[key] = _coerce(env_val)
        with self._lock:
            merged.update(self._overrides)
        return merged


_global_config: Optional[ZooConfig] = None
_config_lock = threading.Lock()


def get_config() -> ZooConfig:
    global _global_config
    with _config_lock:
        if _global_config is None:
            _global_config = ZooConfig()
        return _global_config


def reset_config() -> None:
    global _global_config
    with _config_lock:
        _global_config = None
