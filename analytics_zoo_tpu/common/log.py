"""Logging + lightweight timing instrumentation.

Timing helpers mirror the reference's ``Supportive.timing(name){...}``
(ref: zoo/.../serving/utils/Supportive.scala:22) and ``EstimateSupportive``
wrappers; per-stage stats mirror the serving ``Timer``
(ref: zoo/.../serving/engine/Timer.scala:24-90: total/avg/max/min/topN).
"""

from __future__ import annotations

import contextlib
import logging
import sys
import threading
import time
from typing import Dict, List, Optional

from analytics_zoo_tpu.obs.metrics import StatCore

_LOG_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"
_configured = False
_lock = threading.Lock()


def get_logger(name: str = "analytics_zoo_tpu") -> logging.Logger:
    global _configured
    with _lock:
        if not _configured:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(logging.Formatter(_LOG_FORMAT))
            root = logging.getLogger("analytics_zoo_tpu")
            if not root.handlers:
                root.addHandler(handler)
            root.setLevel(logging.INFO)
            root.propagate = False
            _configured = True
    return logging.getLogger(name)


class TimerStat:
    """Accumulated stats for one named stage (count/total/avg/max/min/
    top-k) -- a thin shim over :class:`analytics_zoo_tpu.obs.metrics.
    StatCore`, the single stat-math implementation shared with the
    serving Timer and the registry histograms (ISSUE-2 dedup)."""

    __slots__ = ("name", "_core")

    def __init__(self, name: str, k: int = 10):
        self.name = name
        self._core = StatCore(top_k=k)

    def record(self, elapsed: float) -> None:
        self._core.observe(elapsed)

    @property
    def count(self) -> int:
        return self._core.count

    @property
    def total(self) -> float:
        return self._core.total

    @property
    def max(self) -> float:
        return self._core.max

    @property
    def min(self) -> float:
        return self._core.min

    @property
    def avg(self) -> float:
        return self._core.avg

    def top(self, n: int = 10) -> List[float]:
        return self._core.top(n)

    def summary(self) -> str:
        return (
            f"[{self.name}] count={self.count} total={self.total:.4f}s "
            f"avg={self.avg * 1e3:.2f}ms max={self.max * 1e3:.2f}ms "
            f"min={(0.0 if self.min == float('inf') else self.min) * 1e3:.2f}ms"
        )


class Timer:
    """Named-stage timer registry; thread-safe. ``mirror`` (an obs
    registry histogram family labelled by ``stage``) additionally
    publishes every recorded duration process-wide -- how training
    stage timers join the same ``/metrics`` scrape as serving."""

    def __init__(self, mirror=None):
        self._stats: Dict[str, TimerStat] = {}
        self._lock = threading.Lock()
        self._mirror = mirror

    @contextlib.contextmanager
    def timing(self, name: str, log: Optional[logging.Logger] = None):
        start = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - start
            with self._lock:
                stat = self._stats.setdefault(name, TimerStat(name))
                stat.record(elapsed)
            if self._mirror is not None:
                self._mirror.labels(stage=name).observe(elapsed)
            if log is not None:
                log.info("%s took %.2f ms", name, elapsed * 1e3)

    def stat(self, name: str) -> Optional[TimerStat]:
        with self._lock:
            return self._stats.get(name)

    def stats(self) -> Dict[str, TimerStat]:
        with self._lock:
            return dict(self._stats)

    def summaries(self) -> List[str]:
        with self._lock:
            return [s.summary() for s in self._stats.values()]

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


GLOBAL_TIMER = Timer()
timing = GLOBAL_TIMER.timing
