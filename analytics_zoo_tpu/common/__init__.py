from analytics_zoo_tpu.common.config import ZooConfig, get_config
from analytics_zoo_tpu.common.context import (
    ZooContext,
    init_zoo_context,
    init_orca_context,
    stop_orca_context,
)
from analytics_zoo_tpu.common.triggers import (
    Trigger,
    TriggerState,
    EveryEpoch,
    SeveralIteration,
    MaxEpoch,
    MaxIteration,
    MaxScore,
    MinLoss,
    TimeLimit,
    And,
    Or,
)

__all__ = [
    "ZooConfig",
    "get_config",
    "ZooContext",
    "init_zoo_context",
    "init_orca_context",
    "stop_orca_context",
    "Trigger",
    "TriggerState",
    "EveryEpoch",
    "SeveralIteration",
    "MaxEpoch",
    "MaxIteration",
    "MaxScore",
    "MinLoss",
    "TimeLimit",
    "And",
    "Or",
]
