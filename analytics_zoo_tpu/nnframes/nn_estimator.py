"""NNEstimator / NNModel / NNClassifier over pandas DataFrames.

The Spark-ML Estimator/Transformer contract re-hosted on pandas
(ref: zoo/src/main/scala/com/intel/analytics/zoo/pipeline/nnframes/NNEstimator.scala:198-505
``internalFit`` builds a FeatureSet from DataFrame rows through
Preprocessing chains and runs InternalDistriOptimizer; ``NNModel``
broadcasts the model for ``transform`` :628-750; classifier sugar in
NNClassifier.scala and pyzoo .../nnframes/nn_classifier.py:140-620).

TPU-first collapse: rows -> numpy via the Preprocessing chain once, then
one jitted SPMD ``learn.Estimator`` step trains over the mesh; transform
is a sharded ``predict`` appended back as a DataFrame column.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import numpy as np

from analytics_zoo_tpu.common.triggers import EveryEpoch, Trigger
from analytics_zoo_tpu.nnframes.preprocessing import (
    FeatureLabelPreprocessing, Preprocessing)

ColSpec = Union[str, Sequence[str]]


def _extract(df, cols: ColSpec, chain: Optional[Preprocessing],
             dtype=None):
    """DataFrame columns -> stacked ndarray (or tuple for multi-input)."""

    def one(col):
        values = df[col].tolist()
        if chain is not None:
            return chain.apply_column(values)
        arr = np.asarray(
            [np.asarray(v) for v in values])
        return arr.astype(dtype) if dtype is not None else arr

    if isinstance(cols, str):
        return one(cols)
    out = tuple(one(c) for c in cols)
    return out[0] if len(out) == 1 else out


class NNEstimator:
    """``fit(df) -> NNModel`` (ref: NNEstimator.scala:198-505).

    Args:
      model: a KerasNet (``keras.Sequential``/``Model``) or a flax module.
      criterion: loss name or ``fn(preds, labels)``.
      feature_preprocessing / label_preprocessing: per-row
        ``Preprocessing`` chains, or one ``FeatureLabelPreprocessing``
        passed as ``feature_preprocessing``.
    """

    def __init__(self, model, criterion="mse",
                 feature_preprocessing: Optional[Preprocessing] = None,
                 label_preprocessing: Optional[Preprocessing] = None):
        if isinstance(feature_preprocessing, FeatureLabelPreprocessing):
            label_preprocessing = feature_preprocessing.label_preprocessing
            feature_preprocessing = \
                feature_preprocessing.feature_preprocessing
        self.model = model
        self.criterion = criterion
        self.feature_preprocessing = feature_preprocessing
        self.label_preprocessing = label_preprocessing
        self.features_col: ColSpec = "features"
        self.label_col = "label"
        self.prediction_col = "prediction"
        self.batch_size = 32
        self.max_epoch = 10
        self.optim_method: Any = "adam"
        self.clip_norm: Optional[float] = None
        self.clip_value: Optional[float] = None
        self.validation_df = None
        self.validation_trigger: Optional[Trigger] = None
        self.validation_batch_size: Optional[int] = None
        self.checkpoint_path: Optional[str] = None
        self.checkpoint_trigger: Optional[Trigger] = None
        self.log_dir: Optional[str] = None
        self._label_dtype = None

    # fluent setters (reference camelCase API parity,
    # nn_classifier.py:229-443)
    def setFeaturesCol(self, col: ColSpec):
        self.features_col = col
        return self

    def setLabelCol(self, col: str):
        self.label_col = col
        return self

    def setPredictionCol(self, col: str):
        self.prediction_col = col
        return self

    def setBatchSize(self, v: int):
        self.batch_size = int(v)
        return self

    def setMaxEpoch(self, v: int):
        self.max_epoch = int(v)
        return self

    def setLearningRate(self, lr: float):
        from analytics_zoo_tpu.learn.optim import Adam

        self.optim_method = Adam(lr=lr)
        return self

    def setOptimMethod(self, method):
        self.optim_method = method
        return self

    def setGradientClippingByL2Norm(self, clip_norm: float):
        self.clip_norm = float(clip_norm)
        return self

    def setConstantGradientClipping(self, min_v: float, max_v: float):
        if abs(min_v) != abs(max_v):
            raise ValueError("constant clipping is symmetric: pass "
                             "(-v, v)")
        self.clip_value = float(max_v)
        return self

    def clearGradientClipping(self):
        self.clip_norm = self.clip_value = None
        return self

    def setValidation(self, trigger: Trigger, val_df,
                      batch_size: Optional[int] = None):
        self.validation_trigger = trigger
        self.validation_df = val_df
        self.validation_batch_size = batch_size
        return self

    def setCheckpoint(self, path: str, trigger: Optional[Trigger] = None):
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger or EveryEpoch()
        return self

    def setTrainSummary(self, log_dir: str):
        self.log_dir = log_dir
        return self

    # --------------------------------------------------------------- fit --
    def _module(self):
        return (self.model.module if hasattr(self.model, "module")
                else self.model)

    def _make_estimator(self):
        from analytics_zoo_tpu.learn.estimator import Estimator

        return Estimator(self._module(), loss=self.criterion,
                         optimizer=self.optim_method,
                         clip_norm=self.clip_norm,
                         clip_value=self.clip_value)

    def _dataset(self, df):
        x = _extract(df, self.features_col, self.feature_preprocessing,
                     np.float32)
        y = _extract(df, self.label_col, self.label_preprocessing,
                     self._label_dtype or np.float32)
        return x, y

    def fit(self, df) -> "NNModel":
        estimator = self._make_estimator()
        x, y = self._dataset(df)
        val = (self._dataset(self.validation_df)
               if self.validation_df is not None else None)
        estimator.fit(
            (x, y), batch_size=self.batch_size, epochs=self.max_epoch,
            validation_data=val,
            validation_trigger=self.validation_trigger,
            checkpoint_dir=self.checkpoint_path,
            checkpoint_trigger=self.checkpoint_trigger,
            log_dir=self.log_dir)
        return self._create_model(estimator)

    def _create_model(self, estimator) -> "NNModel":
        return NNModel(self.model, estimator=estimator,
                       feature_preprocessing=self.feature_preprocessing,
                       features_col=self.features_col,
                       prediction_col=self.prediction_col,
                       batch_size=self.batch_size)


class NNModel:
    """DataFrame transformer carrying a trained model
    (ref: NNModel, NNEstimator.scala:628-750)."""

    def __init__(self, model, estimator=None,
                 feature_preprocessing: Optional[Preprocessing] = None,
                 features_col: ColSpec = "features",
                 prediction_col: str = "prediction",
                 batch_size: int = 32):
        from analytics_zoo_tpu.learn.estimator import Estimator

        self.model = model
        module = (model.module if hasattr(model, "module") else model)
        self.estimator = estimator or Estimator(module)
        self.feature_preprocessing = feature_preprocessing
        self.features_col = features_col
        self.prediction_col = prediction_col
        self.batch_size = batch_size

    def setFeaturesCol(self, col: ColSpec):
        self.features_col = col
        return self

    def setPredictionCol(self, col: str):
        self.prediction_col = col
        return self

    def setBatchSize(self, v: int):
        self.batch_size = int(v)
        return self

    def _predict_array(self, df) -> np.ndarray:
        x = _extract(df, self.features_col, self.feature_preprocessing,
                     np.float32)
        return np.asarray(
            self.estimator.predict(x, batch_size=self.batch_size))

    def _post(self, preds: np.ndarray) -> List[Any]:
        # [N] rows stay scalar; [N, ...] rows become per-row arrays --
        # the pandas analog of Spark's Vector prediction column
        if preds.ndim == 1:
            return list(preds)
        return [row for row in preds]

    def transform(self, df):
        out = df.copy()
        out[self.prediction_col] = self._post(self._predict_array(df))
        return out

    def save(self, ckpt_dir: str) -> None:
        self.estimator.save(ckpt_dir)

    def load_weights(self, ckpt_dir: str) -> "NNModel":
        self.estimator.load(ckpt_dir)
        return self


class NNClassifier(NNEstimator):
    """Classification sugar: integer label column, cross-entropy default
    (ref: NNClassifier.scala; nn_classifier.py:543-589)."""

    def __init__(self, model, criterion="sparse_categorical_crossentropy",
                 feature_preprocessing: Optional[Preprocessing] = None):
        super().__init__(model, criterion=criterion,
                         feature_preprocessing=feature_preprocessing)
        self._label_dtype = np.int32

    def _create_model(self, estimator) -> "NNClassifierModel":
        return NNClassifierModel(
            self.model, estimator=estimator,
            feature_preprocessing=self.feature_preprocessing,
            features_col=self.features_col,
            prediction_col=self.prediction_col,
            batch_size=self.batch_size)


class NNClassifierModel(NNModel):
    """Transformer emitting argmax class ids
    (ref: NNClassifierModel, nn_classifier.py:590-614)."""

    def _post(self, preds: np.ndarray) -> List[Any]:
        # single-output (sigmoid/probability) models -> 0.5 threshold,
        # the reference's HasThreshold default (nn_classifier.py:107-139);
        # multi-output -> argmax class id
        if preds.ndim == 2 and preds.shape[-1] == 1:
            preds = preds[:, 0]
        if preds.ndim == 1:
            return list((preds > 0.5).astype(np.int64))
        return list(np.argmax(preds, axis=-1).astype(np.int64))
