"""NNFrames: DataFrame-native fit/transform pipeline API.

The analog of the reference's Spark-ML integration
(ref: zoo/src/main/scala/com/intel/analytics/zoo/pipeline/nnframes/NNEstimator.scala:198-505,
NNModel :628-750, NNClassifier.scala; python surface
pyzoo/zoo/pipeline/nnframes/nn_classifier.py:140-620). Spark DataFrames
become pandas DataFrames; the Spark-ML Estimator/Transformer contract
(``fit(df) -> model``, ``model.transform(df) -> df``) is preserved, and
training funnels into the one SPMD ``learn.Estimator`` instead of
InternalDistriOptimizer.
"""

from analytics_zoo_tpu.nnframes.preprocessing import (
    ArrayToTensor, ChainedPreprocessing, FeatureLabelPreprocessing,
    Preprocessing, ScalarToTensor, SeqToTensor, TensorToSample)
from analytics_zoo_tpu.nnframes.nn_estimator import (
    NNClassifier, NNClassifierModel, NNEstimator, NNModel)

__all__ = [
    "Preprocessing", "ChainedPreprocessing", "ScalarToTensor",
    "SeqToTensor", "ArrayToTensor", "FeatureLabelPreprocessing",
    "TensorToSample", "NNEstimator", "NNModel", "NNClassifier",
    "NNClassifierModel",
]
