"""XGBoost DataFrame helpers (ref: zoo/src/main/scala/com/intel/
analytics/zoo/pipeline/nnframes/XGBoostHelper.scala -- the reference
wraps xgboost4j-spark's XGBoostClassifier/Regressor into the NNFrames
Estimator/Transformer pattern; here the same fit(df) -> model ->
transform(df) surface runs on the real ``xgboost`` package when
importable, else on the framework GBT engine).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Union

import numpy as np

from analytics_zoo_tpu.ml.gbt import (
    GBTClassifier, GBTRegressor, GradientBoostedTrees)

ColSpec = Union[str, Sequence[str]]


def _have_xgboost() -> bool:
    try:
        import xgboost  # noqa: F401

        return True
    except ImportError:
        return False


def _features(df, cols: ColSpec) -> np.ndarray:
    names = [cols] if isinstance(cols, str) else list(cols)
    parts = []
    for c in names:
        arr = np.asarray([np.asarray(v, np.float32).reshape(-1)
                          for v in df[c].tolist()])
        parts.append(arr)
    return np.concatenate(parts, axis=1).astype(np.float32)


class _XGBEstimatorBase:
    _classifier = False

    def __init__(self, **params):
        self.params = params
        self.features_col: ColSpec = "features"
        self.label_col = "label"
        self.prediction_col = "prediction"

    def setFeaturesCol(self, col: ColSpec):
        self.features_col = col
        return self

    def setLabelCol(self, col: str):
        self.label_col = col
        return self

    def setPredictionCol(self, col: str):
        self.prediction_col = col
        return self

    def setNthread(self, n: int):  # API parity; engine is in-process
        return self

    def fit(self, df) -> "XGBModel":
        x = _features(df, self.features_col)
        y = np.asarray(df[self.label_col].tolist())
        if _have_xgboost():
            from xgboost.sklearn import XGBClassifier as _RealC
            from xgboost.sklearn import XGBRegressor as _RealR

            if self._classifier:
                model = _RealC(**self.params)
                model.fit(x, y.astype(np.int64))
            else:
                model = _RealR(**self.params)
                model.fit(x, y.astype(np.float32))
        elif self._classifier:
            num_class = int(y.max()) + 1
            model = GBTClassifier(num_class=num_class, **self.params)
            model.fit(x, y.astype(np.int64))
        else:
            model = GBTRegressor(**self.params)
            model.fit(x, y.astype(np.float32))
        return XGBModel(model, features_col=self.features_col,
                        prediction_col=self.prediction_col)


class XGBClassifier(_XGBEstimatorBase):
    """(ref: XGBoostHelper XGBClassifier wrapper)."""

    _classifier = True


class XGBRegressor(_XGBEstimatorBase):
    """(ref: XGBoostHelper XGBRegressor wrapper)."""

    _classifier = False


class XGBModel:
    """Transformer: adds ``prediction_col`` (ref: XGBClassifierModel /
    XGBRegressorModel transform). ``model`` is either a real xgboost
    sklearn model or a framework :class:`GradientBoostedTrees`; both
    expose predict/predict_proba."""

    def __init__(self, model,
                 features_col: ColSpec = "features",
                 prediction_col: str = "prediction"):
        self.model = model
        self.features_col = features_col
        self.prediction_col = prediction_col

    def setFeaturesCol(self, col: ColSpec):
        self.features_col = col
        return self

    def setPredictionCol(self, col: str):
        self.prediction_col = col
        return self

    def transform(self, df):
        x = _features(df, self.features_col)
        out = df.copy()
        out[self.prediction_col] = list(np.asarray(
            self.model.predict(x)).reshape(-1))
        return out

    def predict_proba(self, df) -> np.ndarray:
        return self.model.predict_proba(_features(df, self.features_col))

    # ----------------------------------------------------- persistence --
    def save(self, path: str) -> None:
        if isinstance(self.model, GradientBoostedTrees):
            p = path if path.endswith(".json") \
                else os.path.join(path, "gbt.json")
            self.model.save(p)
        else:  # real xgboost model
            p = path if path.endswith(".json") \
                else os.path.join(path, "xgb.json")
            os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
            self.model.save_model(p)

    @classmethod
    def load(cls, path: str, features_col: ColSpec = "features",
             prediction_col: str = "prediction") -> "XGBModel":
        import json
        import re

        if os.path.isdir(path):
            xgb_p = os.path.join(path, "xgb.json")
            p = xgb_p if os.path.exists(xgb_p) \
                else os.path.join(path, "gbt.json")
        else:
            p = path
        # dispatch on CONTENT, not filename: the framework format
        # carries a top-level "meta" section, the xgboost format a
        # "learner". Sniff the leading bytes only -- a large tree
        # ensemble should not be JSON-parsed twice just to dispatch.
        with open(p) as f:
            head = f.read(4096)
        hits = {k: m.start() for k, m in
                ((k, re.search(f'"{k}"', head)) for k in
                 ("meta", "learner")) if m}
        if hits.get("meta", 1 << 30) < hits.get("learner", 1 << 30):
            model = GradientBoostedTrees.load(p)
        else:
            try:
                from xgboost.sklearn import XGBClassifier as _RealC
                from xgboost.sklearn import XGBRegressor as _RealR
            except ImportError as e:
                raise ImportError(
                    f"checkpoint {p!r} was saved with the real xgboost "
                    "library (its JSON carries a 'learner' section), "
                    "which is not installed in this environment -- "
                    "install xgboost to load it, or re-train with the "
                    "built-in GradientBoostedTrees backend") from e

            m = re.search(r'"name"\s*:\s*"((?:multi|binary):[^"]*)"',
                          head)
            if m is None:  # objective may sit past the sniffed prefix
                with open(p) as f:
                    objective = (json.load(f).get("learner", {})
                                 .get("objective", {}).get("name", ""))
            else:
                objective = m.group(1)
            classifier = objective.startswith(("multi:", "binary:"))
            model = _RealC() if classifier else _RealR()
            model.load_model(p)
        return cls(model, features_col=features_col,
                   prediction_col=prediction_col)
