"""Composable per-row Preprocessing chains.

The analog of the reference's ``Preprocessing[A, B]`` transformer algebra
(ref: zoo/src/main/scala/com/intel/analytics/zoo/feature/common/Preprocessing.scala;
python wrappers pyzoo/zoo/feature/common.py:94-238): small pure functions
over one row's value, composed with ``>>`` (the reference's ``->``), and
vectorized over a DataFrame column by ``apply_column``. The terminal
to-Sample/to-MiniBatch stages of the reference collapse away -- chains
here produce numpy rows that ``ZooDataset`` batches and shards.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import numpy as np


class Preprocessing:
    """One per-row transform step; compose with ``a >> b``."""

    def apply(self, value: Any) -> Any:
        raise NotImplementedError

    def __call__(self, value: Any) -> Any:
        return self.apply(value)

    def __rshift__(self, other: "Preprocessing") -> "ChainedPreprocessing":
        return ChainedPreprocessing([self, other])

    def apply_column(self, column: Sequence[Any]) -> np.ndarray:
        """Apply to every row of a column and stack to [N, ...]."""
        rows = [np.asarray(self.apply(v)) for v in column]
        return np.stack(rows)


class ChainedPreprocessing(Preprocessing):
    """Left-to-right composition (ref: ChainedPreprocessing,
    feature/common.py:122-134)."""

    def __init__(self, stages: Sequence[Preprocessing]):
        flat = []
        for s in stages:
            if not isinstance(s, Preprocessing):
                raise TypeError(f"{s!r} is not a Preprocessing")
            if isinstance(s, ChainedPreprocessing):
                flat.extend(s.stages)
            else:
                flat.append(s)
        self.stages = flat

    def apply(self, value: Any) -> Any:
        for s in self.stages:
            value = s.apply(value)
        return value


class Lambda(Preprocessing):
    """Wrap an arbitrary per-row function into the chain algebra."""

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def apply(self, value: Any) -> Any:
        return self.fn(value)


class ScalarToTensor(Preprocessing):
    """Python/numpy scalar -> float32 scalar array (ref: ScalarToTensor,
    feature/common.py:136-144)."""

    def __init__(self, dtype: str = "float32"):
        self.dtype = np.dtype(dtype)

    def apply(self, value: Any):
        return np.asarray(value, self.dtype)


class SeqToTensor(Preprocessing):
    """Sequence/array -> array, optionally reshaped to ``size``
    (ref: SeqToTensor, feature/common.py:145-154)."""

    def __init__(self, size: Optional[Sequence[int]] = None,
                 dtype: str = "float32"):
        self.size = tuple(size) if size is not None else None
        self.dtype = np.dtype(dtype)

    def apply(self, value: Any):
        arr = np.asarray(value, self.dtype)
        if self.size is not None:
            arr = arr.reshape(self.size)
        return arr


class ArrayToTensor(SeqToTensor):
    """Alias of SeqToTensor for numpy-array columns (ref: ArrayToTensor,
    feature/common.py:165-174)."""


class TensorToSample(Preprocessing):
    """Identity terminal stage kept for reference API parity
    (ref: TensorToSample, feature/common.py:200-208): samples here are
    just numpy rows."""

    def apply(self, value: Any):
        return value


class FeatureLabelPreprocessing(Preprocessing):
    """Pairs a feature chain with a label chain over (feature, label)
    rows (ref: FeatureLabelPreprocessing, feature/common.py:186-199)."""

    def __init__(self, feature_preprocessing: Preprocessing,
                 label_preprocessing: Preprocessing):
        self.feature_preprocessing = feature_preprocessing
        self.label_preprocessing = label_preprocessing

    def apply(self, value: Any):
        feature, label = value
        return (self.feature_preprocessing.apply(feature),
                self.label_preprocessing.apply(label))
