"""Serving wire-protocol vocabulary: ONE declaring module.

Everything that crosses the serving wire as an out-of-band *name* --
reserved blob keys and structured error-reply prefixes -- is declared
here and imported everywhere else. A hand-typed copy elsewhere in
``serving/`` is a zoolint finding (``analysis/protocol.py``): a typo'd
key silently drops a deadline on the floor and a prefix the frontend
cannot map turns a structured rejection into a generic 500, and both
only surface under load.

Reserved wire keys (AZT1/npz blob tensor names; see
``queues._encode``):

- ``__uri__``       request id, the reply-correlation key
- ``__reply__``     reply-to stream for brokered deployments
- ``__trace__``     obs trace id riding the blob (zoo.obs.trace.*)
- ``__deadline__``  absolute epoch-seconds deadline
                    (zoo.serving.deadline_ms)
- ``__tenant__``    parameter-lane id for population-backed models
                    (ISSUE-13): selects which member of a stacked
                    parameter tree answers this request; one warmed
                    compile serves every tenant (zoo.serving.tenant.*)
- ``__priority__``  admission class index (ISSUE-15): brownout
                    shedding refuses low classes first
                    (zoo.serving.priority.*, zoo.serving.shed.*)
- ``__error__``     reply-side: the structured error message tensor

Structured error prefixes (the *class* of a failure rides the reply
message as a greppable ``<prefix>: detail`` string, so the frontend
can map it to an HTTP status without a second wire field):

- ``deadline_exceeded`` -> 504 (the client's budget ran out; not a
  server fault)
- ``circuit_open`` -> 503 (breaker fast-fail; the handler adds
  Retry-After to every 503 so clients back off)
- ``generation_overflow`` -> 503 (KV-cache admission refusal;
  transient, retryable)
- ``invalid_request`` -> 400 (malformed client content the worker,
  not the frontend, detected)
- ``overloaded`` -> 503 (priority-ordered admission refusal; the
  Retry-After adapts to shed pressure)

``ERROR_PREFIXES`` is the complete prefix -> HTTP-status contract;
zoolint's ``error-prefix-unmapped`` rule fails any declared prefix
missing from it, so a new failure class cannot ship half-wired.
"""

from __future__ import annotations

from typing import Optional

# ---------------------------------------------------------- wire keys --
URI_KEY = "__uri__"
REPLY_KEY = "__reply__"
TRACE_KEY = "__trace__"
DEADLINE_KEY = "__deadline__"
ERROR_KEY = "__error__"
# generation serving (ISSUE-10). Request side: MAX_TOKENS_KEY caps the
# new tokens a generate request may emit and EOS_KEY names its stop
# token id (-1 = none) -- both ride the request blob next to
# __deadline__. Reply side: STREAM_KEY is the monotonically increasing
# chunk sequence number of a streamed generation reply; its PRESENCE
# is what routes a blob into a stream mailbox instead of the one-shot
# result path, and its value is the client's exactly-once dedup key (a
# supervisor-restarted stream regenerates deterministically from chunk
# 0, so consumers drop seq <= last-seen and never double-count a
# token).
STREAM_KEY = "__stream__"
MAX_TOKENS_KEY = "__max_tokens__"
EOS_KEY = "__eos__"
# per-tenant parameter lanes (ISSUE-13): the lane index into a
# population-backed model's stacked parameter tree. A request carrying
# it dispatches through the SAME warmed executable as every other
# tenant -- the lane is a traced argument, not a shape -- so thousands
# of per-tenant variants serve from one compile. Absent -> the
# zoo.serving.tenant.default_lane (or a 400 invalid_request when
# zoo.serving.tenant.strict).
TENANT_KEY = "__tenant__"
# priority classes (ISSUE-15): the request's admission class rides the
# blob as a small int32 index into PRIORITY_CLASSES, so brownout
# shedding can refuse low classes first and a requeued/restarted
# request keeps its class exactly like __tenant__ keeps its lane.
# Absent -> zoo.serving.priority.default_class.
PRIORITY_KEY = "__priority__"
# disaggregated prefill/decode pools (ISSUE-20): a blob carrying
# HANDOFF_KEY is a prefill->decode stream handoff riding the broker's
# handoff stream, NOT a client request. Its value is the handoff
# format version (int32); the blob's tensors carry the prompt, the
# page-aligned KV snapshot, and the slot replay state (next token,
# position, produced count, chunk seq) so a decode replica can import
# the stream -- or deterministically regenerate it when the snapshot
# was dropped -- without breaking the chunk-seq exactly-once contract.
HANDOFF_KEY = "__handoff__"

# request-side out-of-band keys the decoder strips from tensor dicts
# (ERROR_KEY/STREAM_KEY are reply-side only: model outputs named
# "error" stay usable, and an error reply is recognised by ERROR_KEY's
# presence, a stream chunk by STREAM_KEY's)
WIRE_KEYS = (URI_KEY, REPLY_KEY, TRACE_KEY, DEADLINE_KEY,
             MAX_TOKENS_KEY, EOS_KEY, TENANT_KEY, PRIORITY_KEY,
             HANDOFF_KEY)

# ---------------------------------------------------- priority classes --
# Index 0 is the HIGHEST class: the admission ladder sheds from the
# tail of this tuple first, and the no-inversion contract is "a class
# is never refused while a strictly lower class is admitted at the
# same queue depth". Wire value = index (int32), so class ordering is
# total and comparison is integer comparison.
PRIORITY_CLASSES = ("interactive", "batch", "background")
PRIORITY_DEFAULT = PRIORITY_CLASSES[0]


def priority_index(value) -> Optional[int]:
    """Normalize a class name or index to an index into
    PRIORITY_CLASSES, or None when the value names no class."""
    if value is None:
        return None
    if isinstance(value, str):
        name = value.strip().lower()
        if name in PRIORITY_CLASSES:
            return PRIORITY_CLASSES.index(name)
        return None
    try:
        idx = int(value)
    except (TypeError, ValueError):
        return None
    if 0 <= idx < len(PRIORITY_CLASSES):
        return idx
    return None


def priority_name(index) -> str:
    """Class name for a wire index; out-of-range indexes clamp to the
    lowest class (a garbled byte must never PROMOTE a request)."""
    try:
        idx = int(index)
    except (TypeError, ValueError):
        return PRIORITY_CLASSES[-1]
    if 0 <= idx < len(PRIORITY_CLASSES):
        return PRIORITY_CLASSES[idx]
    return PRIORITY_CLASSES[-1]

# ------------------------------------------------------ error prefixes --
DEADLINE_PREFIX = "deadline_exceeded"
CIRCUIT_PREFIX = "circuit_open"
# fleet vocabulary (ISSUE-9): a draining replica refuses NEW work while
# it finishes in-flight requests (rolling restart / SIGTERM drain), and
# the front-tier router answers replica_unavailable only after its
# one-retry-on-a-dead-replica budget is spent -- both are retryable,
# so both map to 503 (every 503 carries Retry-After)
DRAINING_PREFIX = "draining"
REPLICA_PREFIX = "replica_unavailable"
# generation vocabulary (ISSUE-10): a generate request refused at
# admission because the paged KV cache has no free slot/pages left --
# transient by construction (slots free as streams finish), so 503 +
# Retry-After, never a generic 500
GENERATION_PREFIX = "generation_overflow"
# a request the worker could not honor because the CLIENT sent
# malformed content past the frontend's shape checks (out-of-vocab
# token ids, missing prompt tensor): 400, not 500 -- bad input must
# never read as a server fault on the error-rate dashboard
INVALID_PREFIX = "invalid_request"
# brownout shedding (ISSUE-15): the admission controller refused the
# request because its class's depth threshold was exceeded --
# transient by construction, so 503 with an ADAPTIVE Retry-After
# (EWMA of shed pressure, zoo.serving.shed.retry_after_s the floor)
SHED_PREFIX = "overloaded"

# prefix -> HTTP status the frontend answers with; prefixes absent
# here fall through to 500 (generic server fault), which is exactly
# what the zoolint contract rule exists to prevent for declared ones
ERROR_PREFIXES = {
    DEADLINE_PREFIX: 504,
    CIRCUIT_PREFIX: 503,
    DRAINING_PREFIX: 503,
    REPLICA_PREFIX: 503,
    GENERATION_PREFIX: 503,
    INVALID_PREFIX: 400,
    SHED_PREFIX: 503,
}


def error_status(message: str) -> Optional[int]:
    """HTTP status for a structured error reply, or None when the
    message carries no declared prefix (-> generic 500 at the
    frontend). Matches ``<prefix>`` exactly or ``<prefix>:``-led."""
    for prefix, status in ERROR_PREFIXES.items():
        if message == prefix or message.startswith(prefix + ":"):
            return status
    return None
