"""Serving wire-protocol vocabulary: ONE declaring module.

Everything that crosses the serving wire as an out-of-band *name* --
reserved blob keys and structured error-reply prefixes -- is declared
here and imported everywhere else. A hand-typed copy elsewhere in
``serving/`` is a zoolint finding (``analysis/protocol.py``): a typo'd
key silently drops a deadline on the floor and a prefix the frontend
cannot map turns a structured rejection into a generic 500, and both
only surface under load.

Reserved wire keys (AZT1/npz blob tensor names; see
``queues._encode``):

- ``__uri__``       request id, the reply-correlation key
- ``__reply__``     reply-to stream for brokered deployments
- ``__trace__``     obs trace id riding the blob (zoo.obs.trace.*)
- ``__deadline__``  absolute epoch-seconds deadline
                    (zoo.serving.deadline_ms)
- ``__error__``     reply-side: the structured error message tensor

Structured error prefixes (the *class* of a failure rides the reply
message as a greppable ``<prefix>: detail`` string, so the frontend
can map it to an HTTP status without a second wire field):

- ``deadline_exceeded`` -> 504 (the client's budget ran out; not a
  server fault)
- ``circuit_open`` -> 503 (breaker fast-fail; the handler adds
  Retry-After to every 503 so clients back off)

``ERROR_PREFIXES`` is the complete prefix -> HTTP-status contract;
zoolint's ``error-prefix-unmapped`` rule fails any declared prefix
missing from it, so a new failure class cannot ship half-wired.
"""

from __future__ import annotations

from typing import Optional

# ---------------------------------------------------------- wire keys --
URI_KEY = "__uri__"
REPLY_KEY = "__reply__"
TRACE_KEY = "__trace__"
DEADLINE_KEY = "__deadline__"
ERROR_KEY = "__error__"

# request-side out-of-band keys the decoder strips from tensor dicts
# (ERROR_KEY is reply-side only: model outputs named "error" stay
# usable, and an error reply is recognised by ERROR_KEY's presence)
WIRE_KEYS = (URI_KEY, REPLY_KEY, TRACE_KEY, DEADLINE_KEY)

# ------------------------------------------------------ error prefixes --
DEADLINE_PREFIX = "deadline_exceeded"
CIRCUIT_PREFIX = "circuit_open"
# fleet vocabulary (ISSUE-9): a draining replica refuses NEW work while
# it finishes in-flight requests (rolling restart / SIGTERM drain), and
# the front-tier router answers replica_unavailable only after its
# one-retry-on-a-dead-replica budget is spent -- both are retryable,
# so both map to 503 (every 503 carries Retry-After)
DRAINING_PREFIX = "draining"
REPLICA_PREFIX = "replica_unavailable"

# prefix -> HTTP status the frontend answers with; prefixes absent
# here fall through to 500 (generic server fault), which is exactly
# what the zoolint contract rule exists to prevent for declared ones
ERROR_PREFIXES = {
    DEADLINE_PREFIX: 504,
    CIRCUIT_PREFIX: 503,
    DRAINING_PREFIX: 503,
    REPLICA_PREFIX: 503,
}


def error_status(message: str) -> Optional[int]:
    """HTTP status for a structured error reply, or None when the
    message carries no declared prefix (-> generic 500 at the
    frontend). Matches ``<prefix>`` exactly or ``<prefix>:``-led."""
    for prefix, status in ERROR_PREFIXES.items():
        if message == prefix or message.startswith(prefix + ":"):
            return status
    return None
