"""Serving deployment lifecycle: start / status / stop from YAML.

The analog of ``ClusterServingManager`` (ref: zoo/src/main/scala/com/
intel/analytics/zoo/serving/ClusterServingManager.scala -- job
lifecycle driven by the serving YAML). A deployment is one detached
launcher process; the manager tracks it with a state file
(``<name>.json`` with pid + config + address) under
``~/.analytics-zoo-tpu/serving`` (override with ``state_dir``).

CLI::

    python -m analytics_zoo_tpu.serving.manager start   -c config.yaml
    python -m analytics_zoo_tpu.serving.manager status  [-n name]
    python -m analytics_zoo_tpu.serving.manager stop    -n name
    python -m analytics_zoo_tpu.serving.manager restart -n name

Liveness is identity-checked, not pid-checked: the state file records
the launcher's /proc start time + cmdline at spawn, and ``status`` /
``stop`` / duplicate-``start`` only treat a pid as "our deployment"
when the identity still matches -- a recycled pid (days-old state file,
busy host) no longer reads as a running deployment, and ``stop`` can
no longer signal an innocent process. ``status`` garbage-collects the
state files of dead deployments (reported once with
``running: false``).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from analytics_zoo_tpu.common.log import get_logger

logger = get_logger(__name__)

DEFAULT_STATE_DIR = os.path.expanduser("~/.analytics-zoo-tpu/serving")


def _state_path(name: str, state_dir: Optional[str]) -> str:
    return os.path.join(state_dir or DEFAULT_STATE_DIR, f"{name}.json")


def _alive(pid: int) -> bool:
    if not isinstance(pid, int) or pid <= 0:
        # a truncated state file must never reach os.kill: pid -1
        # signals EVERY process the user can signal
        return False
    try:
        # reap if it's our zombie child: without this, a dead launcher
        # spawned by THIS process keeps answering kill(pid, 0) forever
        os.waitpid(pid, os.WNOHANG)
    except ChildProcessError:
        pass  # not our child (manager CLI from another process)
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:  # exists, owned elsewhere
        return True


def _proc_identity(pid: int):
    """(starttime_ticks, cmdline) from /proc, or None where /proc (or
    the process) is unavailable. The start time is the kernel's own
    per-boot monotonic stamp -- two processes can share a recycled
    pid, never a (pid, starttime) pair."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read()
        # field 22 (starttime); split after the ")" because field 2
        # (comm) may itself contain spaces/parens
        starttime = int(stat.rsplit(b")", 1)[1].split()[19])
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmdline = (f.read().replace(b"\0", b" ")
                       .decode("utf-8", "replace").strip())
        return starttime, cmdline
    except (OSError, ValueError, IndexError):
        return None


def _alive_state(state: Dict[str, Any]) -> bool:
    """Is the deployment this STATE FILE describes still running --
    i.e. the pid is alive AND still the process we spawned? Without
    the identity check a recycled pid makes a stale state file read
    as a running deployment (and makes ``stop`` SIGTERM a stranger).
    Falls back to the bare pid probe when /proc identity is
    unavailable (non-Linux) or the state file predates it."""
    pid = state.get("pid", -1)
    if not _alive(pid):
        return False
    recorded = state.get("starttime")
    if recorded is None:
        return True  # legacy state file: pid liveness is all we have
    ident = _proc_identity(pid)
    if ident is None:
        return True  # no /proc: cannot disprove, keep legacy behavior
    return ident[0] == recorded


def start(config_path: str, name: Optional[str] = None,
          state_dir: Optional[str] = None,
          log_path: Optional[str] = None) -> Dict[str, Any]:
    """Spawn a detached launcher for the YAML config; returns the state
    record (name, pid, config, log)."""
    import yaml

    with open(config_path) as f:
        config = yaml.safe_load(f) or {}
    name = name or config.get("name") or os.path.splitext(
        os.path.basename(config_path))[0]
    sdir = state_dir or DEFAULT_STATE_DIR
    os.makedirs(sdir, exist_ok=True)
    state_file = _state_path(name, state_dir)
    if os.path.isfile(state_file):
        with open(state_file) as f:
            old = json.load(f)
        if _alive_state(old):
            raise RuntimeError(
                f"deployment {name!r} already running "
                f"(pid {old.get('pid', 0)}); stop it first")
    log_path = log_path or os.path.join(sdir, f"{name}.log")
    log_f = open(log_path, "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "analytics_zoo_tpu.serving.launcher",
         "-c", os.path.abspath(config_path)],
        stdout=log_f, stderr=subprocess.STDOUT,
        start_new_session=True)  # detach: survives the manager exiting
    log_f.close()
    state = {"name": name, "pid": proc.pid,
             "config": os.path.abspath(config_path),
             "log": log_path, "started_at": time.time()}
    ident = _proc_identity(proc.pid)
    if ident is not None:
        # the anti-pid-reuse fingerprint _alive_state checks later
        state["starttime"], state["cmdline"] = ident
    with open(state_file, "w") as f:
        json.dump(state, f)
    try:
        os.unlink(state_file + ".dead")  # superseded by the new run
    except FileNotFoundError:
        pass
    logger.info("started deployment %s (pid %d)", name, proc.pid)
    return state


def status(name: Optional[str] = None,
           state_dir: Optional[str] = None) -> List[Dict[str, Any]]:
    """State of one (or every) tracked deployment; each record gains
    ``running: bool``. Dead deployments are reported ONCE and their
    state files garbage-collected -- a crashed launcher (or a
    recycled pid) stops haunting the listing, and a later ``start``
    under the same name needs no manual cleanup."""
    sdir = state_dir or DEFAULT_STATE_DIR
    if not os.path.isdir(sdir):
        return []
    names = ([name] if name else
             [os.path.splitext(f)[0] for f in sorted(os.listdir(sdir))
              if f.endswith(".json")])
    out = []
    for n in names:
        path = _state_path(n, state_dir)
        if not os.path.isfile(path):
            continue
        with open(path) as f:
            state = json.load(f)
        state["running"] = _alive_state(state)
        if not state["running"]:
            logger.info("reaping stale state file for dead "
                        "deployment %s (pid %s)", n, state.get("pid"))
            try:
                # parked as .dead, not unlinked: the obvious next move
                # after seeing a dead deployment is `restart -n`,
                # which needs the recorded config path
                os.replace(path, path + ".dead")
            except OSError:
                pass  # another status call won the reap
        out.append(state)
    return out


def stop(name: str, state_dir: Optional[str] = None,
         grace_s: float = 10.0) -> bool:
    """SIGTERM the deployment (SIGKILL after ``grace_s``); removes the
    state file. Returns True if a process was stopped."""
    path = _state_path(name, state_dir)
    if not os.path.isfile(path):
        return False
    with open(path) as f:
        state = json.load(f)
    pid = state.get("pid", 0)
    stopped = False
    try:
        # identity-checked: a recycled pid must NOT receive our
        # SIGTERM. The process can still exit between the check and
        # the kill -- either way the deployment is gone; always fall
        # through to state-file removal
        if _alive_state(state):
            os.kill(pid, signal.SIGTERM)
            stopped = True  # the TERM landed: this call stopped it even
            deadline = time.time() + grace_s  # if a later check races
            while _alive(pid) and time.time() < deadline:
                time.sleep(0.1)
            if _alive(pid):
                os.kill(pid, signal.SIGKILL)
            logger.info("stopped deployment %s (pid %d)", name, pid)
    except (ProcessLookupError, PermissionError) as e:
        logger.info("deployment %s (pid %d) already gone or not ours: "
                    "%s", name, pid, e)
    finally:
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
    return stopped


def restart(name: str, state_dir: Optional[str] = None,
            grace_s: float = 10.0) -> Dict[str, Any]:
    """Stop the deployment (if running) and start it again from the
    config path its state file records. Works on dead deployments too
    -- the common recovery move after a crash the in-process
    Supervisor could not absorb (OOM kill, segfault)."""
    path = _state_path(name, state_dir)
    if not os.path.isfile(path):
        # status() parks dead deployments' state as .dead -- restart
        # is exactly the caller that still needs it
        if os.path.isfile(path + ".dead"):
            path = path + ".dead"
        else:
            raise FileNotFoundError(
                f"no tracked deployment {name!r} (state file {path} "
                "missing); use start -c <config>")
    with open(path) as f:
        state = json.load(f)
    config_path = state.get("config")
    if not config_path or not os.path.isfile(config_path):
        raise FileNotFoundError(
            f"deployment {name!r} records config {config_path!r}, "
            "which no longer exists")
    stop(name, state_dir=state_dir, grace_s=grace_s)
    return start(config_path, name=name, state_dir=state_dir,
                 log_path=state.get("log"))


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="analytics_zoo_tpu serving manager")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_start = sub.add_parser("start")
    p_start.add_argument("-c", "--config", required=True)
    p_start.add_argument("-n", "--name")
    p_start.add_argument("--state-dir")
    p_status = sub.add_parser("status")
    p_status.add_argument("-n", "--name")
    p_status.add_argument("--state-dir")
    p_status.add_argument(
        "--json", action="store_true",
        help="machine-readable summary ({deployments, alive, total}) "
             "and exit code 0 only when every queried deployment is "
             "alive -- shell scripts and the fleet controller branch "
             "on $? instead of parsing output")
    p_stop = sub.add_parser("stop")
    p_stop.add_argument("-n", "--name", required=True)
    p_stop.add_argument("--state-dir")
    p_restart = sub.add_parser("restart")
    p_restart.add_argument("-n", "--name", required=True)
    p_restart.add_argument("--state-dir")
    args = ap.parse_args(argv)
    if args.cmd == "start":
        state = start(args.config, name=args.name,
                      state_dir=args.state_dir)
        print(json.dumps(state))
    elif args.cmd == "status":
        records = status(args.name, state_dir=args.state_dir)
        if args.json:
            # the status --json contract (ISSUE-9 satellite): one JSON
            # object + a liveness exit code, so callers never parse
            # log-ish output. Exit 1 when anything queried is dead OR
            # nothing is tracked ("the deployment you asked about is
            # not running" must not exit 0).
            alive = sum(1 for r in records if r.get("running"))
            print(json.dumps({"deployments": records, "alive": alive,
                              "total": len(records)}))
            sys.exit(0 if records and alive == len(records) else 1)
        print(json.dumps(records))
    elif args.cmd == "stop":
        ok = stop(args.name, state_dir=args.state_dir)
        print(json.dumps({"stopped": ok}))
    elif args.cmd == "restart":
        print(json.dumps(restart(args.name,
                                 state_dir=args.state_dir)))


if __name__ == "__main__":
    main()
