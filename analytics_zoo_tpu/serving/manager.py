"""Serving deployment lifecycle: start / status / stop from YAML.

The analog of ``ClusterServingManager`` (ref: zoo/src/main/scala/com/
intel/analytics/zoo/serving/ClusterServingManager.scala -- job
lifecycle driven by the serving YAML). A deployment is one detached
launcher process; the manager tracks it with a state file
(``<name>.json`` with pid + config + address) under
``~/.analytics-zoo-tpu/serving`` (override with ``state_dir``).

CLI::

    python -m analytics_zoo_tpu.serving.manager start  -c config.yaml
    python -m analytics_zoo_tpu.serving.manager status [-n name]
    python -m analytics_zoo_tpu.serving.manager stop   -n name
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from analytics_zoo_tpu.common.log import get_logger

logger = get_logger(__name__)

DEFAULT_STATE_DIR = os.path.expanduser("~/.analytics-zoo-tpu/serving")


def _state_path(name: str, state_dir: Optional[str]) -> str:
    return os.path.join(state_dir or DEFAULT_STATE_DIR, f"{name}.json")


def _alive(pid: int) -> bool:
    if not isinstance(pid, int) or pid <= 0:
        # a truncated state file must never reach os.kill: pid -1
        # signals EVERY process the user can signal
        return False
    try:
        # reap if it's our zombie child: without this, a dead launcher
        # spawned by THIS process keeps answering kill(pid, 0) forever
        os.waitpid(pid, os.WNOHANG)
    except ChildProcessError:
        pass  # not our child (manager CLI from another process)
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:  # exists, owned elsewhere
        return True


def start(config_path: str, name: Optional[str] = None,
          state_dir: Optional[str] = None,
          log_path: Optional[str] = None) -> Dict[str, Any]:
    """Spawn a detached launcher for the YAML config; returns the state
    record (name, pid, config, log)."""
    import yaml

    with open(config_path) as f:
        config = yaml.safe_load(f) or {}
    name = name or config.get("name") or os.path.splitext(
        os.path.basename(config_path))[0]
    sdir = state_dir or DEFAULT_STATE_DIR
    os.makedirs(sdir, exist_ok=True)
    state_file = _state_path(name, state_dir)
    if os.path.isfile(state_file):
        with open(state_file) as f:
            old = json.load(f)
        old_pid = old.get("pid", 0)
        if _alive(old_pid):
            raise RuntimeError(
                f"deployment {name!r} already running (pid {old_pid}); "
                "stop it first")
    log_path = log_path or os.path.join(sdir, f"{name}.log")
    log_f = open(log_path, "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "analytics_zoo_tpu.serving.launcher",
         "-c", os.path.abspath(config_path)],
        stdout=log_f, stderr=subprocess.STDOUT,
        start_new_session=True)  # detach: survives the manager exiting
    log_f.close()
    state = {"name": name, "pid": proc.pid,
             "config": os.path.abspath(config_path),
             "log": log_path, "started_at": time.time()}
    with open(state_file, "w") as f:
        json.dump(state, f)
    logger.info("started deployment %s (pid %d)", name, proc.pid)
    return state


def status(name: Optional[str] = None,
           state_dir: Optional[str] = None) -> List[Dict[str, Any]]:
    """State of one (or every) tracked deployment; each record gains
    ``running: bool``."""
    sdir = state_dir or DEFAULT_STATE_DIR
    if not os.path.isdir(sdir):
        return []
    names = ([name] if name else
             [os.path.splitext(f)[0] for f in sorted(os.listdir(sdir))
              if f.endswith(".json")])
    out = []
    for n in names:
        path = _state_path(n, state_dir)
        if not os.path.isfile(path):
            continue
        with open(path) as f:
            state = json.load(f)
        state["running"] = _alive(state.get("pid", -1))
        out.append(state)
    return out


def stop(name: str, state_dir: Optional[str] = None,
         grace_s: float = 10.0) -> bool:
    """SIGTERM the deployment (SIGKILL after ``grace_s``); removes the
    state file. Returns True if a process was stopped."""
    path = _state_path(name, state_dir)
    if not os.path.isfile(path):
        return False
    with open(path) as f:
        state = json.load(f)
    pid = state.get("pid", 0)
    stopped = False
    try:
        # the process can exit (or its pid be recycled to another
        # user's process, where _alive's PermissionError reads as True)
        # between the liveness check and the kill -- either way the
        # deployment is gone; always fall through to state-file removal
        if _alive(pid):
            os.kill(pid, signal.SIGTERM)
            stopped = True  # the TERM landed: this call stopped it even
            deadline = time.time() + grace_s  # if a later check races
            while _alive(pid) and time.time() < deadline:
                time.sleep(0.1)
            if _alive(pid):
                os.kill(pid, signal.SIGKILL)
            logger.info("stopped deployment %s (pid %d)", name, pid)
    except (ProcessLookupError, PermissionError) as e:
        logger.info("deployment %s (pid %d) already gone or not ours: "
                    "%s", name, pid, e)
    finally:
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
    return stopped


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="analytics_zoo_tpu serving manager")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_start = sub.add_parser("start")
    p_start.add_argument("-c", "--config", required=True)
    p_start.add_argument("-n", "--name")
    p_start.add_argument("--state-dir")
    p_status = sub.add_parser("status")
    p_status.add_argument("-n", "--name")
    p_status.add_argument("--state-dir")
    p_stop = sub.add_parser("stop")
    p_stop.add_argument("-n", "--name", required=True)
    p_stop.add_argument("--state-dir")
    args = ap.parse_args(argv)
    if args.cmd == "start":
        state = start(args.config, name=args.name,
                      state_dir=args.state_dir)
        print(json.dumps(state))
    elif args.cmd == "status":
        print(json.dumps(status(args.name, state_dir=args.state_dir)))
    elif args.cmd == "stop":
        ok = stop(args.name, state_dir=args.state_dir)
        print(json.dumps({"stopped": ok}))


if __name__ == "__main__":
    main()
