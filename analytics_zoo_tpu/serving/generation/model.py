"""Causal-transformer LM with explicit prefill / decode-step math.

The generation engine needs a model whose forward splits the way the
serving path splits: a *prefill* over the whole prompt (compute-bound,
bucketed on prompt length, rides the causal attention dispatch in
``ops/`` -- the Pallas flash kernel on TPU when shapes allow) and a
*decode step* for one position per slot against the paged KV pool
(memory-bound, fixed shape). Flax's module system hides exactly the
seam we need, so the parameters here are a plain pytree and the two
phases are plain functions the engine jits.

:class:`TinyGenLM` is deliberately small and deterministic (seeded
init): it is the reference generation model of the test suite and the
perf driver, the role ``_TinyNet`` plays for the predict path. Real
checkpoints plug in by implementing the same three functions over
their own params (``docs/serving.md`` "Generation serving").

Pre-LN transformer block; learned positional embeddings; all f32 so
greedy argmax parity between the prefill path, the paged decode step,
and the re-run-the-whole-prefix reference is a float-noise question
with margins, not a dtype question.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class GenModelConfig:
    """Geometry of a :class:`TinyGenLM` (and of the KV pool serving
    it -- the engine reads layers/heads/head_dim from here)."""

    vocab: int = 64
    dim: int = 32
    heads: int = 2
    head_dim: int = 16
    layers: int = 2
    max_len: int = 256
    mlp_ratio: int = 2
    seed: int = 0

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "GenModelConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown generation model fields: {sorted(unknown)} "
                f"(known: {sorted(known)})")
        return cls(**{k: int(v) for k, v in d.items()})


def _ln(x, scale, bias):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * scale + bias


class TinyGenLM:
    """Seeded parameter factory + the prefill / decode-step forwards.

    All methods are pure functions of ``(params, inputs)`` -- the
    engine owns jit/caching; instances only carry the config.
    """

    def __init__(self, config: GenModelConfig):
        self.config = config

    # ------------------------------------------------------- params --
    def init_params(self, pos_len: int | None = None) -> Dict[str, Any]:
        """Deterministic f32 parameter pytree. ``pos_len`` sizes the
        positional table (the engine passes its prefill-ladder top so
        padded prefill buckets never index past it)."""
        c = self.config
        pos_len = int(pos_len or c.max_len)
        rng = np.random.RandomState(c.seed)

        def mat(*shape, scale=None):
            scale = scale if scale is not None else 1.0 / np.sqrt(
                shape[0])
            return jnp.asarray(
                rng.normal(0.0, scale, shape).astype(np.float32))

        inner = c.heads * c.head_dim
        blocks = []
        for _ in range(c.layers):
            blocks.append({
                "ln1_s": jnp.ones((c.dim,), jnp.float32),
                "ln1_b": jnp.zeros((c.dim,), jnp.float32),
                "wq": mat(c.dim, inner), "wk": mat(c.dim, inner),
                "wv": mat(c.dim, inner), "wo": mat(inner, c.dim),
                "ln2_s": jnp.ones((c.dim,), jnp.float32),
                "ln2_b": jnp.zeros((c.dim,), jnp.float32),
                "w1": mat(c.dim, c.dim * c.mlp_ratio),
                "w2": mat(c.dim * c.mlp_ratio, c.dim),
            })
        return {
            # deliberately hot init (unit-scale embeddings + strong
            # positional signal): a near-zero random LM's greedy
            # trajectory collapses to one repeated argmax within a
            # couple of tokens, which would let cross-slot
            # contamination bugs hide behind identical fixed points in
            # the parity tests; position-dependent dynamics keep
            # trajectories distinct per (prompt, position)
            "embed": mat(c.vocab, c.dim, scale=1.0),
            "pos": mat(pos_len, c.dim, scale=1.0),
            "blocks": blocks,
            "lnf_s": jnp.ones((c.dim,), jnp.float32),
            "lnf_b": jnp.zeros((c.dim,), jnp.float32),
            "head": mat(c.dim, c.vocab, scale=1.0),
        }

    # ------------------------------------------------------ prefill --
    def prefill(self, params, tokens) -> Tuple[Any, Any, Any]:
        """Full causal forward over ``tokens`` [B, L].

        Returns ``(logits [B, L, vocab], k, v)`` with k/v stacked
        [layers, B, L, heads, head_dim] -- the cache chunks the engine
        scatters into the page pool. Attention routes through the ops
        dispatcher, so TPU prefill rides the owned causal Pallas flash
        kernel when shapes allow (``zoo.ops.attention_impl``)."""
        from analytics_zoo_tpu.ops.attention import (
            dot_product_attention)

        c = self.config
        b, l = tokens.shape
        x = params["embed"][tokens] + params["pos"][:l][None]
        ks, vs = [], []
        for blk in params["blocks"]:
            h = _ln(x, blk["ln1_s"], blk["ln1_b"])
            q = (h @ blk["wq"]).reshape(b, l, c.heads, c.head_dim)
            k = (h @ blk["wk"]).reshape(b, l, c.heads, c.head_dim)
            v = (h @ blk["wv"]).reshape(b, l, c.heads, c.head_dim)
            o = dot_product_attention(
                q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3), causal=True)
            x = x + o.transpose(0, 2, 1, 3).reshape(
                b, l, c.heads * c.head_dim) @ blk["wo"]
            h2 = _ln(x, blk["ln2_s"], blk["ln2_b"])
            x = x + jax.nn.relu(h2 @ blk["w1"]) @ blk["w2"]
            ks.append(k)
            vs.append(v)
        logits = _ln(x, params["lnf_s"], params["lnf_b"]) @ params["head"]
        return logits, jnp.stack(ks), jnp.stack(vs)

    # -------------------------------------------------- decode step --
    def decode_step(self, params, tokens, positions, gather_kv,
                    write_kv):
        """One position per slot: ``tokens``/``positions`` are [S].

        The cache is abstracted behind two callbacks so this math stays
        pool-layout-agnostic: ``write_kv(layer, k, v)`` commits this
        position's [S, H, D] k/v, ``gather_kv(layer)`` returns the
        slot-table context ``(K, V)`` as [S, T, H, D] plus the
        attendable-position mask [S, T]. Returns logits [S, vocab]."""
        c = self.config
        x = params["embed"][tokens] + params["pos"][positions]
        for li, blk in enumerate(params["blocks"]):
            h = _ln(x, blk["ln1_s"], blk["ln1_b"])
            q = (h @ blk["wq"]).reshape(-1, c.heads, c.head_dim)
            k = (h @ blk["wk"]).reshape(-1, c.heads, c.head_dim)
            v = (h @ blk["wv"]).reshape(-1, c.heads, c.head_dim)
            write_kv(li, k, v)
            bk, bv, mask = gather_kv(li)
            scores = jnp.einsum(
                "shd,sthd->sht", q, bk,
                preferred_element_type=jnp.float32)
            scores = scores / np.sqrt(c.head_dim)
            scores = jnp.where(mask[:, None, :], scores, NEG_INF)
            probs = jax.nn.softmax(scores, axis=-1)
            o = jnp.einsum("sht,sthd->shd", probs.astype(bv.dtype), bv)
            x = x + o.reshape(-1, c.heads * c.head_dim) @ blk["wo"]
            h2 = _ln(x, blk["ln2_s"], blk["ln2_b"])
            x = x + jax.nn.relu(h2 @ blk["w1"]) @ blk["w2"]
        return _ln(x, params["lnf_s"], params["lnf_b"]) @ params["head"]

    # ---------------------------------------------------- reference --
    def reference_generate(self, params, prompt, max_new_tokens: int,
                           eos: int = -1) -> np.ndarray:
        """Greedy generation by re-running the full prefill on the
        growing prefix every token -- the unbatched, cache-free
        reference the engine's paged decode is parity-tested against
        (and the naive baseline of the perf A/B). One jit compile per
        prefix length; O(T^2) device calls by construction."""
        toks = list(np.asarray(prompt, np.int32).reshape(-1))
        out = []
        for _ in range(int(max_new_tokens)):
            arr = jnp.asarray(np.asarray(toks, np.int32)[None])
            logits, _, _ = self.prefill(params, arr)
            nxt = int(np.asarray(jnp.argmax(logits[0, -1])))
            out.append(nxt)
            toks.append(nxt)
            if eos >= 0 and nxt == eos:
                break
        return np.asarray(out, np.int32)
