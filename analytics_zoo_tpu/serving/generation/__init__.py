"""Autoregressive generation serving (ISSUE-10).

The token-streaming data plane the predict path cannot express: a
``generate`` request has a *lifetime* (prefill, then one token per
decode step until eos/max_tokens/deadline), so batching is not "stack
N requests into one tensor" but "keep a fixed-shape decode step full
of whichever streams are alive right now". The package splits that
into:

- :mod:`model` -- a self-contained causal-transformer LM
  (:class:`TinyGenLM`) with explicit prefill and single-position
  decode math (the two phases the engine compiles separately);
- :mod:`engine` -- :class:`DecodeEngine`: bucketed prefill ladder (its
  own shape ladder, same recompile-storm discipline as the predict
  bucket cache) + ONE fixed-shape decode step over the slot table,
  backed by :class:`~analytics_zoo_tpu.inference.kv_cache.PagedKVCache`;
- :mod:`batcher` -- :class:`ContinuousBatcher`: AdaptiveBatcher's role
  evolved into slot *admission* -- requests join and leave the running
  batch at step boundaries instead of waiting for a batch window;
- :mod:`worker` -- :class:`GenerationWorker`: the serving loop
  (queues in, streamed chunks out) with the same drain / chaos /
  supervisor / fleet seams as :class:`~..worker.ServingWorker`.

Wire vocabulary (``serving/protocol.py``): requests ride
``__max_tokens__``/``__eos__``; streamed reply chunks carry
``__stream__`` (the chunk sequence number -- also the client's
exactly-once dedup key) and the terminal chunk a ``finish_reason``
(or ``__error__`` with a structured prefix, e.g.
``generation_overflow`` -> 503, ``deadline_exceeded`` -> mid-stream
structured terminal chunk).
"""

from analytics_zoo_tpu.serving.generation.model import (  # noqa: F401
    GenModelConfig,
    TinyGenLM,
)
from analytics_zoo_tpu.serving.generation.engine import (  # noqa: F401
    DecodeEngine,
    prefill_ladder,
)
from analytics_zoo_tpu.serving.generation.batcher import (  # noqa: F401
    ContinuousBatcher,
)
from analytics_zoo_tpu.serving.generation.worker import (  # noqa: F401
    GenerationWorker,
)
