"""DecodeEngine: prefill/decode split over a paged KV cache.

The generation analog of ``InferenceModel``'s bucketed predict path,
split the way the workload splits:

- **Prefill** is compute-bound and ragged: prompts are padded onto a
  *prompt-length ladder* (``prefill_ladder`` -- page-size-aligned
  powers of two, so every bucket scatters into whole pages) and run
  through the model's full causal forward, one jitted program per
  bucket. Same discipline as the predict bucket cache: ``warm_up``
  walks the ladder under ``obs.events.warming()`` and every live
  compile feeds the recompile-storm detector.
- **Decode** is memory-bound and regular: ONE fixed-shape jitted step
  advances every active slot of the slot table by one token --
  requests joining or leaving the running batch never mint a new XLA
  shape, which is what makes continuous batching tractable on TPU at
  all (ROADMAP "autoregressive generation serving").

The engine owns slot *state* (next input token, write position per
slot); :class:`~analytics_zoo_tpu.inference.kv_cache.PagedKVCache`
owns page *accounting*; request metadata (uri, deadline, budget) is
the worker's business. Greedy sampling (argmax) runs inside the jitted
step so only S int32 tokens cross to the host per step, and the host
sync lives in ``_finalize_*`` methods -- the declared hot-path barrier
deepcheck's ``hotpath-block-on-device`` rule checks against.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.inference.kv_cache import CacheOverflow, PagedKVCache
from analytics_zoo_tpu.obs.events import record_compile, warming
from analytics_zoo_tpu.obs.metrics import get_registry
from analytics_zoo_tpu.serving.generation.model import (
    GenModelConfig, TinyGenLM)

logger = get_logger(__name__)

# deepcheck hot-path roots (docs/zoolint.md "deepcheck"): the decode
# loop and prefill are the generation data plane's per-token /
# per-request device paths -- host blocking syncs belong behind the
# _finalize_* barrier, not inline
ZOOLINT_HOT_PATH = ("DecodeEngine.step", "DecodeEngine.admit")

_REG = get_registry()
_M_PREFILL = _REG.histogram(
    "zoo_generation_prefill_duration_seconds",
    "Prefill wall time per admitted request, by prompt bucket",
    labelnames=("bucket",))
_M_STEP = _REG.histogram(
    "zoo_generation_decode_step_duration_seconds",
    "One fixed-shape decode step over the slot table (all active "
    "slots advance one token)")
_M_OCC = _REG.gauge(
    "zoo_generation_slot_occupancy_items",
    "Active decode slots (streams currently in the running batch)")
_M_KV = _REG.gauge(
    "zoo_generation_kv_utilization_ratio",
    "Assigned KV-cache pages / total pages (PagedKVCache accounting)")


def prefill_ladder(page_size: int, max_len: int) -> List[int]:
    """The prompt-length shape ladder: ``page_size`` doubling until it
    covers ``max_len``. Page-aligned by construction, so every bucket
    scatters into whole pages; the top entry is the positional-table
    size prefill can index."""
    out = [int(page_size)]
    while out[-1] < max_len:
        out.append(out[-1] * 2)
    return out


class DecodeEngine:
    """Slot-table decode over a paged KV pool.

    Args:
      model: a :class:`TinyGenLM` (or anything exposing its
        ``config``/``init_params``/``prefill``/``decode_step``
        surface).
      params: model parameter pytree; None = ``model.init_params()``
        (seeded -- the test/bench path).
      num_slots / page_size / num_pages / max_len: cache geometry;
        None reads the ``zoo.generation.*`` keys.

    Host API (all called from ONE worker loop thread):
      ``admit(prompt, max_new_tokens) -> (slot, first_token)``,
      ``step() -> [(slot, token), ...]``, ``release(slot)``,
      ``warm_up()``.
    """

    def __init__(self, model: TinyGenLM,
                 params: Optional[Dict[str, Any]] = None,
                 num_slots: Optional[int] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 max_len: Optional[int] = None,
                 dtype: Any = None):
        from analytics_zoo_tpu.common.config import get_config

        cfg = get_config()
        if num_slots is None:
            num_slots = int(cfg.get("zoo.generation.slots", 8))
        if page_size is None:
            page_size = int(cfg.get("zoo.generation.page_size", 16))
        if num_pages is None:
            num_pages = int(cfg.get("zoo.generation.num_pages", 0))
        if max_len is None:
            max_len = int(cfg.get("zoo.generation.max_len", 256))
        self.model = model
        c = model.config
        self.ladder = prefill_ladder(page_size, max_len)
        self.params = (params if params is not None
                       else model.init_params(pos_len=self.ladder[-1]))
        self.cache = PagedKVCache(
            num_layers=c.layers, num_heads=c.heads,
            head_dim=c.head_dim, page_size=page_size,
            num_slots=num_slots, num_pages=num_pages, max_len=max_len,
            dtype=dtype)
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        self.max_len = int(max_len)
        # per-slot decode state: the token the next step consumes and
        # the position it writes at (position L for a length-L prefix)
        self._tokens = np.zeros(self.num_slots, np.int32)
        self._positions = np.zeros(self.num_slots, np.int32)
        self._active: set = set()
        self._compiled_prefill: set = set()
        self._step_compiled = False
        import jax

        # donate the pool: both fns functionally rebuild the ENTIRE
        # kv array and the caller unconditionally replaces
        # self.cache.kv with the result, so without donation XLA must
        # keep the input alive -- one full-pool copy per generated
        # token and 2x peak HBM on the dominant allocation. (On CPU
        # donation is ignored with a one-time warning; the estimator's
        # train step uses the same pattern under
        # zoo.train.donate_buffers.)
        self._prefill_jit = jax.jit(self._prefill_impl,
                                    donate_argnums=(1,))
        self._step_jit = jax.jit(self._step_impl, donate_argnums=(1,))

    # ------------------------------------------------- jitted bodies --
    def _prefill_impl(self, params, kv, tokens, pages, last_idx):
        """Full forward over one padded prompt [Lb]; scatters its K/V
        pages into the pool (bucket pages beyond the prompt's
        assignment point at the trash page) and returns the greedy
        first token from the true last position."""
        import jax.numpy as jnp

        logits, k, v = self.model.prefill(params, tokens[None])
        npages = tokens.shape[0] // self.page_size
        c = self.model.config
        kc = k[:, 0].reshape(c.layers, npages, self.page_size,
                             c.heads, c.head_dim)
        vc = v[:, 0].reshape(c.layers, npages, self.page_size,
                             c.heads, c.head_dim)
        kv = kv.at[:, 0, pages].set(kc.astype(kv.dtype))
        kv = kv.at[:, 1, pages].set(vc.astype(kv.dtype))
        return kv, jnp.argmax(logits[0, last_idx]).astype(jnp.int32)

    def _step_impl(self, params, kv, tokens, positions, block):
        """One token for every slot lane (inactive lanes write to the
        trash page and produce ignored garbage -- fixed shape is the
        contract). Returns (kv', greedy tokens [S])."""
        import jax.numpy as jnp

        page = self.page_size
        t_ctx = block.shape[1] * page
        pp = jnp.take_along_axis(
            block, (positions // page)[:, None], axis=1)[:, 0]
        off = positions % page
        kvh = [kv]

        def write_kv(layer, k, v):
            kvh[0] = kvh[0].at[layer, 0, pp, off].set(
                k.astype(kv.dtype))
            kvh[0] = kvh[0].at[layer, 1, pp, off].set(
                v.astype(kv.dtype))

        def gather_kv(layer):
            bk = kvh[0][layer, 0][block].reshape(
                self.num_slots, t_ctx, -1, self.model.config.head_dim)
            bv = kvh[0][layer, 1][block].reshape(
                self.num_slots, t_ctx, -1, self.model.config.head_dim)
            mask = (jnp.arange(t_ctx)[None, :]
                    <= positions[:, None])
            return bk.astype(jnp.float32), bv.astype(jnp.float32), mask

        logits = self.model.decode_step(params, tokens, positions,
                                        gather_kv, write_kv)
        return kvh[0], jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # --------------------------------------------------------- admit --
    def can_admit(self, prompt_len: int, max_new_tokens: int) -> bool:
        return self.cache.can_admit(int(prompt_len)
                                    + int(max_new_tokens))

    def free_slots(self) -> int:
        return self.cache.free_slot_count()

    def active_slots(self) -> int:
        return len(self._active)

    def admit(self, prompt, max_new_tokens: int) -> Tuple[int, int]:
        """Join the running batch: claim a slot + pages, prefill the
        prompt into the pool, return ``(slot, first_token)``. Raises
        :class:`CacheOverflow` (the caller maps it to the structured
        ``generation_overflow`` refusal) and ValueError on an empty or
        over-long prompt. On success the CALLER owns the slot and owes
        :meth:`release` on every path (zoolint ``leak-on-path``
        enforces the pairing statically); on any failure past the
        claim, the slot is given back here before re-raising."""
        import jax.numpy as jnp

        prompt = np.asarray(prompt, np.int32).reshape(-1)
        lp = int(prompt.shape[0])
        if lp < 1:
            raise ValueError("empty prompt")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        vocab = self.model.config.vocab
        if prompt.min() < 0 or prompt.max() >= vocab:
            raise ValueError(
                f"prompt token ids must be in [0, {vocab})")
        slot = self.cache.admit(lp, max_new_tokens)  # CacheOverflow
        try:
            return slot, self._prefill_slot(slot, prompt, lp)
        except BaseException:
            # anything after the claim (page assignment, prefill) must
            # give the slot + reservation back, or a poisoned request
            # permanently shrinks capacity (8 bad requests = a dead
            # engine)
            self.cache.release(slot)
            raise

    def _prefill_slot(self, slot: int, prompt: np.ndarray,
                      lp: int) -> int:
        import jax.numpy as jnp

        self.cache.ensure_length(slot, lp)
        bucket = next(b for b in self.ladder if b >= lp)
        padded = np.zeros(bucket, np.int32)
        padded[:lp] = prompt
        npages = bucket // self.page_size
        pages = np.zeros(npages, np.int32)  # trash beyond the prompt
        n_assigned = self.cache.pages_for(lp)
        pages[:n_assigned] = self.cache.block_tables()[
            slot, :n_assigned]
        fresh = bucket not in self._compiled_prefill
        t0 = time.perf_counter()
        kv, tok0 = self._prefill_jit(
            self.params, self.cache.kv, jnp.asarray(padded),
            jnp.asarray(pages), np.int32(lp - 1))
        tok0 = self._finalize_prefill(kv, tok0)
        wall = time.perf_counter() - t0
        if fresh:
            self._compiled_prefill.add(bucket)
            record_compile("generation.prefill",
                           [((bucket,), "int32")], wall,
                           subsystem="generation")
        _M_PREFILL.labels(bucket=str(bucket)).observe(wall)
        self._tokens[slot] = tok0
        self._positions[slot] = lp
        self._active.add(slot)
        self._update_gauges()
        return tok0

    def _finalize_prefill(self, kv, tok0) -> int:
        """Commit the new pool and sync the first token (the one host
        round-trip an admission pays)."""
        self.cache.kv = kv
        return int(np.asarray(tok0))

    # ---------------------------------------------------------- step --
    def step(self) -> List[Tuple[int, int]]:
        """Advance every active slot one token; returns
        ``[(slot, next_token), ...]`` for active slots only (the token
        each slot's *current* input produced). Empty batch = no-op."""
        import jax.numpy as jnp

        if not self._active:
            return []
        for slot in self._active:
            # lazy page assignment at the boundary (never fails inside
            # the admission-time reservation)
            self.cache.ensure_length(slot,
                                     int(self._positions[slot]) + 1)
        fresh = not self._step_compiled
        t0 = time.perf_counter()
        kv, toks = self._step_jit(
            self.params, self.cache.kv, jnp.asarray(self._tokens),
            jnp.asarray(self._positions),
            jnp.asarray(self.cache.block_tables()))
        out = self._finalize_step(kv, toks)
        wall = time.perf_counter() - t0
        if fresh:
            self._step_compiled = True
            record_compile(
                "generation.decode_step",
                [((self.num_slots,), "int32")], wall,
                subsystem="generation")
        _M_STEP.observe(wall)
        results = []
        for slot in sorted(self._active):
            nxt = int(out[slot])
            self._positions[slot] += 1
            self._tokens[slot] = nxt
            results.append((slot, nxt))
        return results

    def _finalize_step(self, kv, toks) -> np.ndarray:
        """Commit the pool and sync the step's S tokens to the host --
        the per-step device->host barrier (everything before it is
        async dispatch)."""
        self.cache.kv = kv
        return np.asarray(toks)

    # ------------------------------------------------------- release --
    def release(self, slot: int) -> None:
        """Leave the running batch: free the slot and its pages (block
        reuse -- the next admission takes them over)."""
        self._active.discard(slot)
        self._tokens[slot] = 0
        self._positions[slot] = 0
        self.cache.release(slot)
        self._update_gauges()

    # ------------------------------------------------------- handoff --
    # ISSUE-20: prefill/decode disaggregation. A prefill engine
    # exports a slot's full decode state -- page-aligned KV snapshot
    # plus the host slot registers (next input token, write position)
    # -- and a decode engine on another replica imports it and keeps
    # stepping bit-identically. Sampling is greedy argmax, so the slot
    # carries no sampler RNG; ``rng`` stays in the snapshot as an
    # explicit None so a future stochastic sampler extends the format
    # instead of forking it (replay determinism is the exactly-once
    # contract's foundation).

    def export_slot(self, slot: int) -> Dict[str, Any]:
        """Serialize an active slot for handoff. The slot stays active
        here -- the caller releases it once the handoff is safely
        published (or keeps decoding if publication failed)."""
        if slot not in self._active:
            raise ValueError(f"slot {slot} is not active")
        snap = self.cache.export_pages(slot)
        snap["next_token"] = int(self._tokens[slot])
        snap["position"] = int(self._positions[slot])
        snap["rng"] = None  # greedy decode: no sampler state
        return snap

    def import_slot(self, snapshot: Dict[str, Any]) -> int:
        """Re-admit a handed-off stream: claims a slot via
        :meth:`PagedKVCache.import_pages` (raising
        :class:`CacheOverflow` on exhaustion -- the caller maps it to
        the structured ``generation_overflow`` refusal), restores the
        slot registers, and joins the running batch. On success the
        CALLER owns the slot and owes :meth:`release` on every path,
        exactly as for :meth:`admit`."""
        slot = self.cache.import_pages(snapshot)  # CacheOverflow
        try:
            self._tokens[slot] = int(snapshot["next_token"])
            self._positions[slot] = int(snapshot["position"])
            self._active.add(slot)
            self._update_gauges()
        except BaseException:
            # a malformed register (non-int next_token) must not
            # strand the pages import_pages just claimed
            self.cache.release(slot)
            self._active.discard(slot)
            raise
        return slot

    def _update_gauges(self) -> None:
        _M_OCC.set(len(self._active))
        _M_KV.set(self.cache.utilization())

    # ------------------------------------------------------- warm-up --
    def warm_up(self) -> "DecodeEngine":
        """Compile the whole prefill ladder and the decode step before
        traffic arrives, flagged warm so N shapes in N seconds don't
        read as a recompile storm. Writes land on the trash page; slot
        state and accounting are untouched."""
        import jax.numpy as jnp

        with warming():
            for bucket in self.ladder:
                if bucket in self._compiled_prefill:
                    continue
                t0 = time.perf_counter()
                kv, _ = self._prefill_jit(
                    self.params, self.cache.kv,
                    jnp.zeros(bucket, jnp.int32),
                    jnp.zeros(bucket // self.page_size, jnp.int32),
                    np.int32(0))
                self.cache.kv = kv
                self._compiled_prefill.add(bucket)
                record_compile("generation.prefill",
                               [((bucket,), "int32")],
                               time.perf_counter() - t0,
                               subsystem="generation", warm=True)
            if not self._step_compiled:
                t0 = time.perf_counter()
                kv, _ = self._step_jit(
                    self.params, self.cache.kv,
                    jnp.zeros(self.num_slots, jnp.int32),
                    jnp.zeros(self.num_slots, jnp.int32),
                    jnp.asarray(self.cache.block_tables()))
                self.cache.kv = kv
                self._step_compiled = True
                record_compile("generation.decode_step",
                               [((self.num_slots,), "int32")],
                               time.perf_counter() - t0,
                               subsystem="generation", warm=True)
        return self

    # --------------------------------------------------------- stats --
    def stats(self) -> Dict[str, Any]:
        return {
            "slots": self.num_slots,
            "active": len(self._active),
            "ladder": list(self.ladder),
            "prefill_buckets_compiled": sorted(self._compiled_prefill),
            "cache": self.cache.stats(),
        }


def engine_from_config(gen_cfg: Dict[str, Any]) -> DecodeEngine:
    """Build an engine from a launcher ``generation:`` YAML block:
    ``model:`` holds :class:`GenModelConfig` fields (the seeded
    builtin LM); ``slots``/``page_size``/``num_pages``/``max_len``
    override the ``zoo.generation.*`` defaults for this launch only."""
    model_cfg = dict(gen_cfg.get("model") or {})
    config = GenModelConfig.from_dict(model_cfg)
    return DecodeEngine(
        TinyGenLM(config),
        num_slots=gen_cfg.get("slots"),
        page_size=gen_cfg.get("page_size"),
        num_pages=gen_cfg.get("num_pages"),
        max_len=gen_cfg.get("max_len"))
