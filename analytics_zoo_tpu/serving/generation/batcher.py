"""ContinuousBatcher: slot admission at decode-step boundaries.

``AdaptiveBatcher``'s role, evolved for generation. The predict
batchers answer "how long do I linger assembling THIS batch" -- a
question that does not exist here, because the decode batch is never
assembled: it is a standing slot table requests join and leave.
What remains of batching policy is *admission pacing*:

- when slots are free, pull up to that many waiting requests in one
  non-blocking sweep (``get_many`` where the backend has it -- one
  lock/broker trip, the deep-backlog fast path);
- when the engine is otherwise **idle** (no active slots), block up to
  ``wait_timeout`` for the first request so an idle worker wakes on
  arrival instead of spinning;
- when the engine is **busy**, never block: a decode step for N live
  streams must not wait on the queue -- a request that arrives
  mid-step joins at the next boundary, which is at most one step away.

The batcher also owns the pull-side chaos seam (same ``pull`` seam as
the predict batchers) and admission wait accounting: ``last_depth``
feeds the queue-depth gauge exactly like ``AdaptiveBatcher`` does, so
the serving dashboard reads the same series for both data planes.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from analytics_zoo_tpu.serving.chaos import chaos_point


class ContinuousBatcher:
    """Admission-side pull policy for :class:`~.worker.GenerationWorker`.

    Args:
      queue: queue-like with ``get(timeout)``; ``get_many(n)`` and
        ``__len__`` are used when available.
      max_admit_per_step: cap on admissions per step boundary (0 =
        bounded only by free slots) -- a guard against one boundary
        paying many prefill stalls back-to-back while live streams
        starve.
    """

    def __init__(self, queue, max_admit_per_step: int = 0):
        self.queue = queue
        self.max_admit_per_step = int(max_admit_per_step)
        self._lock = threading.Lock()
        self._pulls = 0
        self._admitted = 0
        self.last_depth = -1

    def poll(self, n_free: int, wait_timeout: float = 0.05,
             idle: bool = True) -> List[bytes]:
        """Up to ``n_free`` request blobs for this step boundary.
        Blocks (up to ``wait_timeout``) only when ``idle`` -- see the
        module docstring for why a busy engine never waits here."""
        chaos_point("pull")
        if n_free <= 0:
            return []
        if self.max_admit_per_step:
            n_free = min(n_free, self.max_admit_per_step)
        out: List[bytes] = []
        first = self.queue.get(timeout=wait_timeout if idle else 0)
        if first is not None:
            out.append(first)
            if len(out) < n_free and hasattr(self.queue, "get_many"):
                out.extend(self.queue.get_many(n_free - len(out)))
            else:
                while len(out) < n_free:
                    item = self.queue.get(timeout=0)
                    if item is None:
                        break
                    out.append(item)
        try:
            depth = len(self.queue)
        except (TypeError, OSError):
            depth = -1
        with self._lock:
            self._pulls += 1
            self._admitted += len(out)
            self.last_depth = depth
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"pulls": self._pulls, "pulled": self._admitted,
                    "last_depth": self.last_depth,
                    "max_admit_per_step": self.max_admit_per_step}
