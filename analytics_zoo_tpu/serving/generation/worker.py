"""GenerationWorker: the token-streaming serving loop.

The generation data plane's :class:`~..worker.ServingWorker`: pulls
generate requests, admits them into the :class:`~.engine.DecodeEngine`
slot table at step boundaries (continuous batching -- a request joins
the running batch, it never waits for a batch window), and streams
each slot's tokens back as chunked replies the moment they exist.

Reply protocol (all chunks are ordinary wire blobs on the reply/output
stream, so every queue backend and the fleet's consumer-group data
plane carry them unchanged):

- data chunk:      ``{__stream__: seq, token: [k] int32}``
- terminal chunk:  data chunk + ``finish_reason`` ("stop" | "length")
  and ``n_tokens``
- error terminal:  ``{__stream__: -1, __error__: "<prefix>: detail"}``
  -- ``generation_overflow`` for admission refusal (the frontend maps
  it to 503 + Retry-After), ``deadline_exceeded`` when a stream's
  budget ran out mid-decode (the structured mid-stream terminal chunk
  the /generate contract promises).

``seq`` increments per chunk from 0 and is the client's exactly-once
dedup key: greedy decode is deterministic, so a supervisor-restarted
stream (ledger re-queue) regenerates the same tokens and consumers
drop ``seq <= last_seen``. Error terminals ride ``seq = -1`` so a
post-restart failure is never mistaken for a stale duplicate.

Lifecycle seams match ServingWorker exactly -- per-run stop/drain
events, supervision heartbeat, ledger record/settle, consumer-group
ack-on-reply, ``pull``/``decode``/``dispatch``/``finalize``/``push``
chaos points -- so the Supervisor, the drain path, the fleet and the
chaos harness drive both workers through one contract.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.inference.kv_cache import CacheOverflow
from analytics_zoo_tpu.obs.events import emit as emit_event
from analytics_zoo_tpu.obs.flight import get_inflight
from analytics_zoo_tpu.obs.metrics import get_registry
from analytics_zoo_tpu.obs.tracing import get_tracer
from analytics_zoo_tpu.serving.chaos import chaos_point
from analytics_zoo_tpu.serving.generation.batcher import (
    ContinuousBatcher)
from analytics_zoo_tpu.serving.protocol import (
    DEADLINE_PREFIX, ERROR_KEY, GENERATION_PREFIX, INVALID_PREFIX,
    STREAM_KEY, priority_index, priority_name)
from analytics_zoo_tpu.serving.queues import _decode_generation, _encode
from analytics_zoo_tpu.serving.timer import Timer

logger = get_logger(__name__)

# exactly-once-reply obligation (zoolint lifecycle engine): every
# path through these stage methods must reach a reply, error-reply,
# requeue, or ownership hand-off -- the static twin of the ledger
ZOOLINT_REPLY_OBLIGATED = (
    "GenerationWorker._admit_blob",
    "GenerationWorker._finish_stream",
    "GenerationWorker._abort_stream",
)

_REG = get_registry()
_M_REQS = _REG.counter(
    "zoo_generation_requests_total",
    "Generation streams answered (a terminal chunk was pushed: "
    "completions and error terminals)")
_M_TOKENS = _REG.counter(
    "zoo_generation_tokens_total",
    "Tokens generated across all streams (the numerator of the "
    "deployment's tokens/sec)")
_M_ERRORS = _REG.counter(
    "zoo_generation_errors_total",
    "Error terminal chunks pushed (admission refusals, mid-stream "
    "deadlines, internal failures)")
_M_OVERFLOW = _REG.counter(
    "zoo_generation_overflow_total",
    "Generate requests refused at admission because the paged KV "
    "cache had no free slot/pages (503 + Retry-After at the frontend)")
_M_LATENCY = _REG.histogram(
    "zoo_generation_latency_seconds",
    "Generation latency stages: ttft = admission to first token, "
    "inter_token = gap between consecutive tokens of one stream "
    "(the SLO autoscaler's zoo.serving.slo.ttft_ms / inter_token_ms "
    "inputs)",
    labelnames=("stage",))


class _GenStream:
    """Host-side state of one live stream (one engine slot)."""

    __slots__ = ("uri", "reply", "trace", "deadline", "eos",
                 "max_tokens", "priority", "produced", "pending",
                 "seq", "admitted_at", "last_token_at")

    def __init__(self, uri, reply, trace, deadline, eos, max_tokens,
                 priority=None):
        self.uri = uri
        self.reply = reply
        self.trace = trace
        self.deadline = deadline
        self.eos = eos
        self.max_tokens = max_tokens
        self.priority = priority
        self.produced = 0      # tokens generated so far
        self.pending: List[int] = []  # generated, not yet chunked
        self.seq = 0           # next chunk sequence number
        self.admitted_at = time.monotonic()
        self.last_token_at: Optional[float] = None


class GenerationWorker:
    """Continuous-batching generation server over the serving queues.

    Args:
      engine: a warmed :class:`~.engine.DecodeEngine`.
      input_queue / output_queue: the serving queues (request blobs
        carry ``tokens`` + the generation wire keys; chunks go to the
        reply-to stream when the request names one, else the default
        output queue -- the ServingWorker routing contract).
      max_tokens / eos: per-deployment defaults when a request omits
        ``__max_tokens__``/``__eos__`` (None reads
        ``zoo.generation.max_tokens``; eos default -1 = none).
      stream_chunk_tokens: tokens per data chunk (None reads
        ``zoo.generation.stream_chunk_tokens``; 1 = stream every
        token as it exists -- lowest TTFT-to-client, most chunks).
    """

    def __init__(self, engine, input_queue, output_queue,
                 max_tokens: Optional[int] = None,
                 eos: Optional[int] = None,
                 stream_chunk_tokens: Optional[int] = None):
        cfg = get_config()
        self.engine = engine
        self._in = getattr(input_queue, "queue", input_queue)
        self._out_q = output_queue
        self.batcher = ContinuousBatcher(self._in)
        self.default_max_tokens = int(
            cfg.get("zoo.generation.max_tokens", 64)
            if max_tokens is None else max_tokens)
        self.default_eos = -1 if eos is None else int(eos)
        self.stream_chunk_tokens = max(1, int(
            cfg.get("zoo.generation.stream_chunk_tokens", 1)
            if stream_chunk_tokens is None else stream_chunk_tokens))
        self.step_idle_s = float(
            cfg.get("zoo.generation.step_idle_ms", 5.0)) / 1000.0
        self._streams: Dict[int, _GenStream] = {}
        self._reply_queues: Dict[str, Any] = {}
        self.served = 0
        # SLO surfaces (ISSUE-15): TTFT and inter-token samples feed
        # the fleet's SLO-driven autoscaler via metrics()["latency"]
        self._lat = Timer(keep_samples=4096, mirror=_M_LATENCY)
        self._default_priority = priority_index(
            cfg.get("zoo.serving.priority.default_class",
                    "interactive")) or 0
        self._class_served: Dict[str, int] = {}
        # supervision / fleet seams (the ServingWorker contract): the
        # Supervisor reads heartbeat/_thread/_stop/_drain and clears
        # _inflight on restart; consumer-group backends expose
        # ack_uris; a Supervisor attaches the ledger
        self.ledger = None
        self._acker = getattr(self._in, "ack_uris", None)
        self._stop = threading.Event()
        self._drain = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._inflight: collections.deque = collections.deque()
        self.heartbeat = time.monotonic()
        self.heartbeat_decode: Optional[float] = None

    # ----------------------------------------------------------- run --
    def run(self, max_steps: Optional[int] = None,
            wait_timeout: Optional[float] = None) -> int:
        """Serve until stopped (or ``max_steps`` decode steps);
        returns terminal replies pushed in this call. A draining run
        admits nothing new, finishes every live stream, then exits
        cleanly -- the seam SIGTERM and rolling restarts share.
        ``wait_timeout`` is the idle poll patience; None reads
        ``zoo.generation.step_idle_ms`` (bounded runs/tests pass their
        own)."""
        stop_ev = self._stop  # per-run capture: a supervisor restart
        drain_ev = self._drain  # hands the next run fresh events
        idle_wait = (self.step_idle_s if wait_timeout is None
                     else wait_timeout)
        total = 0
        steps = 0
        while not stop_ev.is_set():
            self.heartbeat = time.monotonic()
            draining = drain_ev.is_set()
            if not draining:
                free = self.engine.free_slots()
                if free > 0:
                    idle = not self._streams
                    blobs = self.batcher.poll(
                        free, wait_timeout=idle_wait, idle=idle)
                    for blob in blobs:
                        total += self._admit_blob(blob)
            if not self._streams:
                if draining:
                    break
                if max_steps is not None and steps >= max_steps:
                    break
                continue  # the idle poll above already waited
            chaos_point("dispatch")
            try:
                results = self.engine.step()
            except Exception as e:
                # a step failure strands every live stream: give each
                # one structured terminal error instead of a silent
                # stall (the engine's slot state stays consistent --
                # step() commits nothing on raise)
                logger.exception("generation step failed: %s", e)
                for slot in list(self._streams):
                    total += self._abort_stream(
                        slot, f"generation step failed: {e}")
                continue
            steps += 1
            total += self._finalize_results(results)
            if max_steps is not None and steps >= max_steps:
                break
        return total

    def serve_forever(self) -> None:
        try:
            self.run()
        except BaseException as e:
            emit_event("worker_crash", "generation",
                       error=repr(e)[:500], served=self.served)
            raise

    # ----------------------------------------------------- admission --
    def _admit_blob(self, blob: bytes) -> int:
        """Decode + admit one request at a step boundary; returns the
        terminal replies pushed (0 for a live admission, 1 when the
        request was refused/expired/finished instantly)."""
        chaos_point("decode")
        try:
            (uri, tensors, reply, trace, deadline, max_toks,
             eos, priority) = _decode_generation(blob)
        except Exception as e:
            logger.exception(
                "generation: undecodable request dropped: %s", e)
            # intentional drop: an undecodable blob has no uri/reply
            # channel to answer on -- logging IS the accounting here
            return 0  # zoolint: disable=reply-missing-on-path
        if self.ledger is not None:
            self.ledger.record(uri, blob)
        if deadline is not None and time.time() > deadline:
            self._push_error(
                uri, reply,
                f"{DEADLINE_PREFIX}: request missed its deadline "
                "before admission")
            return 1
        if max_toks is None:
            max_toks = self.default_max_tokens
        # admission always yields at least the prefill's first token,
        # so a <1 budget (direct-queue clients; the frontend already
        # 400s it) is served as 1, not refused
        max_toks = max(1, int(max_toks))
        if eos is None:
            eos = self.default_eos
        prompt = tensors.get("tokens")
        if prompt is None and len(tensors) == 1:
            prompt = next(iter(tensors.values()))
        if prompt is None:
            self._push_error(
                uri, reply,
                f"{INVALID_PREFIX}: generate request needs a "
                "'tokens' tensor (int prompt)")
            return 1
        t0 = time.perf_counter()
        try:
            slot, tok0 = self.engine.admit(prompt, max_toks)
        except ValueError as e:
            # malformed CLIENT content past the frontend's shape
            # checks (out-of-vocab ids, empty prompt): a structured
            # 400, a warning (no traceback -- an unauthenticated
            # client must not be able to flood exception logs or make
            # bad input read as server faults)
            logger.warning("generation: invalid request %s: %s",
                           uri, e)
            self._push_error(uri, reply, f"{INVALID_PREFIX}: {e}")
            return 1
        except CacheOverflow as e:
            _M_OVERFLOW.inc()
            stats = self.engine.cache.stats()
            emit_event("generation_overflow", "generation", uri=uri,
                       need_pages=self.engine.cache.pages_for(
                           int(np.asarray(prompt).size) + max_toks),
                       free_pages=stats["num_pages"]
                       - stats["pages_assigned"],
                       free_slots=stats["slots_free"])
            self._push_error(uri, reply, f"{GENERATION_PREFIX}: {e}")
            return 1
        except Exception as e:
            logger.exception("generation admit failed for %s: %s",
                             uri, e)
            self._push_error(uri, reply, str(e))
            return 1
        try:
            if trace:
                get_tracer().add_span("gen_prefill", trace, t0,
                                      time.perf_counter())
            get_inflight().add((uri,))
            stream = _GenStream(
                uri, reply, trace, deadline, eos, max_toks,
                priority=(self._default_priority
                          if priority is None else priority))
            self._streams[slot] = stream
            cls = priority_name(stream.priority)
            self._class_served[cls] = (
                self._class_served.get(cls, 0) + 1)
        except BaseException:
            # nothing owns the slot until the stream table does: a
            # raise in this window (tracer, crash manifest, stream
            # allocation) would leak the KV reservation until restart
            # -- the admit-path capacity leak leak-on-path guards
            self.engine.release(slot)
            raise
        emit_event("generation_admit", "generation", uri=uri,
                   slot=slot, prompt_len=int(np.asarray(prompt).size),
                   bucket=next(b for b in self.engine.ladder
                               if b >= np.asarray(prompt).size))
        return self._accept_token(slot, stream, tok0)

    # ------------------------------------------------------ stepping --
    def _finalize_results(self, results) -> int:
        """Route one decode step's tokens into their streams: deadline
        checks, chunk flushes, terminal pushes. Returns terminal
        replies pushed."""
        chaos_point("finalize")
        n = 0
        for slot, tok in results:
            stream = self._streams.get(slot)
            if stream is None:
                continue  # lane freed earlier this same step batch
            if (stream.deadline is not None
                    and time.time() > stream.deadline):
                n += self._abort_stream(
                    slot,
                    f"{DEADLINE_PREFIX}: stream missed its deadline "
                    f"after {stream.produced} tokens")
                continue
            n += self._accept_token(slot, stream, tok)
        return n

    def _accept_token(self, slot: int, stream: _GenStream,
                      tok: int) -> int:
        """Append one generated token; flush/terminate as policy
        dictates. Returns 1 when this token finished the stream."""
        now = time.monotonic()
        if stream.produced == 0:
            self._lat.record("ttft", now - stream.admitted_at)
        elif stream.last_token_at is not None:
            self._lat.record("inter_token", now - stream.last_token_at)
        stream.last_token_at = now
        stream.pending.append(int(tok))
        stream.produced += 1
        _M_TOKENS.inc()
        if stream.eos >= 0 and int(tok) == stream.eos:
            return self._finish_stream(slot, stream, "stop")
        if stream.produced >= stream.max_tokens:
            return self._finish_stream(slot, stream, "length")
        if len(stream.pending) >= self.stream_chunk_tokens:
            self._push_chunk(stream)
        return 0

    # -------------------------------------------------------- pushes --
    def _push_chunk(self, stream: _GenStream, final: bool = False,
                    reason: Optional[str] = None) -> None:
        payload: Dict[str, np.ndarray] = {
            STREAM_KEY: np.asarray(stream.seq, np.int32)}
        if stream.pending:
            payload["token"] = np.asarray(stream.pending, np.int32)
        if final:
            payload["finish_reason"] = np.asarray(reason)
            payload["n_tokens"] = np.asarray(stream.produced, np.int32)
        stream.seq += 1
        stream.pending = []
        if chaos_point("push"):
            return  # injected drop-chunk
        backend = self._reply_backend(stream.reply)
        if not backend.put(_encode(stream.uri, payload)):
            logger.warning("output queue full: dropping chunk for %s",
                           stream.uri)

    def _finish_stream(self, slot: int, stream: _GenStream,
                       reason: str) -> int:
        """Terminal chunk + slot release + settlement: the stream
        leaves the running batch at this step boundary."""
        self._push_chunk(stream, final=True, reason=reason)
        self._settle(stream.uri)
        emit_event("generation_complete", "generation", uri=stream.uri,
                   slot=slot, tokens=stream.produced, reason=reason)
        if stream.trace:
            get_tracer().add_span(
                "gen_stream", stream.trace, stream.admitted_at,
                time.monotonic(), tokens=stream.produced)
        self.engine.release(slot)
        self._streams.pop(slot, None)
        self.served += 1
        _M_REQS.inc()
        return 1

    def _abort_stream(self, slot: int, message: str) -> int:
        """Mid-stream failure: structured error terminal, then the
        slot frees exactly like a completion."""
        stream = self._streams.pop(slot, None)
        if stream is None:
            # no stream owns the slot: nothing was admitted, so there
            # is no request to answer (abort raced a finished stream)
            return 0  # zoolint: disable=reply-missing-on-path
        self._push_error(stream.uri, stream.reply, message)
        self.engine.release(slot)
        self.served += 1
        return 1

    def _push_error(self, uri: str, reply: Optional[str],
                    message: str) -> None:
        """Error terminal chunk (``seq = -1``: never deduped away).
        Also the Supervisor's ``_reply_error`` seam -- give-up and
        double-crash replies arrive through here."""
        _M_ERRORS.inc()
        _M_REQS.inc()
        if message.startswith(DEADLINE_PREFIX):
            emit_event("deadline_exceeded", "generation", uri=uri,
                       error=message[:500])
        elif not message.startswith((GENERATION_PREFIX,
                                     INVALID_PREFIX)):
            # overflow refusals already emitted generation_overflow
            # with capacity fields, and invalid_request is client
            # noise an unauthenticated caller could use to churn the
            # event ring; everything else is rare by construction ->
            # one structured event per error
            emit_event("serving_error", "generation", uri=uri,
                       error=message[:500])
        self._settle(uri)
        payload = {STREAM_KEY: np.asarray(-1, np.int32),
                   ERROR_KEY: np.asarray(message)}
        if chaos_point("push"):
            return
        backend = self._reply_backend(reply)
        if not backend.put(_encode(uri, payload)):
            logger.warning("output queue full: dropping error for %s",
                           uri)

    def _settle(self, uri: str) -> None:
        """One settlement point: ledger + crash-manifest + stream-claim
        ack -- the request is answered, nothing may re-serve it."""
        get_inflight().discard((uri,))
        if self.ledger is not None:
            self.ledger.settle((uri,))
        if self._acker is not None:
            try:
                self._acker((uri,))
            except Exception as e:
                logger.warning("input ack for %s failed: %s", uri, e)

    def _reply_backend(self, reply_to: Optional[str]):
        default = getattr(self._out_q, "queue", self._out_q)
        if not reply_to:
            return default
        maker = getattr(default, "for_stream", None)
        if maker is None:
            return default
        if reply_to not in self._reply_queues:
            self._reply_queues[reply_to] = maker(reply_to)
        return self._reply_queues[reply_to]

    # ----------------------------------------------------- lifecycle --
    def start(self) -> "GenerationWorker":
        # fresh per-run events (the ServingWorker restart contract);
        # slots a dead run left occupied are released here -- their
        # requests are ledger-outstanding and re-arrive via the
        # supervisor's re-queue, regenerating deterministically
        self._reset_streams()
        self._stop = threading.Event()
        self._drain = threading.Event()
        self.heartbeat = time.monotonic()
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True,
                                        name="generation-worker")
        self._thread.start()
        emit_event("worker_start", "generation",
                   slots=self.engine.num_slots,
                   max_tokens=self.default_max_tokens)
        return self

    def _reset_streams(self) -> None:
        for slot in list(self._streams):
            self._streams.pop(slot, None)
            self.engine.release(slot)

    def stop(self, join_timeout: float = 5.0) -> None:
        emit_event("worker_stop", "generation", served=self.served)
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(join_timeout)
            if thread.is_alive():
                logger.warning(
                    "generation worker still busy after %.1fs",
                    join_timeout)
                return
            self._thread = None

    def drain(self, deadline_s: Optional[float] = None) -> bool:
        """Stop admitting, finish every live stream, within the
        budget (default ``zoo.serving.drain.deadline_ms``). True =
        fully drained in time."""
        if deadline_s is None:
            deadline_s = float(get_config().get(
                "zoo.serving.drain.deadline_ms", 10000.0)) / 1000.0
        pause = getattr(self._in, "pause", None)
        if pause is not None:
            pause()  # brokered consumer: stop CLAIMING, not just
            # stop pulling claimed entries
        self._drain.set()
        thread = self._thread
        if thread is None:
            return True
        thread.join(max(0.0, deadline_s))
        if thread.is_alive():
            return False
        self._thread = None
        return True

    # ------------------------------------------------------- metrics --
    def metrics(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "served": self.served,
            "streams_active": len(self._streams),
            "engine": self.engine.stats(),
            "batcher": self.batcher.stats(),
            "defaults": {"max_tokens": self.default_max_tokens,
                         "eos": self.default_eos,
                         "chunk_tokens": self.stream_chunk_tokens},
            # latency.ttft / latency.inter_token summaries (p99_s
            # etc.) -- the fleet's SLO sampler scrapes these
            "latency": self._lat.summary(),
            "class_served": dict(self._class_served),
        }
        try:
            out["queue_depth"] = len(self._in)
        except (TypeError, OSError):
            pass
        if self.ledger is not None:
            out["ledger_outstanding"] = len(self.ledger)
        return out
