"""GenerationWorker: the token-streaming serving loop.

The generation data plane's :class:`~..worker.ServingWorker`: pulls
generate requests, admits them into the :class:`~.engine.DecodeEngine`
slot table at step boundaries (continuous batching -- a request joins
the running batch, it never waits for a batch window), and streams
each slot's tokens back as chunked replies the moment they exist.

Reply protocol (all chunks are ordinary wire blobs on the reply/output
stream, so every queue backend and the fleet's consumer-group data
plane carry them unchanged):

- data chunk:      ``{__stream__: seq, token: [k] int32}``
- terminal chunk:  data chunk + ``finish_reason`` ("stop" | "length")
  and ``n_tokens``
- error terminal:  ``{__stream__: -1, __error__: "<prefix>: detail"}``
  -- ``generation_overflow`` for admission refusal (the frontend maps
  it to 503 + Retry-After), ``deadline_exceeded`` when a stream's
  budget ran out mid-decode (the structured mid-stream terminal chunk
  the /generate contract promises).

``seq`` increments per chunk from 0 and is the client's exactly-once
dedup key: greedy decode is deterministic, so a supervisor-restarted
stream (ledger re-queue) regenerates the same tokens and consumers
drop ``seq <= last_seen``. Error terminals ride ``seq = -1`` so a
post-restart failure is never mistaken for a stale duplicate.

Lifecycle seams match ServingWorker exactly -- per-run stop/drain
events, supervision heartbeat, ledger record/settle, consumer-group
ack-on-reply, ``pull``/``decode``/``dispatch``/``finalize``/``push``
chaos points -- so the Supervisor, the drain path, the fleet and the
chaos harness drive both workers through one contract.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.inference.kv_cache import CacheOverflow
from analytics_zoo_tpu.obs.events import emit as emit_event
from analytics_zoo_tpu.obs.flight import get_inflight
from analytics_zoo_tpu.obs.metrics import get_registry
from analytics_zoo_tpu.obs.tracing import get_tracer
from analytics_zoo_tpu.serving.chaos import chaos_point
from analytics_zoo_tpu.serving.generation.batcher import (
    ContinuousBatcher)
from analytics_zoo_tpu.serving.protocol import (
    DEADLINE_PREFIX, ERROR_KEY, GENERATION_PREFIX, INVALID_PREFIX,
    STREAM_KEY, priority_index, priority_name)
from analytics_zoo_tpu.serving.queues import (
    _decode_generation, _decode_handoff, _discard_handoff, _encode,
    _encode_handoff)
from analytics_zoo_tpu.serving.timer import Timer

logger = get_logger(__name__)

# exactly-once-reply obligation (zoolint lifecycle engine): every
# path through these stage methods must reach a reply, error-reply,
# requeue, or ownership hand-off -- the static twin of the ledger
ZOOLINT_REPLY_OBLIGATED = (
    "GenerationWorker._admit_blob",
    "GenerationWorker._finish_stream",
    "GenerationWorker._abort_stream",
    "GenerationWorker._handoff_slot",
    "GenerationWorker._import_blob",
)

_REG = get_registry()
_M_REQS = _REG.counter(
    "zoo_generation_requests_total",
    "Generation streams answered (a terminal chunk was pushed: "
    "completions and error terminals)")
_M_TOKENS = _REG.counter(
    "zoo_generation_tokens_total",
    "Tokens generated across all streams (the numerator of the "
    "deployment's tokens/sec)")
_M_ERRORS = _REG.counter(
    "zoo_generation_errors_total",
    "Error terminal chunks pushed (admission refusals, mid-stream "
    "deadlines, internal failures)")
_M_OVERFLOW = _REG.counter(
    "zoo_generation_overflow_total",
    "Generate requests refused at admission because the paged KV "
    "cache had no free slot/pages (503 + Retry-After at the frontend)")
_M_LATENCY = _REG.histogram(
    "zoo_generation_latency_seconds",
    "Generation latency stages: ttft = admission to first token, "
    "inter_token = gap between consecutive tokens of one stream "
    "(the SLO autoscaler's zoo.serving.slo.ttft_ms / inter_token_ms "
    "inputs)",
    labelnames=("stage",))
_M_HANDOFF = _REG.counter(
    "zoo_generation_handoff_total",
    "Prefill->decode stream handoffs by stage: export (prefill "
    "published a stream), import (decode restored one from its KV "
    "snapshot), regen (decode re-prefilled deterministically because "
    "the snapshot was dropped), moved (a draining decode replica "
    "re-published a live stream), refused (import hit cache "
    "exhaustion -> generation_overflow)",
    labelnames=("stage",))


class _GenStream:
    """Host-side state of one live stream (one engine slot)."""

    __slots__ = ("uri", "reply", "trace", "deadline", "eos",
                 "max_tokens", "priority", "produced", "pending",
                 "seq", "admitted_at", "last_token_at", "prompt")

    def __init__(self, uri, reply, trace, deadline, eos, max_tokens,
                 priority=None, prompt=None):
        self.uri = uri
        self.reply = reply
        self.trace = trace
        self.deadline = deadline
        self.eos = eos
        self.max_tokens = max_tokens
        self.priority = priority
        self.produced = 0      # tokens generated so far
        self.pending: List[int] = []  # generated, not yet chunked
        self.seq = 0           # next chunk sequence number
        self.admitted_at = time.monotonic()
        self.last_token_at: Optional[float] = None
        # original prompt tokens -- a decode-role worker keeps them so
        # a drain-time re-handoff stays regenerable downstream even
        # when the KV snapshot must be dropped (ISSUE-20)
        self.prompt = prompt


class GenerationWorker:
    """Continuous-batching generation server over the serving queues.

    Args:
      engine: a warmed :class:`~.engine.DecodeEngine`.
      input_queue / output_queue: the serving queues (request blobs
        carry ``tokens`` + the generation wire keys; chunks go to the
        reply-to stream when the request names one, else the default
        output queue -- the ServingWorker routing contract).
      max_tokens / eos: per-deployment defaults when a request omits
        ``__max_tokens__``/``__eos__`` (None reads
        ``zoo.generation.max_tokens``; eos default -1 = none).
      stream_chunk_tokens: tokens per data chunk (None reads
        ``zoo.generation.stream_chunk_tokens``; 1 = stream every
        token as it exists -- lowest TTFT-to-client, most chunks).
      role: disaggregated pool role (ISSUE-20). "unified" (default)
        admits AND decodes, the historical behavior. "prefill" admits
        + prefills, then exports the slot's KV pages and publishes the
        stream to ``handoff_queue`` (the broker's handoff stream) --
        it never decodes. "decode" consumes handoff blobs from
        ``input_queue``, imports the snapshot (or deterministically
        re-prefills when it was dropped) and streams tokens; on drain
        it re-publishes live streams to ``handoff_queue`` so a
        survivor continues them.
      handoff_queue: producer to the handoff stream (required for
        "prefill", used for drain re-handoff by "decode").
    """

    def __init__(self, engine, input_queue, output_queue,
                 max_tokens: Optional[int] = None,
                 eos: Optional[int] = None,
                 stream_chunk_tokens: Optional[int] = None,
                 role: str = "unified",
                 handoff_queue=None):
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"unknown generation role {role!r}: expected "
                "unified | prefill | decode")
        cfg = get_config()
        self.engine = engine
        self.role = role
        self._in = getattr(input_queue, "queue", input_queue)
        self._out_q = output_queue
        self._handoff_out = (getattr(handoff_queue, "queue",
                                     handoff_queue)
                             if handoff_queue is not None else None)
        if role == "prefill" and self._handoff_out is None:
            raise ValueError("prefill role needs a handoff_queue")
        self.handoff_max_bytes = int(cfg.get(
            "zoo.serving.fleet.handoff_max_bytes", 8388608))
        self.batcher = ContinuousBatcher(self._in)
        self.default_max_tokens = int(
            cfg.get("zoo.generation.max_tokens", 64)
            if max_tokens is None else max_tokens)
        self.default_eos = -1 if eos is None else int(eos)
        self.stream_chunk_tokens = max(1, int(
            cfg.get("zoo.generation.stream_chunk_tokens", 1)
            if stream_chunk_tokens is None else stream_chunk_tokens))
        self.step_idle_s = float(
            cfg.get("zoo.generation.step_idle_ms", 5.0)) / 1000.0
        self._streams: Dict[int, _GenStream] = {}
        self._reply_queues: Dict[str, Any] = {}
        self.served = 0
        # SLO surfaces (ISSUE-15): TTFT and inter-token samples feed
        # the fleet's SLO-driven autoscaler via metrics()["latency"]
        self._lat = Timer(keep_samples=4096, mirror=_M_LATENCY)
        self._default_priority = priority_index(
            cfg.get("zoo.serving.priority.default_class",
                    "interactive")) or 0
        self._class_served: Dict[str, int] = {}
        self._handoff_counts: Dict[str, int] = {}
        # supervision / fleet seams (the ServingWorker contract): the
        # Supervisor reads heartbeat/_thread/_stop/_drain and clears
        # _inflight on restart; consumer-group backends expose
        # ack_uris; a Supervisor attaches the ledger
        self.ledger = None
        self._acker = getattr(self._in, "ack_uris", None)
        self._stop = threading.Event()
        self._drain = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._inflight: collections.deque = collections.deque()
        self.heartbeat = time.monotonic()
        self.heartbeat_decode: Optional[float] = None

    # ----------------------------------------------------------- run --
    def run(self, max_steps: Optional[int] = None,
            wait_timeout: Optional[float] = None) -> int:
        """Serve until stopped (or ``max_steps`` decode steps);
        returns terminal replies pushed in this call. A draining run
        admits nothing new, finishes every live stream, then exits
        cleanly -- the seam SIGTERM and rolling restarts share.
        ``wait_timeout`` is the idle poll patience; None reads
        ``zoo.generation.step_idle_ms`` (bounded runs/tests pass their
        own)."""
        stop_ev = self._stop  # per-run capture: a supervisor restart
        drain_ev = self._drain  # hands the next run fresh events
        idle_wait = (self.step_idle_s if wait_timeout is None
                     else wait_timeout)
        total = 0
        steps = 0
        while not stop_ev.is_set():
            self.heartbeat = time.monotonic()
            draining = drain_ev.is_set()
            if (draining and self.role == "decode" and self._streams
                    and self._handoff_out is not None):
                # drain moves in-flight decode streams (ISSUE-20):
                # re-publish each live stream's KV snapshot + replay
                # state so a surviving decode replica continues it;
                # streams the publish could not move finish here
                total += self._rehandoff_streams()
            if not draining:
                free = self.engine.free_slots()
                if free > 0:
                    idle = not self._streams
                    blobs = self.batcher.poll(
                        free, wait_timeout=idle_wait, idle=idle)
                    for blob in blobs:
                        total += (self._import_blob(blob)
                                  if self.role == "decode"
                                  else self._admit_blob(blob))
            if not self._streams:
                if draining:
                    break
                if max_steps is not None and steps >= max_steps:
                    break
                continue  # the idle poll above already waited
            chaos_point("dispatch")
            try:
                results = self.engine.step()
            except Exception as e:
                # a step failure strands every live stream: give each
                # one structured terminal error instead of a silent
                # stall (the engine's slot state stays consistent --
                # step() commits nothing on raise)
                logger.exception("generation step failed: %s", e)
                for slot in list(self._streams):
                    total += self._abort_stream(
                        slot, f"generation step failed: {e}")
                continue
            steps += 1
            total += self._finalize_results(results)
            if max_steps is not None and steps >= max_steps:
                break
        return total

    def serve_forever(self) -> None:
        try:
            self.run()
        except BaseException as e:
            emit_event("worker_crash", "generation",
                       error=repr(e)[:500], served=self.served)
            raise

    # ----------------------------------------------------- admission --
    def _admit_blob(self, blob: bytes) -> int:
        """Decode + admit one request at a step boundary; returns the
        terminal replies pushed (0 for a live admission, 1 when the
        request was refused/expired/finished instantly)."""
        chaos_point("decode")
        try:
            (uri, tensors, reply, trace, deadline, max_toks,
             eos, priority) = _decode_generation(blob)
        except Exception as e:
            logger.exception(
                "generation: undecodable request dropped: %s", e)
            # intentional drop: an undecodable blob has no uri/reply
            # channel to answer on -- logging IS the accounting here
            return 0  # zoolint: disable=reply-missing-on-path
        if self.ledger is not None:
            self.ledger.record(uri, blob)
        if deadline is not None and time.time() > deadline:
            self._push_error(
                uri, reply,
                f"{DEADLINE_PREFIX}: request missed its deadline "
                "before admission")
            return 1
        if max_toks is None:
            max_toks = self.default_max_tokens
        # admission always yields at least the prefill's first token,
        # so a <1 budget (direct-queue clients; the frontend already
        # 400s it) is served as 1, not refused
        max_toks = max(1, int(max_toks))
        if eos is None:
            eos = self.default_eos
        prompt = tensors.get("tokens")
        if prompt is None and len(tensors) == 1:
            prompt = next(iter(tensors.values()))
        if prompt is None:
            self._push_error(
                uri, reply,
                f"{INVALID_PREFIX}: generate request needs a "
                "'tokens' tensor (int prompt)")
            return 1
        t0 = time.perf_counter()
        try:
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            slot, tok0 = self.engine.admit(prompt, max_toks)
        except ValueError as e:
            # malformed CLIENT content past the frontend's shape
            # checks (out-of-vocab ids, empty prompt): a structured
            # 400, a warning (no traceback -- an unauthenticated
            # client must not be able to flood exception logs or make
            # bad input read as server faults)
            logger.warning("generation: invalid request %s: %s",
                           uri, e)
            self._push_error(uri, reply, f"{INVALID_PREFIX}: {e}")
            return 1
        except CacheOverflow as e:
            _M_OVERFLOW.inc()
            stats = self.engine.cache.stats()
            emit_event("generation_overflow", "generation", uri=uri,
                       need_pages=self.engine.cache.pages_for(
                           int(np.asarray(prompt).size) + max_toks),
                       free_pages=stats["num_pages"]
                       - stats["pages_assigned"],
                       free_slots=stats["slots_free"])
            self._push_error(uri, reply, f"{GENERATION_PREFIX}: {e}")
            return 1
        except Exception as e:
            logger.exception("generation admit failed for %s: %s",
                             uri, e)
            self._push_error(uri, reply, str(e))
            return 1
        if self.role == "prefill":
            # prefill pool (ISSUE-20): this worker's part of the
            # stream ends at the handoff publish -- no stream-table
            # entry, no decode steps
            return self._handoff_slot(
                slot, uri, prompt, tok0, reply, trace, deadline,
                eos, max_toks, priority)
        try:
            if trace:
                get_tracer().add_span("gen_prefill", trace, t0,
                                      time.perf_counter())
            get_inflight().add((uri,))
            stream = _GenStream(
                uri, reply, trace, deadline, eos, max_toks,
                priority=(self._default_priority
                          if priority is None else priority),
                prompt=prompt)
            self._streams[slot] = stream
            cls = priority_name(stream.priority)
            self._class_served[cls] = (
                self._class_served.get(cls, 0) + 1)
        except BaseException:
            # nothing owns the slot until the stream table does: a
            # raise in this window (tracer, crash manifest, stream
            # allocation) would leak the KV reservation until restart
            # -- the admit-path capacity leak leak-on-path guards
            self.engine.release(slot)
            raise
        emit_event("generation_admit", "generation", uri=uri,
                   slot=slot, prompt_len=int(np.asarray(prompt).size),
                   bucket=next(b for b in self.engine.ladder
                               if b >= np.asarray(prompt).size))
        return self._accept_token(slot, stream, tok0)

    # ------------------------------------------------------- handoff --
    def _handoff_slot(self, slot: int, uri: str, prompt: np.ndarray,
                      tok0: int, reply, trace, deadline, eos,
                      max_toks: int, priority) -> int:
        """Prefill role: export the freshly prefilled slot and publish
        the stream to the decode pool; the slot frees here either way
        (on a failed publish the client gets a retryable structured
        refusal -- the stream has no owner to decode it)."""
        snap = None
        try:
            snap = self.engine.export_slot(slot)
            state = {"next_token": int(tok0),
                     "position": int(snap["position"]),
                     "produced": 0, "seq": 0, "emitted": 0}
            blob = _encode_handoff(
                uri, prompt, state, snap, reply_to=reply,
                trace_id=trace, deadline=deadline,
                max_tokens=max_toks, eos=eos, priority=priority,
                max_bytes=self.handoff_max_bytes)
        except Exception as e:
            logger.exception("handoff export failed for %s: %s",
                             uri, e)
            _discard_handoff(snap)
            self.engine.release(slot)
            self._push_error(uri, reply, str(e))
            return 1
        self.engine.release(slot)
        ok = self._handoff_out.put(blob)
        if not ok:
            self._push_error(
                uri, reply,
                f"{GENERATION_PREFIX}: handoff stream full")
            return 1
        self._count_handoff("export")
        # "ttft" on a prefill replica = admission to handoff publish
        # (prefill + export + publish): the prefill pool's
        # SLO-attainment signal -- the client-visible first token
        # lands after the decode side imports
        emit_event("kv_handoff", "generation", uri=uri, slot=slot,
                   prompt_len=int(prompt.size),
                   inline_kv=int(snap["kv"].nbytes
                                 <= self.handoff_max_bytes
                                 or not self.handoff_max_bytes))
        self._settle(uri)
        self.served += 1
        return 1

    def _import_blob(self, blob: bytes) -> int:
        """Decode role: restore one handed-off stream at a step
        boundary -- import its KV snapshot, or deterministically
        re-prefill from the prompt when the snapshot was dropped (or
        belonged to a dead pool geometry). Returns terminal replies
        pushed, exactly like :meth:`_admit_blob`."""
        chaos_point("decode")
        try:
            (uri, handoff, reply, trace, deadline, max_toks,
             eos, priority) = _decode_handoff(blob)
        except Exception as e:
            logger.exception(
                "generation: undecodable handoff dropped: %s", e)
            # intentional drop: no uri/reply channel to answer on
            return 0  # zoolint: disable=reply-missing-on-path
        if self.ledger is not None:
            self.ledger.record(uri, blob)
        if deadline is not None and time.time() > deadline:
            self._push_error(
                uri, reply,
                f"{DEADLINE_PREFIX}: stream missed its deadline "
                f"after {int(handoff['produced'])} tokens")
            return 1
        if max_toks is None:
            max_toks = self.default_max_tokens
        max_toks = max(1, int(max_toks))
        if eos is None:
            eos = self.default_eos
        prompt = handoff["prompt"]
        tok0 = int(handoff["next_token"])
        snap = handoff["snapshot"]
        if snap is not None:
            try:
                slot = self.engine.import_slot(snap)
            except CacheOverflow as e:
                self._count_handoff("refused")
                _M_OVERFLOW.inc()
                self._push_error(uri, reply,
                                 f"{GENERATION_PREFIX}: {e}")
                return 1
            except ValueError as e:
                # snapshot geometry does not match this pool (mixed
                # engine configs): fall through to deterministic
                # regeneration rather than stranding the stream
                logger.warning(
                    "handoff snapshot for %s unusable (%s); "
                    "re-prefilling", uri, e)
            else:
                try:
                    get_inflight().add((uri,))
                    stream = _GenStream(
                        uri, reply, trace, deadline, eos, max_toks,
                        priority=(self._default_priority
                                  if priority is None else priority),
                        prompt=prompt)
                    # continue mid-stream: chunk seqs resume where
                    # the previous owner stopped, so the client sees
                    # one gapless sequence
                    stream.produced = int(handoff["produced"])
                    stream.seq = int(handoff["seq"])
                    self._streams[slot] = stream
                    cls = priority_name(stream.priority)
                    self._class_served[cls] = (
                        self._class_served.get(cls, 0) + 1)
                except BaseException:
                    self.engine.release(slot)
                    raise
                self._count_handoff("import")
                emit_event("kv_import", "generation", uri=uri,
                           slot=slot, regenerated=0,
                           produced=stream.produced)
                if not int(handoff["emitted"]):
                    # the next-input token has not reached the client
                    # yet (fresh prefill handoff): emit it now
                    return self._accept_token(slot, stream, tok0)
                return 0
        # deterministic regeneration: the snapshot was size-dropped at
        # publish or unusable here -- re-prefill from the prompt and
        # replay from scratch (produced=0, seq=0): greedy decode
        # re-emits identical chunks and consumers drop
        # seq <= last_seen -- the exactly-once contract's
        # determinism leg
        try:
            slot, tok0 = self.engine.admit(prompt, max_toks)
        except ValueError as e:
            logger.warning("generation: invalid handoff %s: %s",
                           uri, e)
            self._push_error(uri, reply, f"{INVALID_PREFIX}: {e}")
            return 1
        except CacheOverflow as e:
            self._count_handoff("refused")
            _M_OVERFLOW.inc()
            self._push_error(uri, reply,
                             f"{GENERATION_PREFIX}: {e}")
            return 1
        except Exception as e:
            logger.exception(
                "handoff re-prefill failed for %s: %s", uri, e)
            self._push_error(uri, reply, str(e))
            return 1
        try:
            get_inflight().add((uri,))
            stream = _GenStream(
                uri, reply, trace, deadline, eos, max_toks,
                priority=(self._default_priority
                          if priority is None else priority),
                prompt=prompt)
            self._streams[slot] = stream
            cls = priority_name(stream.priority)
            self._class_served[cls] = (
                self._class_served.get(cls, 0) + 1)
        except BaseException:
            self.engine.release(slot)
            raise
        self._count_handoff("regen")
        emit_event("kv_import", "generation", uri=uri, slot=slot,
                   regenerated=1, produced=0)
        return self._accept_token(slot, stream, tok0)

    def _rehandoff_streams(self) -> int:
        """Decode-role drain: flush pending chunks, then re-publish
        every live stream (KV snapshot + replay state) to the handoff
        stream for a surviving decode replica. Streams whose publish
        failed stay live and finish here inside the drain budget.
        Returns the number of streams moved."""
        moved = 0
        for slot in list(self._streams):
            stream = self._streams.get(slot)
            if stream is None:
                continue
            if stream.pending:
                self._push_chunk(stream)
            snap = None
            try:
                snap = self.engine.export_slot(slot)
                state = {"next_token": int(snap["next_token"]),
                         "position": int(snap["position"]),
                         "produced": stream.produced,
                         "seq": stream.seq,
                         "emitted": 1}
                blob = _encode_handoff(
                    stream.uri,
                    stream.prompt if stream.prompt is not None
                    else np.zeros(0, np.int32),
                    state, snap, reply_to=stream.reply,
                    trace_id=stream.trace, deadline=stream.deadline,
                    max_tokens=stream.max_tokens, eos=stream.eos,
                    priority=stream.priority,
                    max_bytes=self.handoff_max_bytes)
            except Exception as e:
                logger.warning(
                    "drain re-handoff export for %s failed (%s); "
                    "finishing locally", stream.uri, e)
                _discard_handoff(snap)
                continue
            if not self._handoff_out.put(blob):
                logger.warning(
                    "handoff stream full: stream %s finishes locally",
                    stream.uri)
                continue
            self._count_handoff("moved")
            emit_event("kv_handoff", "generation", uri=stream.uri,
                       slot=slot, prompt_len=int(
                           stream.prompt.size
                           if stream.prompt is not None else 0),
                       moved=1)
            self._streams.pop(slot, None)
            self.engine.release(slot)
            self._settle(stream.uri)
            moved += 1
        return moved

    # ------------------------------------------------------ stepping --
    def _finalize_results(self, results) -> int:
        """Route one decode step's tokens into their streams: deadline
        checks, chunk flushes, terminal pushes. Returns terminal
        replies pushed."""
        chaos_point("finalize")
        n = 0
        for slot, tok in results:
            stream = self._streams.get(slot)
            if stream is None:
                continue  # lane freed earlier this same step batch
            if (stream.deadline is not None
                    and time.time() > stream.deadline):
                n += self._abort_stream(
                    slot,
                    f"{DEADLINE_PREFIX}: stream missed its deadline "
                    f"after {stream.produced} tokens")
                continue
            n += self._accept_token(slot, stream, tok)
        return n

    def _accept_token(self, slot: int, stream: _GenStream,
                      tok: int) -> int:
        """Append one generated token; flush/terminate as policy
        dictates. Returns 1 when this token finished the stream."""
        now = time.monotonic()
        if stream.produced == 0:
            self._lat.record("ttft", now - stream.admitted_at)
        elif stream.last_token_at is not None:
            self._lat.record("inter_token", now - stream.last_token_at)
        stream.last_token_at = now
        stream.pending.append(int(tok))
        stream.produced += 1
        _M_TOKENS.inc()
        if stream.eos >= 0 and int(tok) == stream.eos:
            return self._finish_stream(slot, stream, "stop")
        if stream.produced >= stream.max_tokens:
            return self._finish_stream(slot, stream, "length")
        if len(stream.pending) >= self.stream_chunk_tokens:
            self._push_chunk(stream)
        return 0

    # -------------------------------------------------------- pushes --
    def _push_chunk(self, stream: _GenStream, final: bool = False,
                    reason: Optional[str] = None) -> None:
        payload: Dict[str, np.ndarray] = {
            STREAM_KEY: np.asarray(stream.seq, np.int32)}
        if stream.pending:
            payload["token"] = np.asarray(stream.pending, np.int32)
        if final:
            payload["finish_reason"] = np.asarray(reason)
            payload["n_tokens"] = np.asarray(stream.produced, np.int32)
        stream.seq += 1
        stream.pending = []
        if chaos_point("push"):
            return  # injected drop-chunk
        backend = self._reply_backend(stream.reply)
        if not backend.put(_encode(stream.uri, payload)):
            logger.warning("output queue full: dropping chunk for %s",
                           stream.uri)

    def _finish_stream(self, slot: int, stream: _GenStream,
                       reason: str) -> int:
        """Terminal chunk + slot release + settlement: the stream
        leaves the running batch at this step boundary."""
        self._push_chunk(stream, final=True, reason=reason)
        self._settle(stream.uri)
        emit_event("generation_complete", "generation", uri=stream.uri,
                   slot=slot, tokens=stream.produced, reason=reason)
        if stream.trace:
            get_tracer().add_span(
                "gen_stream", stream.trace, stream.admitted_at,
                time.monotonic(), tokens=stream.produced)
        self.engine.release(slot)
        self._streams.pop(slot, None)
        self.served += 1
        _M_REQS.inc()
        return 1

    def _abort_stream(self, slot: int, message: str) -> int:
        """Mid-stream failure: structured error terminal, then the
        slot frees exactly like a completion."""
        stream = self._streams.pop(slot, None)
        if stream is None:
            # no stream owns the slot: nothing was admitted, so there
            # is no request to answer (abort raced a finished stream)
            return 0  # zoolint: disable=reply-missing-on-path
        self._push_error(stream.uri, stream.reply, message)
        self.engine.release(slot)
        self.served += 1
        return 1

    def _push_error(self, uri: str, reply: Optional[str],
                    message: str) -> None:
        """Error terminal chunk (``seq = -1``: never deduped away).
        Also the Supervisor's ``_reply_error`` seam -- give-up and
        double-crash replies arrive through here."""
        _M_ERRORS.inc()
        _M_REQS.inc()
        if message.startswith(DEADLINE_PREFIX):
            emit_event("deadline_exceeded", "generation", uri=uri,
                       error=message[:500])
        elif not message.startswith((GENERATION_PREFIX,
                                     INVALID_PREFIX)):
            # overflow refusals already emitted generation_overflow
            # with capacity fields, and invalid_request is client
            # noise an unauthenticated caller could use to churn the
            # event ring; everything else is rare by construction ->
            # one structured event per error
            emit_event("serving_error", "generation", uri=uri,
                       error=message[:500])
        self._settle(uri)
        payload = {STREAM_KEY: np.asarray(-1, np.int32),
                   ERROR_KEY: np.asarray(message)}
        if chaos_point("push"):
            return
        backend = self._reply_backend(reply)
        if not backend.put(_encode(uri, payload)):
            logger.warning("output queue full: dropping error for %s",
                           uri)

    def _settle(self, uri: str) -> None:
        """One settlement point: ledger + crash-manifest + stream-claim
        ack -- the request is answered, nothing may re-serve it."""
        get_inflight().discard((uri,))
        if self.ledger is not None:
            self.ledger.settle((uri,))
        if self._acker is not None:
            try:
                self._acker((uri,))
            except Exception as e:
                logger.warning("input ack for %s failed: %s", uri, e)

    def _count_handoff(self, stage: str) -> None:
        _M_HANDOFF.labels(stage=stage).inc()
        self._handoff_counts[stage] = (
            self._handoff_counts.get(stage, 0) + 1)

    def _reply_backend(self, reply_to: Optional[str]):
        default = getattr(self._out_q, "queue", self._out_q)
        if not reply_to:
            return default
        maker = getattr(default, "for_stream", None)
        if maker is None:
            return default
        if reply_to not in self._reply_queues:
            self._reply_queues[reply_to] = maker(reply_to)
        return self._reply_queues[reply_to]

    # ----------------------------------------------------- lifecycle --
    def start(self) -> "GenerationWorker":
        # fresh per-run events (the ServingWorker restart contract);
        # slots a dead run left occupied are released here -- their
        # requests are ledger-outstanding and re-arrive via the
        # supervisor's re-queue, regenerating deterministically
        self._reset_streams()
        self._stop = threading.Event()
        self._drain = threading.Event()
        self.heartbeat = time.monotonic()
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True,
                                        name="generation-worker")
        self._thread.start()
        emit_event("worker_start", "generation",
                   slots=self.engine.num_slots,
                   max_tokens=self.default_max_tokens)
        return self

    def _reset_streams(self) -> None:
        for slot in list(self._streams):
            self._streams.pop(slot, None)
            self.engine.release(slot)

    def stop(self, join_timeout: float = 5.0) -> None:
        emit_event("worker_stop", "generation", served=self.served)
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(join_timeout)
            if thread.is_alive():
                logger.warning(
                    "generation worker still busy after %.1fs",
                    join_timeout)
                return
            self._thread = None

    def drain(self, deadline_s: Optional[float] = None) -> bool:
        """Stop admitting, finish every live stream, within the
        budget (default ``zoo.serving.drain.deadline_ms``). True =
        fully drained in time."""
        if deadline_s is None:
            deadline_s = float(get_config().get(
                "zoo.serving.drain.deadline_ms", 10000.0)) / 1000.0
        pause = getattr(self._in, "pause", None)
        if pause is not None:
            pause()  # brokered consumer: stop CLAIMING, not just
            # stop pulling claimed entries
        self._drain.set()
        thread = self._thread
        if thread is None:
            return True
        thread.join(max(0.0, deadline_s))
        if thread.is_alive():
            return False
        self._thread = None
        return True

    # ------------------------------------------------------- metrics --
    def metrics(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "served": self.served,
            "role": self.role,
            "streams_active": len(self._streams),
            "engine": self.engine.stats(),
            "batcher": self.batcher.stats(),
            "defaults": {"max_tokens": self.default_max_tokens,
                         "eos": self.default_eos,
                         "chunk_tokens": self.stream_chunk_tokens},
            # latency.ttft / latency.inter_token summaries (p99_s
            # etc.) -- the fleet's SLO sampler scrapes these
            "latency": self._lat.summary(),
            "class_served": dict(self._class_served),
            # per-stage handoff counts (mirrors the labeled
            # zoo_generation_handoff_total counter, readable per
            # worker without scraping the registry)
            "handoffs": dict(self._handoff_counts),
        }
        try:
            out["queue_depth"] = len(self._in)
        except (TypeError, OSError):
            pass
        if self.ledger is not None:
            out["ledger_outstanding"] = len(self.ledger)
        return out
