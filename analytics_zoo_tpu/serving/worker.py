"""ServingWorker: the inference engine of the serving data plane.

The analog of the Flink inference task (ref: zoo/.../serving/engine/
FlinkInference.scala:32-80 -- per-TM singleton InferenceModel fed by
micro-batches from the Redis source; batching logic in
engine/ClusterServingInference.scala:33-160). The TPU redesign runs one
worker loop per serving host, in one of two modes:

- **pipelined** (default, ``zoo.serving.pipeline.enabled``): an
  explicitly staged engine. A *decode* stage (its own thread, image
  decode fanned out over the shared thread pool) pulls micro-batches
  via :class:`AdaptiveBatcher` and feeds an *assembly* stage that
  stacks shape-compatible requests into padded, bucket-ladder device
  batches and dispatches them through the non-blocking
  ``InferenceModel.predict_async`` -- JAX's async dispatch keeps up to
  ``pipeline_depth`` batches in flight -- while a *finalize* stage on a
  third thread drains completed results in dispatch order. Decode of
  batch k+1 therefore overlaps device compute of batch k and result
  fetch/postprocess/push of batch k-1 (the stage overlap BigDL 2.0's
  Cluster Serving gets from the Flink dataflow, arXiv:2204.01715).
- **synchronous** (the escape hatch): one pull -> decode -> predict ->
  finalize cycle at a time on the caller's thread, still with
  ``pipeline_depth`` async dispatches in flight between cycles.

Results never reorder: the in-flight window is a FIFO and finalize is
single-threaded, so responses leave in dispatch order. Every stage is
Timer-instrumented (ref: serving/engine/Timer.scala:24-90), including
queue-depth / batch-occupancy / in-flight gauges.
"""

from __future__ import annotations

import collections
import os
import queue as _pyqueue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.obs.events import emit as emit_event
from analytics_zoo_tpu.obs.flight import get_inflight
from analytics_zoo_tpu.obs.metrics import get_registry
from analytics_zoo_tpu.obs.tracing import get_tracer
from analytics_zoo_tpu.serving.batcher import AdaptiveBatcher, MicroBatcher
from analytics_zoo_tpu.serving.chaos import chaos_point
from analytics_zoo_tpu.serving.protocol import (
    CIRCUIT_PREFIX, DEADLINE_PREFIX, ERROR_KEY, INVALID_PREFIX,
    priority_index, priority_name)
from analytics_zoo_tpu.serving.queues import _decode_predict, _encode
from analytics_zoo_tpu.serving.timer import Timer

logger = get_logger(__name__)

# exactly-once-reply obligation (zoolint lifecycle engine): every
# path through these stage methods must reach a reply, error-reply,
# requeue, or ownership hand-off -- the static twin of the ledger
ZOOLINT_REPLY_OBLIGATED = (
    "ServingWorker._predict_group",
    "ServingWorker._finalize_record",
)

# unified-registry wiring (obs, ISSUE-2): stage latencies as one
# labelled histogram family (every worker Timer mirrors into it),
# request/error counters, and the pipeline's operational gauges --
# the series HttpFrontend's /metrics Prometheus exposition scrapes
_REG = get_registry()
_M_STAGE = _REG.histogram(
    "zoo_serving_stage_duration_seconds",
    "Serving pipeline stage latency (decode, stack, predict_dispatch, "
    "predict_fetch, postprocess, service, ...)", labelnames=("stage",))
_M_SERVED = _REG.counter(
    "zoo_serving_requests_total", "Requests answered by the worker "
    "(successes and per-request error replies)")
_M_ERRORS = _REG.counter(
    "zoo_serving_errors_total",
    "Per-request error replies pushed by the worker")
_M_QUEUE_DEPTH = _REG.gauge(
    "zoo_serving_queue_depth_items",
    "Input-queue backlog observed behind the latest batch pull")
_M_OCCUPANCY = _REG.histogram(
    "zoo_serving_batch_occupancy_items",
    "Requests per pulled micro-batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
_M_INFLIGHT = _REG.gauge(
    "zoo_serving_inflight_batches_items",
    "Dispatched batches awaiting finalize (pipeline window fill)")
_M_DEADLINE = _REG.counter(
    "zoo_serving_deadline_exceeded_total",
    "Requests rejected for missing their zoo.serving.deadline_ms "
    "budget (the catching stage rides the error message/event)")
_M_CLASS = _REG.counter(
    "zoo_serving_class_requests_total",
    "Requests decoded by the worker, by admission class (ISSUE-15; "
    "requests without __priority__ count as the default class)",
    labelnames=("class",))

# ERROR_KEY / DEADLINE_PREFIX / CIRCUIT_PREFIX are re-exported above
# from serving.protocol -- the wire vocabulary's one declaring module
# (zoolint's protocol family fails hand-typed copies); the error REPLY
# is a plain string on the wire, so the class of failure rides as a
# greppable prefix the frontend maps to an HTTP status
# (protocol.ERROR_PREFIXES) and _push_error picks the right
# event/counter from without a second argument threading through the
# in-flight record tuples

# compressed-image magic numbers: requests may ship JPEG/PNG bytes
# instead of raw pixel tensors (the reference decodes base64 images
# server-side, ref: zoo/.../serving/preprocessing/PreProcessing.scala:
# 83-99 decodeImage); a 224x224x3 JPEG is ~10-20x smaller on the wire
_JPEG_MAGIC = b"\xff\xd8\xff"
_PNG_MAGIC = b"\x89PNG\r\n\x1a\n"


def _is_image_bytes(a: np.ndarray) -> bool:
    if a.ndim != 1 or a.dtype != np.uint8 or a.size < 8:
        return False
    head = a[:8].tobytes()
    return head.startswith(_JPEG_MAGIC) or head == _PNG_MAGIC


def _decode_one_image(a: np.ndarray) -> np.ndarray:
    import io

    from PIL import Image

    img = Image.open(io.BytesIO(a.tobytes()))
    return np.asarray(img.convert("RGB"), np.uint8)


_decode_pool = None
_decode_pool_lock = threading.Lock()


def _image_pool():
    """Shared decode pool: PIL releases the GIL during JPEG decode, so
    a thread pool decodes a 32-image batch ~cores-x faster than the
    serial loop (which would otherwise dominate worker service time)."""
    global _decode_pool
    with _decode_pool_lock:
        if _decode_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            _decode_pool = ThreadPoolExecutor(
                max_workers=min(16, os.cpu_count() or 4))
        return _decode_pool


def decode_image_tensors(tensors: Dict[str, np.ndarray]
                         ) -> Dict[str, np.ndarray]:
    """Replace any 1-D uint8 tensor holding JPEG/PNG bytes with the
    decoded [H, W, 3] uint8 pixel array (host-side PIL decode, the
    PreProcessing.decodeImage role). Non-image tensors pass through;
    undecodable image bytes raise (the batch path maps that to a
    per-request error)."""
    ok, failures = decode_image_batch([("", tensors, None)])
    if failures:
        raise ValueError(f"undecodable image bytes: {failures[0][2]}")
    return ok[0][1]


def decode_image_batch(items):
    """Decode every image tensor across a whole micro-batch through the
    shared thread pool (batch-level parallelism beats per-request).

    Items are ``(uri, tensors, reply, ...)`` tuples -- any tail beyond
    the tensors (reply-to, trace id) passes through untouched. Returns
    ``(decoded_items, failures)`` where failures are
    ``(uri, reply, message)`` for requests whose image bytes would not
    decode -- one corrupt upload must error that request, never the
    worker (same invariant as the per-blob decode guard)."""
    jobs = []
    for idx, item in enumerate(items):
        for k, v in item[1].items():
            a = np.asarray(v)
            if _is_image_bytes(a):
                jobs.append((idx, k, a))
    if not jobs:
        return items, []

    def safe_decode(job):
        try:
            return _decode_one_image(job[2])
        except Exception as e:
            return e

    pool = _image_pool()
    decoded = list(pool.map(safe_decode, jobs))
    out = [(item[0], dict(item[1])) + tuple(item[2:]) for item in items]
    bad = {}
    for (idx, k, _), img in zip(jobs, decoded):
        if isinstance(img, Exception):
            uri, _, reply = items[idx][:3]
            bad[idx] = (uri, reply, f"image decode failed for "
                                    f"{k!r}: {img}")
        else:
            out[idx][1][k] = img
    if not bad:
        return out, []
    return ([t for i, t in enumerate(out) if i not in bad],
            list(bad.values()))


def _default_input_fn(tensors: Dict[str, np.ndarray]) -> Any:
    """Map a request's named tensors to a model input pytree: a single
    tensor stays bare; several become a tuple in sorted-name order (the
    positional-args convention of the Estimator's multi-input models)."""
    if len(tensors) == 1:
        return next(iter(tensors.values()))
    return tuple(tensors[k] for k in sorted(tensors))


def _default_output_fn(pred: Any) -> Dict[str, np.ndarray]:
    """Map one request's slice of the model output back to named tensors
    (ref: PostProcessing -- the reference base64-encodes; we keep arrays)."""
    if isinstance(pred, dict):
        return {k: np.asarray(v) for k, v in pred.items()}
    if isinstance(pred, (tuple, list)):
        return {f"output_{i}": np.asarray(p) for i, p in enumerate(pred)}
    return {"output": np.asarray(pred)}


# in-flight records: either a dispatched batch awaiting finalize, or a
# bundle of per-request errors funneled through the same FIFO so
# responses keep dispatch order and one thread owns the served counter
_BATCH = "batch"    # ("batch", uris, replies, preds, n, prep_s, traces)
_ERRORS = "errors"  # ("errors", [(uri, reply, message), ...])

_SENTINEL = object()  # closes a pipeline stage


class ServingWorker:
    """Pulls, batches, predicts, pushes. Run inline (``serve_forever``),
    one bounded number of batches (``run``), or on a daemon thread
    (``start``/``stop``).

    Args:
      model: an ``InferenceModel`` (anything with ``predict(x)``;
        ``predict_async`` enables non-blocking dispatch).
      input_queue / output_queue: ``InputQueue``/``OutputQueue`` (or any
        object exposing their ``queue`` backend).
      batch_size: base micro-batch cap (ref: ClusterServingHelper
        coreNumber as batch size).
      timeout_ms: maximum linger after the first request of a batch.
      min_timeout_ms: linger floor the adaptive deadline tightens
        toward when the input queue is shallow.
      max_batch_size: cap the adaptive batcher may grow to under
        backlog (bucket-snapped); None reads config, 0 = 4x batch_size.
      input_fn / output_fn: request-tensors -> model-input pytree and
        model-output-slice -> response-tensors hooks (PreProcessing /
        PostProcessing analogs).
      top_n: if set, responses carry ``classes``/``scores`` of the top-N
        logits instead of the raw output (ref: PostProcessing topN).
      pipeline_depth: bounded in-flight window -- how many dispatched
        batches may await finalize (None reads config).
      pipelined: True runs the staged decode/assemble/finalize engine;
        False the synchronous loop; None reads
        ``zoo.serving.pipeline.enabled``.
    """

    def __init__(self, model, input_queue, output_queue,
                 batch_size: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 input_fn: Callable = _default_input_fn,
                 output_fn: Callable = _default_output_fn,
                 top_n: Optional[int] = None,
                 timer: Optional[Timer] = None,
                 pipeline_depth: Optional[int] = None,
                 pipelined: Optional[bool] = None,
                 min_timeout_ms: Optional[float] = None,
                 max_batch_size: Optional[int] = None,
                 breaker=None):
        cfg = get_config()
        if batch_size is None:
            batch_size = int(cfg.get("zoo.serving.batch_size", 8))
        if timeout_ms is None:
            timeout_ms = float(cfg.get("zoo.serving.batch_timeout_ms", 5))
        if min_timeout_ms is None:
            min_timeout_ms = float(
                cfg.get("zoo.serving.batch_timeout_min_ms", 1.0))
        if max_batch_size is None:
            max_batch_size = int(cfg.get("zoo.serving.batch_max_size", 0))
        if pipeline_depth is None:
            pipeline_depth = int(cfg.get("zoo.serving.pipeline.depth", 2))
        if pipelined is None:
            pipelined = bool(cfg.get("zoo.serving.pipeline.enabled", True))
        self.model = model
        self._in = getattr(input_queue, "queue", input_queue)
        self._out_q = output_queue
        self.pipelined = bool(pipelined)
        if self.pipelined:
            self.batcher = AdaptiveBatcher(
                self._in, batch_size=batch_size, timeout_ms=timeout_ms,
                min_timeout_ms=min_timeout_ms,
                max_batch_size=max_batch_size or None)
        else:
            # the escape hatch restores the WHOLE pre-pipeline engine,
            # fixed size/timeout batching included -- an operator
            # disabling the pipeline gets the proven old path, not a
            # half-new one
            self.batcher = MicroBatcher(self._in, batch_size=batch_size,
                                        timeout_ms=timeout_ms)
        self.input_fn = input_fn
        self.output_fn = output_fn
        self.top_n = top_n
        # default Timer mirrors every stage duration into the
        # process-wide registry histogram (Prometheus /metrics); a
        # caller-supplied timer keeps whatever mirroring it was built
        # with
        self.timer = timer or Timer(keep_samples=4096, mirror=_M_STAGE)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.served = 0
        # reply-to routing for brokered deployments: requests may name
        # the result stream of the frontend that issued them; results
        # go there instead of the default output queue. The route
        # travels WITH the request through grouping/finalize (clients
        # choose their own uris, so a uri-keyed side table would
        # cross-route same-uri requests that grouping reorders)
        self._reply_queues: Dict[str, Any] = {}
        # dispatch pipelining: keep up to pipeline_depth batches in
        # flight (predict_async), so batch n+1's host->device transfer
        # overlaps batch n's device compute + result fetch; 1 disables
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._inflight: collections.deque = collections.deque()
        # live handle on the pipelined engine's in-flight window (for
        # metrics); set for the duration of a pipelined run
        self._inflight_q: Optional[_pyqueue.Queue] = None
        # resilience hooks (ISSUE-5) -- all None/absent-cheap when off:
        # * ledger: a Supervisor attaches a RequestLedger so the
        #   requests a dead run had pulled can be re-queued exactly
        #   once (recorded at decode, settled on reply);
        # * breaker: CircuitBreaker consulted before dispatch, fed by
        #   predict failures/successes (config-gated default);
        # * heartbeat: stamped by every stage loop iteration, read by
        #   the Supervisor's wedge detector.
        self.ledger = None
        # fleet ack seam (ISSUE-9): consumer-group input backends
        # (RedisStreamQueue) expose ack_uris -- the worker settles a
        # claim the moment it pushes the reply, so a replica SIGKILLed
        # mid-serve leaves its claims pending for another replica to
        # reclaim. None for every other backend: one getattr at
        # construction, zero per-request cost
        self._acker = getattr(self._in, "ack_uris", None)
        # tenant-lane routing (ISSUE-13): population-backed models
        # expose tenant_lanes (the member count) + resolve_lane; every
        # other model leaves it None, and a request carrying __tenant__
        # anyway is a structured 400 -- one getattr at construction,
        # zero per-request cost on the no-tenant path
        self._tenant_lanes = getattr(model, "tenant_lanes", None)
        # admission class of requests without __priority__ (ISSUE-15):
        # resolved once so the per-request counter pays one list index
        self._default_priority = priority_index(
            cfg.get("zoo.serving.priority.default_class",
                    "interactive")) or 0
        if breaker is None and bool(
                cfg.get("zoo.serving.breaker.enabled", False)):
            from analytics_zoo_tpu.serving.resilience import (
                CircuitBreaker)

            breaker = CircuitBreaker()
        self.breaker = breaker
        # drain flag (ISSUE-9): set-once per run; a draining engine
        # stops pulling, finishes in-flight work, and exits cleanly
        self._drain = threading.Event()
        self.heartbeat = time.monotonic()
        # decode stage's own heartbeat: None while no decode thread is
        # running (sync engine, bounded runs after their decode loop
        # finished) -- the supervisor only reads it when set, so a
        # finished decode loop cannot read as a wedge
        self.heartbeat_decode: Optional[float] = None

    def _count_served(self, n: int) -> None:
        """Single owner of the served counters (instance total + the
        process-wide registry counter)."""
        self.served += n
        if n:
            _M_SERVED.inc(n)

    def _ack_input(self, uris) -> None:
        """Settle consumer-group claims for answered requests (no-op
        off the fleet data plane). Ack failures are survivable: the
        entries re-deliver after the idle threshold -- duplicate work,
        never lost work."""
        if self._acker is None:
            return
        try:
            self._acker(uris)
        except Exception as e:
            logger.warning("input ack for %d request(s) failed: %s",
                           len(tuple(uris)), e)

    # ------------------------------------------------- synchronous loop --
    def process_one_batch(self, wait_timeout: float = 1.0) -> int:
        """One pull->predict->push cycle (the synchronous engine);
        returns requests served."""
        self.heartbeat = time.monotonic()
        with self.timer.timing("batch_wait"):
            blobs = self.batcher.next_batch(wait_timeout=wait_timeout)
        if not blobs:
            n = 0
            while self._inflight:  # idle: drain pipelined batches
                n += self._finalize_one()
            self._count_served(n)
            return n
        items, bad_images, decode_s = self._decode_stage(blobs)
        n_failed = 0
        for uri, reply, msg in bad_images:
            logger.warning("serving: %s", msg)
            self._push_error(uri, reply, msg)
            n_failed += 1
        groups = self._group_compatible(items)
        # the decode stage is shared by every signature group of this
        # cycle: apportion it by group size so a group's "service"
        # metric neither double-counts earlier groups' decode+prep
        # time nor charges a 1-item group a 127-item group's decode
        self._decode_per_item = decode_s / max(1, len(items))
        n = n_failed
        for group in groups:
            group, expired = self._split_expired(group, "dispatch")
            for uri, reply, msg in expired:
                self._push_error(uri, reply, msg)
            n += len(expired)
            if not group:
                continue
            try:
                n += self._predict_group(group)
            except Exception as e:  # input_fn/output_fn bugs must not
                logger.exception(  # kill the serving thread
                    "serving batch failed: %s", e)
                for item in group:
                    self._push_error(item[0], item[2], str(e))
                n += len(group)
        # finalize the oldest in-flight batches beyond the pipeline
        # depth (idle cycles drain the rest -- see the early return)
        while len(self._inflight) >= self.pipeline_depth:
            n += self._finalize_one()
        self._count_served(n)
        return n

    # ------------------------------------------------------- stages -----
    def _decode_stage(self, blobs) -> Tuple[List, List, float]:
        """Wire-decode a pulled micro-batch, then image-decode through
        the shared thread pool. Returns (items, failures,
        decode_seconds); items are (uri, tensors, reply, trace,
        deadline, tenant, priority), failures are (uri, reply,
        message) -- undecodable images plus requests already past
        their deadline."""
        t0 = time.perf_counter()
        with self.timer.timing("decode", batch=len(blobs)):
            items: List[Tuple[str, Dict[str, np.ndarray],
                              Optional[str], Optional[str],
                              Optional[float], Optional[int],
                              Optional[int]]]
            try:  # fast path: no per-item try frames on clean batches
                items = [_decode_predict(b) for b in blobs]
                if self.ledger is not None:
                    for b, it in zip(blobs, items):
                        self.ledger.record(it[0], b)
            except Exception:
                items = []
                for b in blobs:
                    try:
                        items.append(_decode_predict(b))
                    except Exception as e:  # malformed blob: drop,
                        logger.exception(   # keep serving
                            "serving: undecodable request dropped: %s",
                            e)
                        continue
                    if self.ledger is not None:
                        self.ledger.record(items[-1][0], b)
            # chaos seam AFTER the ledger record: blobs are already
            # off the input queue, so a stage death here must be
            # requeue-covered or the requests would vanish replyless
            # (the only residual uncovered window is the wire-decode
            # loop itself)
            chaos_point("decode")
            for it in items:
                # per-class traffic counter (ISSUE-15): requests
                # without __priority__ count as the default class
                pri = it[6] if len(it) > 6 and it[6] is not None \
                    else self._default_priority
                _M_CLASS.labels(**{"class": priority_name(pri)}).inc()
            items, bad_images = decode_image_batch(items)
            items, expired = self._split_expired(items, "decode")
        t1 = time.perf_counter()
        self._emit_spans("decode", (it[3] for it in items), t0, t1,
                         batch=len(items))
        return items, bad_images + expired, t1 - t0

    def _split_expired(self, items, stage: str):
        """Partition a batch on its per-request deadlines: (live,
        expired-error-tuples). Requests without a deadline (the
        default wire format) always pass -- the common case is one
        ``is None`` check per request."""
        expired = []
        live = None  # copy-on-write: stays None on the no-expiry path
        now = None
        for i, it in enumerate(items):
            deadline = it[4]
            if deadline is not None:
                if now is None:
                    now = time.time()
                if now > deadline:
                    if live is None:
                        live = list(items[:i])
                    expired.append(
                        (it[0], it[2],
                         f"{DEADLINE_PREFIX}: request missed its "
                         f"deadline before {stage}"))
                    continue
            if live is not None:
                live.append(it)
        return (items if live is None else live), expired

    @staticmethod
    def _emit_spans(name, traces, t0: float, t1: float, **args) -> None:
        """One span per traced request covering this batch stage --
        a no-op loop when nothing in the batch carries a trace id (the
        tracing-disabled hot path)."""
        tracer = None
        for tr in traces:
            if tr:
                if tracer is None:
                    tracer = get_tracer()
                tracer.add_span(name, tr, t0, t1, **args)

    @staticmethod
    def _group_compatible(items):
        """Group requests whose tensors share keys+shapes+dtypes so they
        stack into one device batch (ref: batchInput groups by model
        signature implicitly -- one model, one schema). The tenant lane
        joins the signature: a device batch answers ONE lane, so
        same-shape requests for different tenants dispatch separately
        (each through the same warmed executable -- the lane is traced,
        not a shape)."""
        groups: Dict[Any, List] = {}
        for item in items:
            sig = (tuple(sorted((k, v.shape, str(v.dtype))
                                for k, v in item[1].items())),
                   item[5] if len(item) > 5 else None)
            groups.setdefault(sig, []).append(item)
        return list(groups.values())

    def _dispatch_group(self, group):
        """Assembly stage for one signature group: stack the requests
        into a device batch and dispatch it (non-blocking when the
        model exposes ``predict_async``). Returns an in-flight record
        -- (``_BATCH``, ...) awaiting finalize, or (``_ERRORS``, ...)
        when dispatch failed. Stack/input_fn exceptions propagate (the
        caller owns the per-request error mapping for those)."""
        chaos_point("dispatch")
        uris = [it[0] for it in group]
        replies = [it[2] for it in group]
        traces = [it[3] if len(it) > 3 else None for it in group]
        deadlines = [it[4] if len(it) > 4 else None for it in group]
        if self.breaker is not None and not self.breaker.allow():
            # open circuit: fast-fail the whole group instead of
            # burning a device slot on a backend that keeps dying
            self.breaker.rejected(len(group))
            return (_ERRORS,
                    [(u, r, f"{CIRCUIT_PREFIX}: backend dispatch "
                            "suspended after repeated failures")
                     for u, r in zip(uris, replies)])
        # tenant-lane resolution (ISSUE-13): grouping made the lane
        # uniform across this group. Resolution failures (lane out of
        # range, missing tenant under strict) are CLIENT errors -- they
        # reply with the structured invalid_request message before any
        # device work and never feed the breaker
        tenant = group[0][5] if len(group[0]) > 5 else None
        lane = None
        if self._tenant_lanes is not None:
            try:
                lane = self.model.resolve_lane(tenant)
            except ValueError as e:
                return (_ERRORS, [(u, r, str(e))
                                  for u, r in zip(uris, replies)])
        elif tenant is not None:
            return (_ERRORS,
                    [(u, r, f"{INVALID_PREFIX}: request names tenant "
                            f"lane {tenant} but the serving model has "
                            "no parameter lanes")
                     for u, r in zip(uris, replies)])
        t0 = time.perf_counter()  # this group's own prep starts here
        with self.timer.timing("stack", batch=len(group)):
            stacked = {
                k: np.stack([it[1][k] for it in group])
                for k in group[0][1]
            }
            x = self.input_fn(stacked)
        try:
            with self.timer.timing("predict_dispatch", batch=len(group)):
                if hasattr(self.model, "predict_async"):
                    if self._tenant_lanes is not None:
                        preds, n = self.model.predict_async(x, lane=lane)
                    else:
                        preds, n = self.model.predict_async(x)
                else:  # duck-typed models (tests): synchronous path
                    preds, n = self.model.predict(x), len(group)
        except Exception as e:  # push per-request errors, keep serving
            logger.exception("serving predict failed: %s", e)
            if self.breaker is not None:
                self.breaker.record_failure()
            return (_ERRORS, [(u, r, str(e))
                              for u, r in zip(uris, replies)])
        # start the device->host result copy NOW: by finalize time
        # (pipeline_depth batches later) the bytes are already host-
        # side. A synchronous fetch costs a full round trip per batch
        # on remote-device runtimes (~0.6 s measured on the tunnel --
        # it was the serving cycle's dominant cost), and d2h overlaps
        # the next batches' compute for free
        import jax as _jax

        for leaf in _jax.tree_util.tree_leaves(preds):
            if hasattr(leaf, "copy_to_host_async"):
                try:
                    leaf.copy_to_host_async()
                except Exception:  # fall back to the sync fetch path
                    break
        # prep time for THIS group: its share of the cycle's decode
        # stage + its own stack/dispatch (stored so the service metric
        # can exclude pipeline residency while other batches finalize)
        t1 = time.perf_counter()
        self._emit_spans("dispatch", traces, t0, t1, batch=len(group))
        prep_s = (getattr(self, "_decode_per_item", 0.0) * len(group)
                  + t1 - t0)
        # dispatched-but-unanswered ids into the flight recorder's
        # in-flight registry: a crash postmortem names exactly which
        # requests were lost (one set update per BATCH, not per request)
        get_inflight().add(uris)
        return (_BATCH, uris, replies, preds, n, prep_s, traces,
                deadlines)

    def _predict_group(self, group) -> int:
        rec = self._dispatch_group(group)
        if rec[0] == _ERRORS:
            for uri, reply, msg in rec[1]:
                self._push_error(uri, reply, msg)
            return len(rec[1])
        self._inflight.append(rec)
        return 0  # counted when finalized

    def _finalize_one(self) -> int:
        """Materialize the oldest in-flight batch and push its results
        (async dispatch errors surface here). The pop is race-guarded:
        after a wedge restart an abandoned run's drain can briefly
        overlap the new run on this deque (deque ops are atomic, the
        check-then-pop is not) -- losing the race must cost nothing,
        not an IndexError that kills a serving thread."""
        try:
            rec = self._inflight.popleft()
        except IndexError:
            return 0
        return self._finalize_record(rec)

    def _finalize_record(self, rec) -> int:
        """Finalize stage for one in-flight record. Never raises:
        push-path failures (broker down, spool disk full) must not kill
        the serving loop -- callers sit outside the batch guard."""
        chaos_point("finalize")
        if rec[0] == _ERRORS:
            try:
                for uri, reply, msg in rec[1]:
                    self._push_error(uri, reply, msg)
            except Exception as e:  # push path down (broker gone):
                logger.exception(   # the contract still holds
                    "serving error-push failed (%d error replies "
                    "lost): %s", len(rec[1]), e)
            return len(rec[1])
        _, uris, replies, preds, n, prep_s, traces, deadlines = rec
        t0 = time.perf_counter()
        try:
            try:
                served = self._finalize_inner(uris, replies, preds, n,
                                              deadlines)
            finally:  # answered (or accounted): off the crash manifest
                get_inflight().discard(uris)
                if self.ledger is not None:
                    # settled = this engine accounted for the request
                    # (reply pushed, or its loss logged); the
                    # supervisor must not re-queue it after a later
                    # crash -- that would duplicate the reply
                    self.ledger.settle(uris)
                # same settlement for brokered consumer-group claims
                # (a SIGKILL before this line leaves them pending ->
                # reclaimed by a surviving replica)
                self._ack_input(uris)
            t1 = time.perf_counter()
            self._emit_spans("finalize", traces, t0, t1,
                             batch=len(uris))
            # worker-side service time for this batch: its own decode/
            # stack/dispatch prep + its remaining result wait + push.
            # Residency in the in-flight window while OTHER batches
            # finalize is excluded -- which also means device compute
            # that OVERLAPPED that residency doesn't show up here; this
            # is "host work + un-overlapped device wait", the marginal
            # per-batch cost under pipelining (zero overlap = full
            # decode->predict->push)
            self.timer.record("service", prep_s + t1 - t0)
            return served
        except Exception as e:
            logger.exception("serving finalize failed (results for %d "
                             "requests lost): %s", len(uris), e)
            # intentional: if the finally block itself raised before
            # settle/ack ran, the ledger entry and broker claim stay
            # pending -- the supervisor/replica requeue redelivers the
            # request, so the contract degrades to at-least-once
            # rather than silently losing the reply
            return len(uris)  # zoolint: disable=reply-missing-on-path

    def _finalize_inner(self, uris, replies, preds, n,
                        deadlines=None) -> int:
        import jax

        try:
            with self.timer.timing("predict_fetch", batch=len(uris)):
                preds = jax.tree_util.tree_map(
                    lambda a: np.asarray(a)[:n], preds)
        except Exception as e:
            logger.exception("serving predict failed: %s", e)
            if self.breaker is not None:
                self.breaker.record_failure()
            for uri, reply in zip(uris, replies):
                self._push_error(uri, reply, str(e))
            return len(uris)
        if self.breaker is not None:
            # fetch materialized: the backend really answered -- this
            # is the success signal that closes a half-open breaker
            self.breaker.record_success()
        # finalize-time deadline check: the device slot is spent, but
        # a reply nobody is waiting for must still be the STRUCTURED
        # error the contract promises, not a late result
        late = None
        if deadlines is not None and any(
                d is not None for d in deadlines):
            now = time.time()
            late = [d is not None and now > d for d in deadlines]
            if not any(late):
                late = None
        with self.timer.timing("postprocess", batch=len(uris)):
            # hot path: the common single-ndarray output with default
            # hooks slices rows directly -- per-request jax tree_map
            # costs ~10 us each, which dominates postprocess at large
            # adaptive batches
            fast = (self.top_n is None
                    and self.output_fn is _default_output_fn
                    and isinstance(preds, np.ndarray))
            backend = getattr(self._out_q, "queue", self._out_q)
            if (fast and late is None and not any(replies)
                    and hasattr(backend, "put_many")):
                # one batched push: per-item lock/notify trips cost
                # more than the encode itself at adaptive batch sizes
                if chaos_point("push"):
                    return len(uris)  # injected drop-reply
                blobs = [_encode(uri, {"output": preds[i]})
                         for i, uri in enumerate(uris)]
                accepted = backend.put_many(blobs)
                if accepted < len(blobs):
                    logger.warning(
                        "output queue full: dropped %d results",
                        len(blobs) - accepted)
                return len(uris)
            for i, (uri, reply) in enumerate(zip(uris, replies)):
                try:
                    if late is not None and late[i]:
                        self._push_error(
                            uri, reply,
                            f"{DEADLINE_PREFIX}: request missed its "
                            "deadline before finalize")
                        continue
                    if fast:
                        self._push(uri, reply, {"output": preds[i]})
                        continue
                    pred_i = _tree_index(preds, i)
                    if self.top_n is not None:
                        pred_i = _top_n(np.asarray(pred_i), self.top_n)
                        self._push(uri, reply, pred_i)
                    else:
                        self._push(uri, reply, self.output_fn(pred_i))
                except Exception as e:  # output_fn bugs must not kill
                    logger.exception(  # the serving thread
                        "serving postprocess failed for %s: %s", uri, e)
                    self._push_error(uri, reply, str(e))
        return len(uris)

    # ---------------------------------------------- pipelined engine ----
    def _run_pipelined(self, max_batches: Optional[int],
                       wait_timeout: float,
                       stop_ev: threading.Event,
                       drain_ev: Optional[threading.Event] = None) -> int:
        """The staged engine: decode thread -> assembly/dispatch (this
        thread) -> finalize thread, bounded by ``pipeline_depth``
        dispatched batches in flight. A bounded run returns only after
        every request it pulled is answered. ``stop_ev`` is THIS run's
        stop event (captured, not ``self._stop``): a supervisor
        restart hands the next run a fresh event, so an abandoned
        wedged thread that wakes later sees its own set event and
        exits instead of double-serving."""
        decoded_q: _pyqueue.Queue = _pyqueue.Queue(
            maxsize=max(2, self.pipeline_depth))
        inflight_q: _pyqueue.Queue = _pyqueue.Queue(
            maxsize=self.pipeline_depth)
        abort = threading.Event()  # abnormal driver exit: unstick stages
        served_box = [0]

        def put_stage(q, item) -> bool:
            while True:
                try:
                    q.put(item, timeout=0.2)
                    return True
                except _pyqueue.Full:
                    if abort.is_set():
                        return False

        def decode_loop():
            pulled = 0
            try:
                while not stop_ev.is_set() and not abort.is_set():
                    if drain_ev is not None and drain_ev.is_set():
                        # draining: stop pulling; the sentinel below
                        # flushes everything already in the pipeline
                        # through dispatch + finalize, then the run
                        # exits cleanly -- the same clean-exit path a
                        # bounded run takes
                        break
                    # iterates at least every wait_timeout when idle
                    # (next_batch returns empty), so staleness means
                    # STUCK (hung broker recv, chaos stall), not idle
                    self.heartbeat_decode = time.monotonic()
                    if max_batches is not None and pulled >= max_batches:
                        break
                    pulled += 1
                    with self.timer.timing("batch_wait"):
                        blobs = self.batcher.next_batch(
                            wait_timeout=wait_timeout)
                    if not blobs:
                        continue
                    # depth the batcher already observed for policy --
                    # a second len() here would cost one more broker
                    # RPC per pull on TcpQueue backends
                    depth = getattr(self.batcher, "last_depth", -1)
                    if depth >= 0:
                        self.timer.gauge("queue_depth", depth)
                        _M_QUEUE_DEPTH.set(depth)
                    self.timer.gauge("batch_occupancy", len(blobs))
                    _M_OCCUPANCY.observe(len(blobs))
                    if not put_stage(decoded_q,
                                     self._decode_stage(blobs)):
                        logger.warning(
                            "serving pipeline aborted with %d decoded "
                            "requests undispatched", len(blobs))
                        return
            except Exception as e:  # batcher/queue failures must
                logger.exception(   # still close the pipeline cleanly
                    "serving decode stage failed: %s", e)
            finally:
                self.heartbeat_decode = None  # not running != wedged
                put_stage(decoded_q, _SENTINEL)

        def finalize_loop():
            while True:
                rec = inflight_q.get()
                if rec is _SENTINEL:
                    return
                self.heartbeat = time.monotonic()
                try:
                    n = self._finalize_record(rec)
                except Exception as e:  # belt-and-braces: this thread
                    # must never die -- the driver blocks on the
                    # bounded FIFO it drains, so a dead finalizer
                    # wedges the whole engine
                    logger.exception("serving finalize stage "
                                     "failed: %s", e)
                    n = len(rec[1])
                served_box[0] += n
                self._count_served(n)

        decode_t = threading.Thread(target=decode_loop, daemon=True,
                                    name="serving-decode")
        finalize_t = threading.Thread(target=finalize_loop, daemon=True,
                                      name="serving-finalize")
        self._inflight_q = inflight_q
        decode_t.start()
        finalize_t.start()
        try:
            while True:
                with self.timer.timing("assembly_wait"):
                    # the DRIVER owns the supervision heartbeat: it is
                    # the thread that holds device work, so "driver
                    # stuck in dispatch/finalize backpressure" is
                    # exactly the wedge the Supervisor must catch --
                    # a sliced wait keeps the heartbeat fresh while
                    # verifiably idle, stale only when truly stuck
                    while True:
                        self.heartbeat = time.monotonic()
                        try:
                            item = decoded_q.get(timeout=0.5)
                            break
                        except _pyqueue.Empty:
                            continue
                if item is _SENTINEL:
                    break
                items, bad_images, decode_s = item
                if bad_images:
                    for uri, reply, msg in bad_images:
                        logger.warning("serving: %s", msg)
                    # errors ride the in-flight FIFO: responses keep
                    # arrival order and finalize owns the counters
                    inflight_q.put((_ERRORS, list(bad_images)))
                if not items:
                    continue
                self.heartbeat = time.monotonic()
                self._decode_per_item = decode_s / max(1, len(items))
                for group in self._group_compatible(items):
                    group, expired = self._split_expired(group,
                                                         "dispatch")
                    if expired:  # deadline hit while queued in-engine
                        inflight_q.put((_ERRORS, expired))
                    if not group:
                        continue
                    try:
                        rec = self._dispatch_group(group)
                    except Exception as e:  # input_fn bugs etc.
                        logger.exception("serving batch failed: %s", e)
                        rec = (_ERRORS, [(it[0], it[2], str(e))
                                         for it in group])
                    with self.timer.timing("inflight_wait"):
                        inflight_q.put(rec)  # blocks at the window cap
                    depth_now = inflight_q.qsize()
                    self.timer.gauge("inflight", depth_now)
                    _M_INFLIGHT.set(depth_now)
        finally:
            abort.set()
            dropped = 0
            while True:  # abnormal exit: unstick + account a blocked
                try:     # decode stage (normal exit finds it empty)
                    item = decoded_q.get_nowait()
                    if item is not _SENTINEL:
                        dropped += len(item[0]) + len(item[1])
                except _pyqueue.Empty:
                    break
            if dropped:
                logger.warning("serving pipeline dropped %d decoded "
                               "requests on abnormal exit", dropped)
                emit_event("pipeline_abort", "serving", dropped=dropped)
            inflight_q.put(_SENTINEL)
            finalize_t.join()
            decode_t.join(timeout=5.0)
            self._inflight_q = None
            # zero the operational gauges: a drained/stopped engine
            # must not scrape as permanently-stuck backlog
            _M_INFLIGHT.set(0)
            _M_QUEUE_DEPTH.set(0)
        return served_box[0]

    # ------------------------------------------------------- lifecycle --
    def run(self, max_batches: Optional[int] = None,
            wait_timeout: float = 0.05) -> int:
        """Serve until stopped (or ``max_batches`` pull cycles); returns
        total requests served in this call."""
        stop_ev = self._stop  # capture: this RUN's stop event -- see
        # _run_pipelined's docstring for the restart semantics
        drain_ev = self._drain  # same per-run capture
        if self.pipelined:
            return self._run_pipelined(max_batches, wait_timeout,
                                       stop_ev, drain_ev)
        total = 0
        batches = 0
        while not stop_ev.is_set() and not drain_ev.is_set():
            total += self.process_one_batch(wait_timeout=wait_timeout)
            batches += 1
            if max_batches is not None and batches >= max_batches:
                break
        # a bounded run returns only after everything it pulled is
        # answered (pipelined batches must not linger past the call).
        # Identity-gated: after a wedge restart this may be an
        # ABANDONED run waking up -- the deque now belongs to the new
        # run, whose own drain answers these records
        while self._inflight and self._stop is stop_ev:
            n = self._finalize_one()
            self._count_served(n)
            total += n
        return total

    def serve_forever(self) -> None:
        try:
            self.run()
        except BaseException as e:
            # mark the death in the event log BEFORE re-raising so the
            # flight recorder's postmortem (threading.excepthook fires
            # next) carries the crash as its final event
            emit_event("worker_crash", "serving", error=repr(e)[:500],
                       served=self.served)
            raise

    def drain(self, deadline_s: Optional[float] = None) -> bool:
        """Graceful drain (ISSUE-9): stop pulling new work (the input
        backend's ``pause`` seam, where it has one), let the engine
        finish every request it already pulled, and wait up to
        ``deadline_s`` (default ``zoo.serving.drain.deadline_ms``).
        Returns True when the run fully drained inside the budget;
        False means in-flight work is still finishing when the
        deadline expired (the caller decides whether to hard-stop).
        This is the seam SIGTERM and rolling restarts share."""
        if deadline_s is None:
            deadline_s = float(get_config().get(
                "zoo.serving.drain.deadline_ms", 10000.0)) / 1000.0
        pause = getattr(self._in, "pause", None)
        if pause is not None:
            pause()  # a brokered consumer must stop CLAIMING, not
            # just stop pulling claimed work -- entries claimed after
            # this point would sit until the reclaim threshold
        self._drain.set()
        thread = self._thread
        if thread is None:
            return True
        thread.join(max(0.0, deadline_s))
        if thread.is_alive():
            return False
        self._thread = None
        while self._inflight:  # sync-engine leftovers
            self._count_served(self._finalize_one())
        return True

    def start(self) -> "ServingWorker":
        # a FRESH stop event per run (not .clear()): a previous run's
        # thread that is still draining -- or was abandoned by a
        # supervisor wedge restart -- holds the old event and must
        # keep seeing it set, or it would resume serving next to the
        # new thread
        self._stop = threading.Event()
        self._drain = threading.Event()  # same per-run freshness
        self.heartbeat = time.monotonic()
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True)
        self._thread.start()
        emit_event("worker_start", "serving", pipelined=self.pipelined,
                   batch_size=self.batcher.batch_size,
                   pipeline_depth=self.pipeline_depth)
        return self

    def stop(self, join_timeout: float = 5.0) -> None:
        emit_event("worker_stop", "serving", served=self.served)
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(join_timeout)
            if thread.is_alive():
                # the worker thread is still draining (e.g. a slow
                # first compile); it owns the in-flight window --
                # draining here would race its pops. KEEP the handle so
                # a retried stop() (or start()) still sees the live
                # thread.
                logger.warning("serving worker still busy after %.1fs; "
                               "in-flight batches drain on its thread",
                               join_timeout)
                return
            self._thread = None
        while self._inflight:  # flush: accepted requests must answer
            self._count_served(self._finalize_one())

    # --------------------------------------------------------- outputs --
    def _push(self, uri: str, reply: Optional[str],
              tensors: Dict[str, np.ndarray]) -> None:
        if chaos_point("push"):
            return  # injected drop-reply
        backend = self._reply_backend(reply)
        if not backend.put(_encode(uri, tensors)):
            logger.warning("output queue full: dropping result for %s",
                           uri)

    def _reply_backend(self, reply_to: Optional[str]):
        """Default output backend, or the named stream on the same
        broker when the request carried a reply-to (several frontends
        sharing one broker each get their own results back). Brokered
        backends (TcpQueue, RedisStreamQueue) expose ``for_stream``;
        everything else ignores reply-to."""
        default = getattr(self._out_q, "queue", self._out_q)
        if not reply_to:
            return default
        maker = getattr(default, "for_stream", None)
        if maker is None:
            return default
        if reply_to not in self._reply_queues:
            self._reply_queues[reply_to] = maker(reply_to)
        return self._reply_queues[reply_to]

    def _push_error(self, uri: str, reply: Optional[str],
                    message: str) -> None:
        # reserved out-of-band key (the "__uri__" convention of
        # queues._encode) so model outputs named "error" stay usable
        _M_ERRORS.inc()
        if message.startswith(DEADLINE_PREFIX):
            _M_DEADLINE.inc()
            emit_event("deadline_exceeded", "serving", uri=uri,
                       error=message[:500])
        elif not message.startswith(CIRCUIT_PREFIX):
            # breaker rejections happen at batch scale while open; the
            # circuit_open/closed transition events carry that story,
            # a per-request event would flood the ring. Everything
            # else is rare by construction, so a structured event per
            # error is cheap and makes /debug/events the first stop
            # for "why did request X fail" instead of log spelunking
            emit_event("serving_error", "serving", uri=uri,
                       error=message[:500])
        if self.ledger is not None:
            self.ledger.settle((uri,))
        self._push(uri, reply, {ERROR_KEY: np.asarray(message)})
        # ack AFTER the push: an error reply answers the request, so
        # its stream claim settles on the same at-least-once contract
        # as a result reply
        self._ack_input((uri,))

    # --------------------------------------------------------- metrics --
    def metrics(self) -> Dict[str, Any]:
        inflight_q = self._inflight_q  # read once: the worker thread
        # clears this attribute when a pipelined run exits
        pipe: Dict[str, Any] = {
            "enabled": self.pipelined,
            "depth": self.pipeline_depth,
            "inflight": (inflight_q.qsize() if inflight_q is not None
                         else len(self._inflight)),
            "batcher": self.batcher.stats(),
        }
        try:
            pipe["queue_depth"] = len(self._in)
        except (TypeError, OSError):
            # a queue backend without __len__ (or a broker hop that
            # cannot answer right now): depth is best-effort metadata,
            # omit the field rather than fail the metrics call
            pass
        out = {"served": self.served, "stages": self.timer.summary(),
               "pipeline": pipe}
        shard_plan = getattr(self.model, "shard_plan", None)
        if shard_plan is not None:
            out["shard"] = shard_plan.describe()
        if self.breaker is not None:
            out["breaker"] = self.breaker.stats()
        if self.ledger is not None:
            out["ledger_outstanding"] = len(self.ledger)
        return out


def _tree_index(preds, i: int):
    import jax

    return jax.tree_util.tree_map(lambda a: np.asarray(a)[i], preds)


def _top_n(logits: np.ndarray, n: int) -> Dict[str, np.ndarray]:
    """(ref: PostProcessing topN -- class indices + scores)."""
    flat = logits.reshape(-1)
    idx = np.argsort(flat)[::-1][:n]
    return {"classes": idx.astype(np.int32), "scores": flat[idx]}
