"""Redis-protocol serving adapter: reference clients work unchanged.

The reference's cluster-serving clients speak Redis streams with
Arrow-encoded tensors (ref: pyzoo/zoo/serving/client.py:37-221 --
``XADD serving_stream uri=<id> data=<b64 Arrow RecordBatch>``, results
read back as hashes ``cluster-serving_<stream>:<uri>`` via
KEYS/HGETALL/DEL; ref wire schema: pyzoo/zoo/serving/schema.py
get_field_and_data). This repo's data plane is its own queue design
(queues.py), so this module bridges the gap: a minimal RESP2 server
that accepts exactly the command surface those clients use and adapts
it onto any InputQueue/OutputQueue backend pair.

Served commands: XGROUP CREATE, XADD, INFO, KEYS, HGETALL, DEL, PING,
CLIENT * (redis-py connection handshake), EXISTS. Everything else gets
a clear -ERR.

Wire-format notes:
- XADD ``data`` fields hold a base64 Arrow RecordBatch stream; dense
  tensors arrive as the reference's 4-row struct (indiceData /
  indiceShape / data / shape), strings as base64 image bytes. Sparse
  tensors are rejected with a clear error (this serving stack has no
  sparse input path).
- Results are stored as ``cluster-serving_<stream>:<uri>`` hashes with
  a ``value`` field holding the JSON-encoded output tensor(s) --
  nested lists, the shape the reference's HTTP route exposes.
"""

from __future__ import annotations

import base64
import fnmatch
import io
import json
import socket
import socketserver
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.obs.events import emit as emit_event

logger = get_logger(__name__)

RESULT_PREFIX = "cluster-serving_"

# result-drain reconnect backoff (capped exponential): the drain loop
# must survive a broker/queue-backend outage, not die on the first
# ConnectionError and silently strand every future client poll
_RECONNECT_BASE_S = 0.05
_RECONNECT_MAX_S = 5.0


# ------------------------------------------------------------- arrow --
def decode_arrow_payload(b64: bytes) -> Dict[str, np.ndarray]:
    """Base64 Arrow RecordBatch stream -> named input tensors, per the
    reference's schema (ref: schema.py get_field_and_data)."""
    import pyarrow as pa

    buf = base64.b64decode(b64)
    reader = pa.ipc.open_stream(buf)
    batch = next(iter(reader))
    out: Dict[str, np.ndarray] = {}
    for name, col in zip(batch.schema.names, batch.columns):
        rows = col.to_pylist()
        if not rows:  # a rowless column has no tensor to build; fail
            # with the column name rather than an IndexError up-stack
            raise ValueError(
                f"input column {name!r} is empty (zero rows); every "
                "column needs tensor-struct rows or base64 payload "
                "rows")
        if isinstance(rows[0], dict):  # tensor struct (dense or sparse)
            merged: Dict[str, Any] = {}
            for row in rows:
                for k, v in (row or {}).items():
                    if v:
                        merged[k] = v
            if merged.get("indiceData"):
                raise ValueError(
                    f"input {name!r} is a sparse tensor; this serving "
                    "stack accepts dense tensors and images only")
            data = np.asarray(merged.get("data", []), np.float32)
            shape = [int(s) for s in merged.get("shape", [])]
            out[name] = data.reshape(shape) if shape else data
        else:  # string: base64 image bytes (the reference's image path).
            # Decode EVERY row, not just row 0 -- a client may chunk a
            # large payload across rows; the decoded chunks concatenate
            # back into the original byte stream
            raw = b"".join(base64.b64decode(r) for r in rows if r)
            out[name] = np.frombuffer(raw, np.uint8)
    return out


def encode_result_value(tensors: Dict[str, np.ndarray]) -> str:
    """Output tensors -> the JSON string stored under the result
    hash's ``value`` field."""
    def tolist(a):
        a = np.asarray(a)
        return a.item() if a.ndim == 0 else a.tolist()

    clean = {k: tolist(v) for k, v in tensors.items()}
    if list(clean) == ["output"]:
        return json.dumps(clean["output"])
    return json.dumps(clean)


# -------------------------------------------------------------- resp --
class _RespConnection:
    """Parses RESP2 command arrays off one client socket."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = b""

    def _fill(self) -> bool:
        chunk = self.sock.recv(65536)
        if not chunk:
            return False
        self.buf += chunk
        return True

    def _line(self) -> Optional[bytes]:
        while b"\r\n" not in self.buf:
            if not self._fill():
                return None
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def _nbytes(self, n: int) -> Optional[bytes]:
        while len(self.buf) < n + 2:
            if not self._fill():
                return None
        data, self.buf = self.buf[:n], self.buf[n + 2:]
        return data

    def read_command(self) -> Optional[List[bytes]]:
        """Parse one RESP command array. The caller may arm a socket
        timeout for the IDLE wait (so a stopped server can reap the
        thread); the moment a command's first bytes arrive the timeout
        is cleared -- a mid-payload stall or a backpressured reply
        must block, never fire a timeout that would desync the parse
        state or truncate a half-written reply."""
        if not self.buf:
            if not self._fill():  # idle point: socket.timeout may
                return None       # propagate to the caller's loop
        self.sock.settimeout(None)
        line = self._line()
        if line is None:
            return None
        while not line.startswith(b"*"):  # inline command (telnet style)
            parts = line.split()
            if parts:
                return parts
            # blank line: keep reading via a LOOP, never recursion -- a
            # client streaming bare CRLFs must not be able to blow the
            # interpreter's recursion limit and kill this connection
            # thread
            line = self._line()
            if line is None:
                return None
        n = int(line[1:])
        parts = []
        for _ in range(n):
            hdr = self._line()
            if hdr is None or not hdr.startswith(b"$"):
                return None
            data = self._nbytes(int(hdr[1:]))
            if data is None:
                return None
            parts.append(data)
        return parts

    # replies ----------------------------------------------------------
    def ok(self, msg: str = "OK") -> None:
        self.sock.sendall(f"+{msg}\r\n".encode())

    def error(self, msg: str) -> None:
        self.sock.sendall(f"-ERR {msg}\r\n".encode())

    def integer(self, n: int) -> None:
        self.sock.sendall(f":{n}\r\n".encode())

    def bulk(self, data) -> None:
        if data is None:
            self.sock.sendall(b"$-1\r\n")
            return
        if isinstance(data, str):
            data = data.encode()
        self.sock.sendall(b"$%d\r\n%s\r\n" % (len(data), data))

    def array(self, items) -> None:
        self.sock.sendall(b"*%d\r\n" % len(items))
        for it in items:
            self.bulk(it)


class RedisFrontend:
    """RESP2 server bridging reference serving clients onto this
    stack's queue backends. Start with ``serve()``; stop with
    ``stop()``. A drain thread moves worker results from the output
    queue into the KEYS/HGETALL-visible result table."""

    def __init__(self, input_queue, output_queue,
                 host: str = "127.0.0.1", port: int = 6379,
                 name: str = "serving_stream"):
        self._in = input_queue
        self._out = output_queue
        self.name = name
        self._results: Dict[str, Dict[str, str]] = {}
        self._groups: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._seq = 0

        adapter = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                conn = _RespConnection(self.request)
                while not adapter._stop.is_set():
                    # finite timeout on the IDLE wait only (so stop()
                    # can reap threads parked on silent connections);
                    # read_command clears it once a command begins, so
                    # slow payloads and backpressured replies block
                    # instead of desyncing or truncating
                    self.request.settimeout(0.5)
                    try:
                        cmd = conn.read_command()
                    except socket.timeout:
                        continue  # idle; re-check stop flag
                    except (ConnectionError, OSError):
                        return
                    if cmd is None:
                        return
                    try:
                        adapter._dispatch(conn, cmd)
                    except (ConnectionError, OSError):
                        return
                    except Exception as e:  # one bad command, not the
                        logger.exception(   # whole connection
                            "redis adapter command failed: %s", e)
                        try:
                            conn.error(str(e))
                        except OSError:
                            return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._threads: List[threading.Thread] = []

    # ---------------------------------------------------------- life --
    def serve(self) -> "RedisFrontend":
        t = threading.Thread(target=self._server.serve_forever,
                             daemon=True)
        d = threading.Thread(target=self._drain_loop, daemon=True)
        t.start()
        d.start()
        self._threads = [t, d]
        logger.info("redis adapter listening on %s:%d (stream %s)",
                    self.host, self.port, self.name)
        return self

    def stop(self) -> None:
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()
        for t in self._threads:
            t.join(timeout=2.0)

    def _drain_loop(self) -> None:
        backoff = _RECONNECT_BASE_S
        while not self._stop.is_set():
            try:
                moved = 0
                for uri, tensors in self._out.dequeue_all():
                    key = f"{RESULT_PREFIX}{self.name}:{uri}"
                    with self._lock:
                        self._results[key] = {
                            "value": encode_result_value(tensors)}
                    moved += 1
                backoff = _RECONNECT_BASE_S  # healthy pass: reset
                if not moved:
                    time.sleep(0.005)
            except (ConnectionError, OSError) as e:
                # the output queue's backend dropped (broker restart,
                # network blip): this thread IS the result path --
                # dying here permanently strands every client poll, so
                # retry forever with capped exponential backoff. The
                # TcpQueue client reconnects per request; we just keep
                # asking.
                if self._stop.is_set():
                    return
                emit_event("redis_reconnect", "serving",
                           error=str(e)[:200],
                           backoff_s=round(backoff, 3))
                logger.warning(
                    "redis adapter result drain lost its queue "
                    "backend (%s); retrying in %.2fs", e, backoff)
                self._stop.wait(backoff)
                backoff = min(backoff * 2.0, _RECONNECT_MAX_S)

    # ------------------------------------------------------ commands --
    def _dispatch(self, conn: _RespConnection,
                  cmd: List[bytes]) -> None:
        op = cmd[0].decode().upper()
        if op == "PING":
            conn.ok("PONG")
        elif op in ("CLIENT", "HELLO", "SELECT"):
            conn.ok()  # redis-py connection handshake chatter
        elif op == "XGROUP":
            self._xgroup(conn, cmd)
        elif op == "XADD":
            self._xadd(conn, cmd)
        elif op == "INFO":
            # the reference client's back-pressure check reads
            # used_memory vs maxmemory; report a tiny fraction so it
            # always proceeds (our queues do their own bounding)
            conn.bulk("# Memory\r\nused_memory:1\r\n"
                      "maxmemory:1000000000\r\n")
        elif op == "KEYS":
            pat = cmd[1].decode()
            with self._lock:
                keys = [k for k in self._results
                        if fnmatch.fnmatchcase(k, pat)]
            conn.array(keys)
        elif op == "HGETALL":
            key = cmd[1].decode()
            with self._lock:
                entry = self._results.get(key, {})
                flat: List[str] = []
                for k, v in entry.items():
                    flat.extend([k, v])
            conn.array(flat)
        elif op in ("DEL", "UNLINK"):
            n = 0
            with self._lock:
                for raw in cmd[1:]:
                    n += self._results.pop(raw.decode(), None) is not None
            conn.integer(n)
        elif op == "EXISTS":
            with self._lock:
                n = sum(raw.decode() in self._results
                        for raw in cmd[1:])
            conn.integer(n)
        else:
            conn.error(f"unknown command '{op}' (this is the "
                       "analytics-zoo-tpu serving adapter, not a full "
                       "redis server)")

    def _xgroup(self, conn: _RespConnection, cmd: List[bytes]) -> None:
        sub = cmd[1].decode().upper() if len(cmd) > 1 else ""
        if sub != "CREATE" or len(cmd) < 4:
            conn.error("only XGROUP CREATE is supported")
            return
        key = (cmd[2].decode(), cmd[3].decode())
        # membership check + add under the lock: two clients racing on
        # XGROUP CREATE must see exactly one +OK and one BUSYGROUP
        # (an unlocked check-then-add could answer +OK to both)
        with self._lock:
            exists = key in self._groups
            if not exists:
                self._groups.add(key)
        if exists:
            # match real redis so client retry logic behaves
            self.sock_err(conn, "BUSYGROUP Consumer Group name "
                                "already exists")
            return
        conn.ok()

    @staticmethod
    def sock_err(conn: _RespConnection, msg: str) -> None:
        conn.sock.sendall(f"-{msg}\r\n".encode())

    def _xadd(self, conn: _RespConnection, cmd: List[bytes]) -> None:
        if len(cmd) < 5:
            conn.error("XADD needs stream, id and field/value pairs")
            return
        stream = cmd[1].decode()
        if stream != self.name:
            # results are keyed under the CONFIGURED stream; silently
            # accepting another name would strand the client polling
            # result keys that never appear -- fail fast instead
            conn.error(f"this adapter serves stream {self.name!r}, "
                       f"not {stream!r} (set the client's name= to "
                       "match the deployment's redis.stream)")
            return
        fields: Dict[bytes, bytes] = {}
        for i in range(3, len(cmd) - 1, 2):
            fields[cmd[i]] = cmd[i + 1]
        # sequence allocation stays inside the lock: concurrent
        # uri-less XADDs must never share a generated uri (results are
        # keyed by uri -- a collision overwrites someone's prediction)
        with self._lock:
            self._seq += 1
            seq = self._seq
        uri = fields.get(b"uri", b"").decode() or f"req-{seq}"
        payload = fields.get(b"data")
        if payload is None:
            conn.error("XADD entry carries no 'data' field")
            return
        tensors = decode_arrow_payload(payload)
        if not self._in.enqueue(uri, **tensors):
            conn.error("OOM input queue full")  # redis-speak for full
            return
        conn.bulk(f"{int(time.time() * 1000)}-{seq}")
