"""Redis-protocol serving adapter: reference clients work unchanged.

The reference's cluster-serving clients speak Redis streams with
Arrow-encoded tensors (ref: pyzoo/zoo/serving/client.py:37-221 --
``XADD serving_stream uri=<id> data=<b64 Arrow RecordBatch>``, results
read back as hashes ``cluster-serving_<stream>:<uri>`` via
KEYS/HGETALL/DEL; ref wire schema: pyzoo/zoo/serving/schema.py
get_field_and_data). This repo's data plane is its own queue design
(queues.py), so this module bridges the gap: a minimal RESP2 server
that accepts exactly the command surface those clients use and adapts
it onto any InputQueue/OutputQueue backend pair.

Two deployment modes:

- **bridge** (the historical single-worker shape): construct with the
  deployment's ``InputQueue``/``OutputQueue``; XADD decodes straight
  into the input queue, a drain thread moves worker results into the
  KEYS/HGETALL-visible result table.
- **stream** (the fleet data plane, ISSUE-9): construct with
  ``input_queue=None``; XADD appends to an in-process
  :class:`StreamStore` and N replica worker processes shard the
  stream through **consumer groups** (XREADGROUP/XACK -- the exact
  fan-out the reference got from FlinkRedisSource's consumer groups,
  ref: serving/engine/FlinkRedisSource.scala). A pending-entries list
  per group remembers which consumer claimed what; entries idle past
  ``zoo.serving.fleet.reclaim_idle_ms`` are **reclaimable**
  (XAUTOCLAIM) so a SIGKILLed replica's claimed-but-unanswered
  requests are re-served by a survivor instead of being orphaned
  forever.

Served commands: XGROUP CREATE, XADD, XREADGROUP, XACK, XPENDING,
XAUTOCLAIM, XLEN, INFO, KEYS, HGETALL, DEL, PING, CLIENT * (redis-py
connection handshake), EXISTS. Everything else gets a clear -ERR.

Wire-format notes:
- XADD ``data`` fields hold a base64 Arrow RecordBatch stream; dense
  tensors arrive as the reference's 4-row struct (indiceData /
  indiceShape / data / shape), strings as base64 image bytes. Sparse
  tensors are rejected with a clear error (this serving stack has no
  sparse input path). XADD ``blob`` fields carry a raw AZT1 wire blob
  (the fleet's replica-to-replica format -- no Arrow round trip).
- Results are stored as ``cluster-serving_<stream>:<uri>`` hashes with
  a ``value`` field holding the JSON-encoded output tensor(s) --
  nested lists, the shape the reference's HTTP route exposes.
"""

from __future__ import annotations

import base64
import collections
import fnmatch
import io
import json
import socket
import socketserver
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.obs.events import emit as emit_event
from analytics_zoo_tpu.obs.metrics import get_registry

logger = get_logger(__name__)

_M_RECLAIMED = get_registry().counter(
    "zoo_serving_stream_reclaimed_total",
    "Pending stream entries reclaimed from dead/stalled consumers "
    "(XAUTOCLAIM with the fleet idle threshold)")

RESULT_PREFIX = "cluster-serving_"
# field name for raw AZT1 blobs riding a stream entry (the fleet data
# plane); reference clients use "data" (base64 Arrow) instead
BLOB_FIELD = b"blob"

# poison-request bound (the fleet's version of the RequestLedger's
# "one error reply after two crashes"): an entry still un-acked after
# this many deliveries has, with high likelihood, KILLED every replica
# that claimed it -- reclaiming it again would crash-loop the whole
# fleet, so the broker dead-letters it with one structured error
# result instead
POISON_MAX_DELIVERIES = 3

# result-drain reconnect backoff (capped exponential): the drain loop
# must survive a broker/queue-backend outage, not die on the first
# ConnectionError and silently strand every future client poll
_RECONNECT_BASE_S = 0.05
_RECONNECT_MAX_S = 5.0


# ------------------------------------------------------------- arrow --
def decode_arrow_payload(b64: bytes) -> Dict[str, np.ndarray]:
    """Base64 Arrow RecordBatch stream -> named input tensors, per the
    reference's schema (ref: schema.py get_field_and_data)."""
    import pyarrow as pa

    buf = base64.b64decode(b64)
    reader = pa.ipc.open_stream(buf)
    batch = next(iter(reader))
    out: Dict[str, np.ndarray] = {}
    for name, col in zip(batch.schema.names, batch.columns):
        rows = col.to_pylist()
        if not rows:  # a rowless column has no tensor to build; fail
            # with the column name rather than an IndexError up-stack
            raise ValueError(
                f"input column {name!r} is empty (zero rows); every "
                "column needs tensor-struct rows or base64 payload "
                "rows")
        if isinstance(rows[0], dict):  # tensor struct (dense or sparse)
            merged: Dict[str, Any] = {}
            for row in rows:
                for k, v in (row or {}).items():
                    if v:
                        merged[k] = v
            if merged.get("indiceData"):
                raise ValueError(
                    f"input {name!r} is a sparse tensor; this serving "
                    "stack accepts dense tensors and images only")
            data = np.asarray(merged.get("data", []), np.float32)
            shape = [int(s) for s in merged.get("shape", [])]
            out[name] = data.reshape(shape) if shape else data
        else:  # string: base64 image bytes (the reference's image path).
            # Decode EVERY row, not just row 0 -- a client may chunk a
            # large payload across rows; the decoded chunks concatenate
            # back into the original byte stream
            raw = b"".join(base64.b64decode(r) for r in rows if r)
            out[name] = np.frombuffer(raw, np.uint8)
    return out


def encode_result_value(tensors: Dict[str, np.ndarray]) -> str:
    """Output tensors -> the JSON string stored under the result
    hash's ``value`` field."""
    def tolist(a):
        a = np.asarray(a)
        return a.item() if a.ndim == 0 else a.tolist()

    clean = {k: tolist(v) for k, v in tensors.items()}
    if list(clean) == ["output"]:
        return json.dumps(clean["output"])
    return json.dumps(clean)


# ------------------------------------------------------------ streams --
class _Pending:
    """One pending-entries-list record: who claimed the entry, when,
    and how many times it has been (re)delivered."""

    __slots__ = ("consumer", "delivered_at", "count")

    def __init__(self, consumer: str, delivered_at: float,
                 count: int = 1):
        self.consumer = consumer
        self.delivered_at = delivered_at
        self.count = count


class StreamStore:
    """In-memory Redis-stream engine with consumer groups.

    The fleet's shared input stream lives here (hosted by the
    controller's :class:`RedisFrontend` in stream mode). Semantics
    follow Redis where it matters for correctness:

    - XADD appends ``(id, fields)``; ids are ``<seq>-0`` with a
      per-stream monotonic ``seq`` (same total order as Redis ids,
      simpler to mint without a clock);
    - XREADGROUP ``>`` delivers entries past the group's
      last-delivered cursor and records each in the group's PEL
      (pending entries list) under the claiming consumer;
    - XACK removes from the PEL -- only then may an entry be trimmed;
    - XAUTOCLAIM reassigns PEL entries idle beyond a threshold to the
      calling consumer (delivery count bumped): the recovery seam for
      entries claimed by a consumer that died before answering.

    Unlike Redis, fully-acknowledged entries are trimmed eagerly (every
    group delivered AND acked them), so ``xlen`` reads as "backlog +
    in-flight" -- exactly the depth admission control and the adaptive
    batcher want -- and memory stays bounded by outstanding work, not
    stream history. ``maxlen`` bounds un-acked backlog; a full stream
    refuses XADD (the queue-full backpressure signal upstream maps to
    503 + Retry-After)."""

    def __init__(self, maxlen: Optional[int] = 10000):
        self._lock = threading.Lock()
        self._maxlen = maxlen
        # stream -> OrderedDict[id, (seq, fields)] (insertion = seq order)
        self._entries: Dict[str, "collections.OrderedDict"] = {}
        self._seq: Dict[str, int] = {}
        # (stream, group) -> {"last": seq, "pel": {id: _Pending}}
        self._groups: Dict[Tuple[str, str], Dict[str, Any]] = {}
        # fully-acked entries PINNED behind an un-acked head (trim only
        # pops head runs): excluded from the outstanding count so one
        # stuck request cannot inflate xlen into -OOM backpressure
        self._done: Dict[str, set] = {}

    # ------------------------------------------------------- producers --
    def xadd(self, stream: str,
             fields: Dict[bytes, bytes]) -> Optional[str]:
        """Append; returns the new id, or None when the stream is at
        ``maxlen`` un-acked entries (backpressure)."""
        with self._lock:
            entries = self._entries.setdefault(
                stream, collections.OrderedDict())
            outstanding = len(entries) - len(self._done.get(stream, ()))
            if self._maxlen is not None and outstanding >= self._maxlen:
                return None
            seq = self._seq.get(stream, 0) + 1
            self._seq[stream] = seq
            entry_id = f"{seq}-0"
            entries[entry_id] = (seq, dict(fields))
            return entry_id

    # ------------------------------------------------------- consumers --
    def create_group(self, stream: str, group: str,
                     start: str = "0") -> bool:
        """Returns False when the group already exists (BUSYGROUP).
        ``start="$"`` delivers only entries added after creation;
        ``"0"`` (the fleet default) delivers from the beginning --
        requests enqueued before the first replica came up must not
        be invisible to the whole fleet."""
        with self._lock:
            key = (stream, group)
            if key in self._groups:
                return False
            last = self._seq.get(stream, 0) if start == "$" else 0
            self._groups[key] = {"last": last, "pel": {}}
            self._entries.setdefault(stream, collections.OrderedDict())
            return True

    def xreadgroup(self, stream: str, group: str, consumer: str,
                   count: int) -> List[Tuple[str, Dict[bytes, bytes]]]:
        with self._lock:
            g = self._groups.get((stream, group))
            if g is None:
                raise KeyError(
                    f"NOGROUP no consumer group {group!r} on stream "
                    f"{stream!r} (XGROUP CREATE it first)")
            out = []
            now = time.monotonic()
            for entry_id, (seq, fields) in self._entries.get(
                    stream, {}).items():
                if seq <= g["last"]:
                    continue
                g["last"] = seq
                g["pel"][entry_id] = _Pending(consumer, now)
                out.append((entry_id, dict(fields)))
                if len(out) >= count:
                    break
            return out

    def xack(self, stream: str, group: str, ids: List[str]) -> int:
        with self._lock:
            g = self._groups.get((stream, group))
            if g is None:
                return 0
            n = 0
            for entry_id in ids:
                if g["pel"].pop(entry_id, None) is not None:
                    n += 1
                    self._mark_done_locked(stream, entry_id)
            if n:
                self._trim_locked(stream)
            return n

    def _entry_done_locked(self, stream: str, entry_id: str,
                           seq: int) -> bool:
        groups = [g for (s, _), g in self._groups.items()
                  if s == stream]
        return bool(groups) and all(
            seq <= g["last"] and entry_id not in g["pel"]
            for g in groups)

    def _mark_done_locked(self, stream: str, entry_id: str) -> None:
        rec = self._entries.get(stream, {}).get(entry_id)
        if rec is not None and self._entry_done_locked(stream, entry_id,
                                                       rec[0]):
            self._done.setdefault(stream, set()).add(entry_id)

    def _trim_locked(self, stream: str) -> None:
        """Pop head runs of entries every group has both delivered and
        acked -- the eager-trim policy that keeps outstanding == real
        work. Entries acked behind an un-acked head stay stored (the
        dict is ordered) but sit in ``_done`` so xlen/backpressure
        ignore them -- one stuck request must not read as a full
        stream."""
        entries = self._entries.get(stream)
        if not entries:
            return
        done = self._done.get(stream, set())
        while entries:
            entry_id, (seq, _) = next(iter(entries.items()))
            if not (entry_id in done
                    or self._entry_done_locked(stream, entry_id, seq)):
                return
            entries.popitem(last=False)
            done.discard(entry_id)

    def xautoclaim(self, stream: str, group: str, consumer: str,
                   min_idle_ms: float, count: int
                   ) -> List[Tuple[str, Dict[bytes, bytes]]]:
        """Reassign up to ``count`` PEL entries idle >= ``min_idle_ms``
        to ``consumer`` (any prior owner, itself included -- a
        restarted same-name consumer recovers its own orphans) and
        return them for re-delivery."""
        with self._lock:
            g = self._groups.get((stream, group))
            if g is None:
                return []
            entries = self._entries.get(stream, {})
            now = time.monotonic()
            out = []
            # sorted by seq so re-delivery keeps arrival order
            for entry_id in sorted(g["pel"],
                                   key=lambda i: int(i.split("-")[0])):
                p = g["pel"][entry_id]
                if (now - p.delivered_at) * 1000.0 < min_idle_ms:
                    continue
                if p.count >= POISON_MAX_DELIVERIES:
                    # presumed poisonous (killed every claimant so
                    # far): left for evict_poisoned's dead-letter
                    # path, never re-served
                    continue
                rec = entries.get(entry_id)
                if rec is None:  # trimmed under our feet: drop the
                    del g["pel"][entry_id]  # dangling PEL record
                    continue
                p.consumer = consumer
                p.delivered_at = now
                p.count += 1
                out.append((entry_id, dict(rec[1])))
                if len(out) >= count:
                    break
            return out

    def evict_poisoned(self, stream: str, group: str,
                       min_idle_ms: float,
                       max_deliveries: int = POISON_MAX_DELIVERIES
                       ) -> List[Tuple[str, Dict[bytes, bytes]]]:
        """Remove-and-return idle PEL entries already delivered
        ``max_deliveries`` times: each claimant died without acking,
        so the entry is presumed to KILL its server and must not be
        reclaimed again (the caller owes each one a structured error
        reply -- the fleet's dead-letter path)."""
        with self._lock:
            g = self._groups.get((stream, group))
            if g is None:
                return []
            entries = self._entries.get(stream, {})
            now = time.monotonic()
            out = []
            for entry_id in sorted(g["pel"],
                                   key=lambda i: int(i.split("-")[0])):
                p = g["pel"][entry_id]
                if (p.count < max_deliveries
                        or (now - p.delivered_at) * 1000.0
                        < min_idle_ms):
                    continue
                del g["pel"][entry_id]
                rec = entries.get(entry_id)
                if rec is None:
                    continue
                out.append((entry_id, dict(rec[1])))
                self._mark_done_locked(stream, entry_id)
            if out:
                self._trim_locked(stream)
            return out

    # --------------------------------------------------- introspection --
    def xlen(self, stream: str) -> int:
        with self._lock:
            return (len(self._entries.get(stream, ()))
                    - len(self._done.get(stream, ())))

    def backlog(self, stream: str, group: str) -> int:
        """Entries not yet delivered to ``group`` -- the autoscaler's
        queue-depth signal (in-flight claims excluded)."""
        with self._lock:
            g = self._groups.get((stream, group))
            entries = self._entries.get(stream)
            if not entries:
                return 0
            if g is None:
                return len(entries)
            last = g["last"]
            return sum(1 for (seq, _) in entries.values() if seq > last)

    def xpending_summary(self, stream: str, group: str
                         ) -> Tuple[int, Optional[str], Optional[str],
                                    List[Tuple[str, int]]]:
        with self._lock:
            g = self._groups.get((stream, group))
            if g is None or not g["pel"]:
                return 0, None, None, []
            ids = sorted(g["pel"], key=lambda i: int(i.split("-")[0]))
            per: Dict[str, int] = {}
            for p in g["pel"].values():
                per[p.consumer] = per.get(p.consumer, 0) + 1
            return (len(ids), ids[0], ids[-1], sorted(per.items()))

    def xpending_range(self, stream: str, group: str, count: int
                       ) -> List[Tuple[str, str, int, int]]:
        """[(id, consumer, idle_ms, delivery_count)] oldest-first."""
        with self._lock:
            g = self._groups.get((stream, group))
            if g is None:
                return []
            now = time.monotonic()
            out = []
            for entry_id in sorted(g["pel"],
                                   key=lambda i: int(i.split("-")[0])):
                p = g["pel"][entry_id]
                out.append((entry_id, p.consumer,
                            int((now - p.delivered_at) * 1000), p.count))
                if len(out) >= count:
                    break
            return out


# -------------------------------------------------------------- resp --
class _RespConnection:
    """Parses RESP2 command arrays off one client socket."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = b""

    def _fill(self) -> bool:
        chunk = self.sock.recv(65536)
        if not chunk:
            return False
        self.buf += chunk
        return True

    def _line(self) -> Optional[bytes]:
        while b"\r\n" not in self.buf:
            if not self._fill():
                return None
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def _nbytes(self, n: int) -> Optional[bytes]:
        while len(self.buf) < n + 2:
            if not self._fill():
                return None
        data, self.buf = self.buf[:n], self.buf[n + 2:]
        return data

    def read_command(self) -> Optional[List[bytes]]:
        """Parse one RESP command array. The caller may arm a socket
        timeout for the IDLE wait (so a stopped server can reap the
        thread); the moment a command's first bytes arrive the timeout
        is cleared -- a mid-payload stall or a backpressured reply
        must block, never fire a timeout that would desync the parse
        state or truncate a half-written reply."""
        if not self.buf:
            if not self._fill():  # idle point: socket.timeout may
                return None       # propagate to the caller's loop
        self.sock.settimeout(None)
        line = self._line()
        if line is None:
            return None
        while not line.startswith(b"*"):  # inline command (telnet style)
            parts = line.split()
            if parts:
                return parts
            # blank line: keep reading via a LOOP, never recursion -- a
            # client streaming bare CRLFs must not be able to blow the
            # interpreter's recursion limit and kill this connection
            # thread
            line = self._line()
            if line is None:
                return None
        n = int(line[1:])
        parts = []
        for _ in range(n):
            hdr = self._line()
            if hdr is None or not hdr.startswith(b"$"):
                return None
            data = self._nbytes(int(hdr[1:]))
            if data is None:
                return None
            parts.append(data)
        return parts

    # replies ----------------------------------------------------------
    def ok(self, msg: str = "OK") -> None:
        self.sock.sendall(f"+{msg}\r\n".encode())

    def error(self, msg: str) -> None:
        self.sock.sendall(f"-ERR {msg}\r\n".encode())

    def integer(self, n: int) -> None:
        self.sock.sendall(f":{n}\r\n".encode())

    def bulk(self, data) -> None:
        if data is None:
            self.sock.sendall(b"$-1\r\n")
            return
        if isinstance(data, str):
            data = data.encode()
        self.sock.sendall(b"$%d\r\n%s\r\n" % (len(data), data))

    def array(self, items) -> None:
        self.sock.sendall(b"*%d\r\n" % len(items))
        for it in items:
            self.bulk(it)

    def resp(self, obj) -> None:
        """Nested RESP2 reply: ints -> :n, None -> nil bulk, lists ->
        arrays (recursive -- XREADGROUP/XAUTOCLAIM reply shapes),
        everything else a bulk string."""
        parts: List[bytes] = []
        self._resp_parts(obj, parts)
        self.sock.sendall(b"".join(parts))

    def _resp_parts(self, obj, parts: List[bytes]) -> None:
        if obj is None:
            parts.append(b"$-1\r\n")
        elif isinstance(obj, bool):  # before int: bool is an int
            parts.append(b":%d\r\n" % int(obj))
        elif isinstance(obj, int):
            parts.append(b":%d\r\n" % obj)
        elif isinstance(obj, (list, tuple)):
            parts.append(b"*%d\r\n" % len(obj))
            for it in obj:
                self._resp_parts(it, parts)
        else:
            data = obj.encode() if isinstance(obj, str) else bytes(obj)
            parts.append(b"$%d\r\n%s\r\n" % (len(data), data))


class RedisFrontend:
    """RESP2 server over this stack's serving data plane. Start with
    ``serve()``; stop with ``stop()``.

    **Bridge mode** (``input_queue`` given, the historical shape):
    XADD decodes straight into the input queue; a drain thread moves
    worker results from ``output_queue`` into the KEYS/HGETALL-visible
    result table.

    **Stream mode** (``input_queue=None``, the fleet broker): XADD
    appends to an in-process :class:`StreamStore`; replica workers
    shard the stream via XREADGROUP consumer groups
    (:class:`RedisStreamQueue` is the client backend) and push result
    blobs to ``result_stream`` on the same store, which the drain
    thread consumes into the result table. ``result_callback(uri,
    tensors)`` observes every consumed result (the fleet soak's
    exactly-once ledger)."""

    def __init__(self, input_queue=None, output_queue=None,
                 host: Optional[str] = None, port: int = 6379,
                 name: str = "serving_stream",
                 result_stream: str = "result_stream",
                 store: Optional[StreamStore] = None,
                 maxlen: Optional[int] = 10000,
                 result_callback: Optional[Callable] = None):
        if (input_queue is None) != (output_queue is None):
            raise ValueError("pass both queues (bridge mode) or "
                             "neither (stream mode)")
        if host is None:
            # cross-host fleets bind 0.0.0.0 via
            # zoo.serving.fleet.bind_host (ISSUE-20); loopback stays
            # the default so single-host deployments expose nothing
            host = str(get_config().get(
                "zoo.serving.fleet.bind_host", "127.0.0.1"))
        self._in = input_queue
        self._out = output_queue
        self.name = name
        self.result_stream = result_stream
        self.stream_mode = input_queue is None
        self.store = store or StreamStore(maxlen=maxlen)
        self.result_callback = result_callback
        self._results: Dict[str, Dict[str, str]] = {}
        # fleet-level exactly-once (stream mode): the PEL's reclaim is
        # at-least-once by construction -- a replica SIGKILLed between
        # reply-push and XACK gets its entry re-served -- so stream
        # mode keeps a delivery LEDGER (the RequestLedger idea at
        # fleet level): a second result for an already-answered uri is
        # a re-serve, suppressed and counted, never delivered twice.
        # The ledger is its OWN bounded structure, not the result
        # table: clients DEL table entries after reading (reopening
        # the window) and may never DEL at all (unbounded table is
        # reference behavior; an unbounded ledger would not be).
        self.duplicates_suppressed = 0
        self._answered: "collections.OrderedDict[str, bool]" = (
            collections.OrderedDict())
        self._answered_cap = 65536
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._seq = 0

        adapter = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                conn = _RespConnection(self.request)
                while not adapter._stop.is_set():
                    # finite timeout on the IDLE wait only (so stop()
                    # can reap threads parked on silent connections);
                    # read_command clears it once a command begins, so
                    # slow payloads and backpressured replies block
                    # instead of desyncing or truncating
                    self.request.settimeout(0.5)
                    try:
                        cmd = conn.read_command()
                    except socket.timeout:
                        continue  # idle; re-check stop flag
                    except (ConnectionError, OSError):
                        return
                    if cmd is None:
                        return
                    try:
                        adapter._dispatch(conn, cmd)
                    except (ConnectionError, OSError):
                        return
                    except Exception as e:  # one bad command, not the
                        logger.exception(   # whole connection
                            "redis adapter command failed: %s", e)
                        try:
                            conn.error(str(e))
                        except OSError:
                            return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._threads: List[threading.Thread] = []

    # ---------------------------------------------------------- life --
    def serve(self) -> "RedisFrontend":
        t = threading.Thread(target=self._server.serve_forever,
                             daemon=True)
        d = threading.Thread(target=self._drain_loop, daemon=True)
        t.start()
        d.start()
        self._threads = [t, d]
        logger.info("redis adapter listening on %s:%d (stream %s)",
                    self.host, self.port, self.name)
        return self

    def stop(self) -> None:
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()
        for t in self._threads:
            t.join(timeout=2.0)

    def _store_result(self, uri: str, tensors) -> None:
        key = f"{RESULT_PREFIX}{self.name}:{uri}"
        with self._lock:
            if self.stream_mode:
                if uri in self._answered:
                    # delivery-ledger hit: this request was already
                    # answered (the at-least-once redelivery window --
                    # see duplicates_suppressed above). Checked even
                    # after a client DELs the table entry.
                    self.duplicates_suppressed += 1
                    logger.warning(
                        "suppressed duplicate result for %s "
                        "(re-served after a reclaim race)", uri)
                    return
                self._answered[uri] = True
                while len(self._answered) > self._answered_cap:
                    # bound: the oldest answers age out of dedup
                    # coverage (a re-serve arrives within seconds of
                    # its original -- reclaim_idle_ms scale -- so the
                    # cap only needs to outlive that window)
                    self._answered.popitem(last=False)
            self._results[key] = {
                "value": encode_result_value(tensors)}
        if self.result_callback is not None:
            try:
                self.result_callback(uri, tensors)
            except Exception as e:  # an observer bug must not kill
                logger.exception(   # the result path
                    "redis adapter result callback failed: %s", e)

    def _drain_results_once(self) -> int:
        """One drain pass; returns results moved into the table."""
        if not self.stream_mode:
            moved = 0
            for uri, tensors in self._out.dequeue_all():
                self._store_result(uri, tensors)
                moved += 1
            return moved
        # stream mode: the result stream lives in OUR store -- consume
        # it directly (group "router", acked immediately: the table is
        # the durable side, and a controller restart restarts the
        # whole broker anyway)
        from analytics_zoo_tpu.serving.queues import _decode

        self.store.create_group(self.result_stream, "router")
        moved = 0
        while True:
            entries = self.store.xreadgroup(
                self.result_stream, "router", "controller", 256)
            if not entries:
                return moved
            self.store.xack(self.result_stream, "router",
                            [eid for eid, _ in entries])
            for _, fields in entries:
                blob = fields.get(BLOB_FIELD)
                if blob is None:
                    continue
                try:
                    uri, tensors = _decode(blob)
                except Exception as e:  # one bad blob, not the drain
                    logger.exception(
                        "redis adapter: undecodable result blob: %s", e)
                    continue
                self._store_result(uri, tensors)
                moved += 1

    def _drain_loop(self) -> None:
        backoff = _RECONNECT_BASE_S
        while not self._stop.is_set():
            try:
                moved = self._drain_results_once()
                backoff = _RECONNECT_BASE_S  # healthy pass: reset
                if not moved:
                    time.sleep(0.005)
            except (ConnectionError, OSError) as e:
                # the output queue's backend dropped (broker restart,
                # network blip): this thread IS the result path --
                # dying here permanently strands every client poll, so
                # retry forever with capped exponential backoff. The
                # TcpQueue client reconnects per request; we just keep
                # asking.
                if self._stop.is_set():
                    return
                emit_event("redis_reconnect", "serving",
                           error=str(e)[:200],
                           backoff_s=round(backoff, 3))
                logger.warning(
                    "redis adapter result drain lost its queue "
                    "backend (%s); retrying in %.2fs", e, backoff)
                self._stop.wait(backoff)
                backoff = min(backoff * 2.0, _RECONNECT_MAX_S)

    # ------------------------------------------------------ commands --
    def _dispatch(self, conn: _RespConnection,
                  cmd: List[bytes]) -> None:
        op = cmd[0].decode().upper()
        if op == "PING":
            conn.ok("PONG")
        elif op in ("CLIENT", "HELLO", "SELECT"):
            conn.ok()  # redis-py connection handshake chatter
        elif op == "XGROUP":
            self._xgroup(conn, cmd)
        elif op == "XADD":
            self._xadd(conn, cmd)
        elif op == "XREADGROUP":
            self._xreadgroup(conn, cmd)
        elif op == "XACK":
            n = self.store.xack(cmd[1].decode(), cmd[2].decode(),
                                [c.decode() for c in cmd[3:]])
            conn.integer(n)
        elif op == "XLEN":
            conn.integer(self.store.xlen(cmd[1].decode()))
        elif op == "XPENDING":
            self._xpending(conn, cmd)
        elif op == "XAUTOCLAIM":
            self._xautoclaim(conn, cmd)
        elif op == "INFO":
            # the reference client's back-pressure check reads
            # used_memory vs maxmemory; report a tiny fraction so it
            # always proceeds (our queues do their own bounding)
            conn.bulk("# Memory\r\nused_memory:1\r\n"
                      "maxmemory:1000000000\r\n")
        elif op == "KEYS":
            pat = cmd[1].decode()
            with self._lock:
                keys = [k for k in self._results
                        if fnmatch.fnmatchcase(k, pat)]
            conn.array(keys)
        elif op == "HGETALL":
            key = cmd[1].decode()
            with self._lock:
                entry = self._results.get(key, {})
                flat: List[str] = []
                for k, v in entry.items():
                    flat.extend([k, v])
            conn.array(flat)
        elif op in ("DEL", "UNLINK"):
            n = 0
            with self._lock:
                for raw in cmd[1:]:
                    n += self._results.pop(raw.decode(), None) is not None
            conn.integer(n)
        elif op == "EXISTS":
            with self._lock:
                n = sum(raw.decode() in self._results
                        for raw in cmd[1:])
            conn.integer(n)
        else:
            conn.error(f"unknown command '{op}' (this is the "
                       "analytics-zoo-tpu serving adapter, not a full "
                       "redis server)")

    def _xgroup(self, conn: _RespConnection, cmd: List[bytes]) -> None:
        sub = cmd[1].decode().upper() if len(cmd) > 1 else ""
        if sub != "CREATE" or len(cmd) < 4:
            conn.error("only XGROUP CREATE is supported")
            return
        start = cmd[4].decode() if len(cmd) > 4 else "$"
        # StreamStore.create_group is atomic: two clients racing on
        # XGROUP CREATE see exactly one +OK and one BUSYGROUP
        if not self.store.create_group(cmd[2].decode(),
                                       cmd[3].decode(), start=start):
            # match real redis so client retry logic behaves
            self.sock_err(conn, "BUSYGROUP Consumer Group name "
                                "already exists")
            return
        conn.ok()

    def _xreadgroup(self, conn: _RespConnection,
                    cmd: List[bytes]) -> None:
        # XREADGROUP GROUP <g> <consumer> [COUNT n] STREAMS <s> >
        # (no BLOCK support -- clients poll; the adaptive batcher's
        # pull loop is already a poll)
        args = [c.decode() for c in cmd[1:]]
        upper = [a.upper() for a in args]
        try:
            gi = upper.index("GROUP")
            group, consumer = args[gi + 1], args[gi + 2]
            count = (int(args[upper.index("COUNT") + 1])
                     if "COUNT" in upper else 1)
            stream = args[upper.index("STREAMS") + 1]
        except (ValueError, IndexError):
            conn.error("XREADGROUP needs GROUP <g> <consumer> "
                       "[COUNT n] STREAMS <stream> >")
            return
        try:
            entries = self.store.xreadgroup(stream, group, consumer,
                                            count)
        except KeyError as e:
            self.sock_err(conn, str(e).strip("'\""))
            return
        if not entries:
            conn.resp(None)
            return
        conn.resp([[stream, [
            [eid, [x for kv in fields.items() for x in kv]]
            for eid, fields in entries]]])

    def _xpending(self, conn: _RespConnection,
                  cmd: List[bytes]) -> None:
        stream, group = cmd[1].decode(), cmd[2].decode()
        if len(cmd) >= 6:  # XPENDING s g - + count (detail form)
            count = int(cmd[5])
            conn.resp([[eid, consumer, idle_ms, n] for
                       eid, consumer, idle_ms, n in
                       self.store.xpending_range(stream, group, count)])
            return
        total, lo, hi, per = self.store.xpending_summary(stream, group)
        conn.resp([total, lo, hi,
                   [[c, str(n)] for c, n in per] if per else None])

    def _xautoclaim(self, conn: _RespConnection,
                    cmd: List[bytes]) -> None:
        # XAUTOCLAIM <s> <g> <consumer> <min-idle-ms> <start> [COUNT n]
        if len(cmd) < 6:
            conn.error("XAUTOCLAIM needs stream, group, consumer, "
                       "min-idle-time and start")
            return
        args = [c.decode() for c in cmd[1:]]
        count = 100
        if len(args) >= 7 and args[5].upper() == "COUNT":
            count = int(args[6])
        if self.stream_mode:
            # dead-letter seam: entries whose every delivery ended in
            # an un-acked death are answered with ONE structured error
            # (the RequestLedger contract at fleet level) instead of
            # being reclaimed into another crash
            self._dead_letter(args[0], args[1], float(args[3]))
        entries = self.store.xautoclaim(args[0], args[1], args[2],
                                        float(args[3]), count)
        conn.resp(["0-0", [
            [eid, [x for kv in fields.items() for x in kv]]
            for eid, fields in entries], []])

    def _dead_letter(self, stream: str, group: str,
                     min_idle_ms: float) -> None:
        from analytics_zoo_tpu.serving.protocol import ERROR_KEY
        from analytics_zoo_tpu.serving.queues import _decode_request

        for _, fields in self.store.evict_poisoned(stream, group,
                                                   min_idle_ms):
            blob = fields.get(BLOB_FIELD)
            if blob is None:
                continue
            try:
                uri = _decode_request(blob)[0]
            except Exception:
                continue  # undecodable: nothing to answer
            msg = (f"request failed: {POISON_MAX_DELIVERIES} replicas "
                   "died while serving it (dead-lettered)")
            emit_event("serving_error", "serving", uri=uri, error=msg)
            logger.error("dead-lettering %s: %s", uri, msg)
            self._store_result(uri, {ERROR_KEY: np.asarray(msg)})

    @staticmethod
    def sock_err(conn: _RespConnection, msg: str) -> None:
        conn.sock.sendall(f"-{msg}\r\n".encode())

    def _xadd(self, conn: _RespConnection, cmd: List[bytes]) -> None:
        if len(cmd) < 5:
            conn.error("XADD needs stream, id and field/value pairs")
            return
        stream = cmd[1].decode()
        if not self.stream_mode and stream != self.name:
            # bridge mode: results are keyed under the CONFIGURED
            # stream; silently accepting another name would strand the
            # client polling result keys that never appear -- fail
            # fast instead. (Stream mode accepts any stream: reply /
            # result streams are part of the fleet plumbing.)
            conn.error(f"this adapter serves stream {self.name!r}, "
                       f"not {stream!r} (set the client's name= to "
                       "match the deployment's redis.stream)")
            return
        fields: Dict[bytes, bytes] = {}
        for i in range(3, len(cmd) - 1, 2):
            fields[cmd[i]] = cmd[i + 1]
        if self.stream_mode and BLOB_FIELD in fields:
            # fleet fast path: the entry already IS an AZT1 wire blob
            if self.store.xadd(stream, fields) is None:
                conn.error("OOM input queue full")
                return
            with self._lock:
                self._seq += 1
                seq = self._seq
            conn.bulk(f"{int(time.time() * 1000)}-{seq}")
            return
        # sequence allocation stays inside the lock: concurrent
        # uri-less XADDs must never share a generated uri (results are
        # keyed by uri -- a collision overwrites someone's prediction)
        with self._lock:
            self._seq += 1
            seq = self._seq
        uri = fields.get(b"uri", b"").decode() or f"req-{seq}"
        payload = fields.get(b"data")
        if payload is None:
            conn.error("XADD entry carries no 'data' field")
            return
        tensors = decode_arrow_payload(payload)
        if self.stream_mode:
            # reference client on the fleet broker: re-encode as the
            # one wire format replicas decode (uri rides the blob)
            from analytics_zoo_tpu.serving.queues import _encode

            blob = _encode(uri, tensors)
            if self.store.xadd(stream, {BLOB_FIELD: blob}) is None:
                conn.error("OOM input queue full")
                return
            with self._lock:
                # a RE-SUBMITTED uri is a new request: it re-opens
                # the delivery ledger (fleet blob producers mint
                # unique ids; uri reuse is a reference-client idiom)
                self._answered.pop(uri, None)
        elif not self._in.enqueue(uri, **tensors):
            conn.error("OOM input queue full")  # redis-speak for full
            return
        conn.bulk(f"{int(time.time() * 1000)}-{seq}")


# ------------------------------------------------------ stream client --
class RedisReplyError(Exception):
    """The server answered ``-ERR ...`` (application-level refusal,
    e.g. a full stream); connection-level failures stay OSError."""


class RedisStreamQueue:
    """Queue backend over the adapter's RESP2 stream surface.

    The fleet's consumer-group client (ISSUE-9): N replica processes
    construct this with the same ``group`` and distinct ``consumer``
    names, and the broker shards the request stream across them --
    each entry is delivered to exactly one consumer, tracked in the
    group's pending list until that consumer ACKs it (the worker acks
    when it pushes the reply, so a SIGKILLed replica's claimed-but-
    unanswered entries stay pending). Every claim pass first runs
    XAUTOCLAIM with ``zoo.serving.fleet.reclaim_idle_ms``: entries a
    dead consumer left idle past the threshold are reclaimed and
    re-served by the caller -- without this, a crashed group member
    orphans its pending messages forever.

    Without ``group`` the instance is a producer / destructive
    consumer (``autoack`` forced): reply/result streams with a single
    owner. Implements the queue-backend protocol ``put`` / ``get`` /
    ``get_many`` / ``__len__`` plus the fleet seams ``ack_uris``
    (called by the worker on reply), ``pause``/``resume`` (the drain
    seam: a paused queue claims nothing new), and ``for_stream`` (the
    worker's reply-to routing)."""

    def __init__(self, address: str, stream: str = "serving_stream",
                 group: Optional[str] = None,
                 consumer: Optional[str] = None,
                 autoack: bool = False,
                 reclaim_idle_ms: Optional[float] = None):
        addr = address
        for prefix in ("redis://", "tcp://"):
            if addr.startswith(prefix):
                addr = addr[len(prefix):]
        host, port = addr.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self.stream = stream
        self.group = group
        self.consumer = consumer or f"consumer-{id(self):x}"
        self.autoack = bool(autoack) or group is None
        self.reclaim_idle_ms = float(
            get_config().get("zoo.serving.fleet.reclaim_idle_ms", 5000.0)
            if reclaim_idle_ms is None else reclaim_idle_ms)
        self._lock = threading.Lock()     # socket (one in-flight cmd)
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._plock = threading.Lock()    # uri -> [entry ids] claims
        self._pending: "collections.OrderedDict[str, List[str]]" = (
            collections.OrderedDict())
        self._group_ready = False
        self._paused = False
        # reclaim pacing: XAUTOCLAIM scans the whole PEL under the
        # store's lock, and idle workers poll every few ms -- running
        # it on every claim pass would double broker traffic for a
        # signal that only changes at reclaim_idle_ms granularity.
        # Half the threshold keeps worst-case recovery latency at
        # ~1.5x the threshold while the steady state pays one
        # XREADGROUP per poll.
        self._next_reclaim = 0.0

    # ------------------------------------------------------- transport --
    def _connect(self) -> None:
        # only ever called from _cmd, which already holds self._lock
        self._sock = socket.create_connection(  # zoolint: disable=lock-guard
            (self._host, self._port), timeout=30.0)
        self._buf = b""

    def _cmd(self, *parts):
        """One RESP2 command round trip (under the socket lock, one
        reconnect retry -- the TcpQueue convention)."""
        payload = [b"*%d\r\n" % len(parts)]
        for p in parts:
            b = (p.encode() if isinstance(p, str)
                 else str(p).encode() if isinstance(p, int)
                 else bytes(p))
            payload.append(b"$%d\r\n%s\r\n" % (len(b), b))
        data = b"".join(payload)
        with self._lock:
            for attempt in (0, 1):
                try:
                    if self._sock is None:
                        self._connect()
                    self._sock.sendall(data)
                    return self._reply()
                except OSError:
                    try:
                        if self._sock is not None:
                            self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                    if attempt:
                        raise
        raise OSError("unreachable")

    def _fill(self) -> None:
        chunk = self._sock.recv(65536)
        if not chunk:
            raise OSError("connection closed")
        self._buf += chunk

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            self._fill()
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_nbytes(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            self._fill()
        data, self._buf = self._buf[:n], self._buf[n + 2:]
        return data

    def _reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RedisReplyError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            return None if n < 0 else self._read_nbytes(n)
        if kind == b"*":
            n = int(rest)
            return None if n < 0 else [self._reply() for _ in range(n)]
        raise OSError(f"bad RESP reply type {line!r}")

    # --------------------------------------------------------- produce --
    def put(self, item: bytes) -> bool:
        try:
            self._cmd("XADD", self.stream, "*", "blob", item)
            return True
        except RedisReplyError as e:
            if "OOM" in str(e):
                return False  # stream full: the backpressure signal
            raise

    def for_stream(self, name: str) -> "RedisStreamQueue":
        """Producer handle for another stream on the same broker (the
        worker's reply-to routing)."""
        return RedisStreamQueue(f"{self._host}:{self._port}",
                                stream=name)

    # --------------------------------------------------------- consume --
    def pause(self) -> None:
        """Drain seam: stop claiming new entries (in-flight claims
        still get acked); ``resume`` re-arms."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def _ensure_group(self) -> None:
        if self._group_ready:
            return
        try:
            # from "0": entries enqueued before the first consumer
            # came up must not be invisible to the whole group
            self._cmd("XGROUP", "CREATE", self.stream, self.group, "0")
        except RedisReplyError as e:
            if "BUSYGROUP" not in str(e):
                raise
        self._group_ready = True

    def _entries_to_blobs(self, entries) -> List[bytes]:
        """Flatten [[id, [k, v, ...]], ...] into blobs, recording the
        uri -> entry-id claim map ``ack_uris`` settles later."""
        from analytics_zoo_tpu.serving.queues import _decode_request

        blobs: List[bytes] = []
        ack_now: List[str] = []
        for entry in entries or []:
            entry_id, kvs = entry[0], entry[1]
            fields = {bytes(kvs[i]): kvs[i + 1]
                      for i in range(0, len(kvs), 2)}
            blob = fields.get(BLOB_FIELD)
            if blob is None:
                ack_now.append(entry_id)  # foreign entry: drop + ack,
                continue                  # or it redelivers forever
            blobs.append(blob)
            entry_id = (entry_id.decode()
                        if isinstance(entry_id, bytes) else entry_id)
            if self.autoack:
                ack_now.append(entry_id)
                continue
            try:
                uri = _decode_request(blob)[0]
            except Exception:
                ack_now.append(entry_id)  # undecodable: the worker
                continue                  # will drop it too
            with self._plock:
                self._pending.setdefault(uri, []).append(entry_id)
                while len(self._pending) > 8192:
                    # bound the claim map: oldest claims age out of
                    # ack coverage (reclaim re-delivers them if the
                    # worker truly never answered)
                    self._pending.popitem(last=False)
        if ack_now:
            self._cmd("XACK", self.stream, self.group, *ack_now)
        return blobs

    def _claim(self, n: int) -> List[bytes]:
        if self.group is None or self._paused:
            return []
        self._ensure_group()
        blobs: List[bytes] = []
        now = time.monotonic()
        if self.reclaim_idle_ms > 0 and now >= self._next_reclaim:
            self._next_reclaim = now + self.reclaim_idle_ms / 2000.0
            reply = self._cmd("XAUTOCLAIM", self.stream, self.group,
                              self.consumer,
                              str(int(self.reclaim_idle_ms)), "0",
                              "COUNT", str(n))
            reclaimed = self._entries_to_blobs(reply[1] if reply else [])
            if reclaimed:
                _M_RECLAIMED.inc(len(reclaimed))
                emit_event("stream_reclaim", "serving",
                           stream=self.stream, group=self.group,
                           n=len(reclaimed))
                logger.warning(
                    "reclaimed %d pending entries idle > %.0f ms on "
                    "%s/%s (previous consumer presumed dead)",
                    len(reclaimed), self.reclaim_idle_ms, self.stream,
                    self.group)
            blobs.extend(reclaimed)
        if len(blobs) < n:
            reply = self._cmd("XREADGROUP", "GROUP", self.group,
                              self.consumer, "COUNT",
                              str(n - len(blobs)), "STREAMS",
                              self.stream, ">")
            if reply:
                blobs.extend(self._entries_to_blobs(reply[0][1]))
        return blobs

    def get(self, timeout: Optional[float] = None) -> Optional[bytes]:
        deadline = (None if timeout is None
                    else time.monotonic() + max(0.0, timeout))
        while True:
            blobs = self._claim(1)
            if blobs:
                return blobs[0]
            if deadline is not None and time.monotonic() >= deadline:
                return None
            time.sleep(0.005)

    def get_many(self, n: int) -> List[bytes]:
        return self._claim(n)

    def ack_uris(self, uris) -> None:
        """Settle claims: called by the worker the moment a request's
        reply is pushed (or its loss accounted). Only an acked entry
        leaves the group's pending list -- everything else is
        reclaimable after the idle threshold."""
        if self.group is None:
            return
        ids: List[str] = []
        with self._plock:
            for uri in uris:
                ids.extend(self._pending.pop(uri, ()))
        if ids:
            try:
                self._cmd("XACK", self.stream, self.group, *ids)
            except (OSError, RedisReplyError) as e:
                # broker briefly away: the entries stay pending and
                # re-deliver after the idle threshold -- duplicate
                # work, never lost work
                logger.warning("XACK of %d entries failed (%s); they "
                               "will re-deliver after the idle "
                               "threshold", len(ids), e)

    def __len__(self) -> int:
        n = self._cmd("XLEN", self.stream)
        return int(n) if isinstance(n, int) else 0

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


# ------------------------------------------------- liveness probe --
# ISSUE-20: a dead broker used to surface only as generic connection
# errors deep inside a claim pass. probe_broker is one cheap PING
# round trip; wait_broker retries it with capped-exponential backoff
# and emits ONE broker_unreachable event when the budget is spent --
# the readiness gate remote replicas and the fleet router run before
# touching the data plane.

def _split_address(address: str) -> Tuple[str, int]:
    """``host:port`` (optionally ``redis://``/``tcp://``-prefixed)
    -> (host, port)."""
    addr = address
    for prefix in ("redis://", "tcp://"):
        if addr.startswith(prefix):
            addr = addr[len(prefix):]
    host, port = addr.rsplit(":", 1)
    return host, int(port)


def probe_broker(address: str, timeout_s: float = 2.0) -> bool:
    """One PING round trip against the stream broker; True iff it
    answered PONG inside ``timeout_s``."""
    host, port = _split_address(address)
    try:
        with socket.create_connection((host, port),
                                      timeout=timeout_s) as sock:
            sock.sendall(b"*1\r\n$4\r\nPING\r\n")
            sock.settimeout(timeout_s)
            data = sock.recv(64)
            return data.startswith((b"+PONG", b"$4\r\nPONG"))
    except OSError:
        return False


def wait_broker(address: str, retries: Optional[int] = None,
                base_s: Optional[float] = None,
                max_s: Optional[float] = None,
                timeout_s: float = 2.0) -> bool:
    """Readiness-probe the broker with capped-backoff retries
    (``zoo.serving.fleet.broker_probe_*`` defaults). False -- after
    emitting one structured ``broker_unreachable`` event -- when every
    attempt failed; callers decide whether that is fatal (a launching
    replica) or a soft degradation (a router health sweep)."""
    cfg = get_config()
    if retries is None:
        retries = int(cfg.get(
            "zoo.serving.fleet.broker_probe_retries", 6))
    if base_s is None:
        base_s = float(cfg.get(
            "zoo.serving.fleet.broker_probe_base_s", 0.05))
    if max_s is None:
        max_s = float(cfg.get(
            "zoo.serving.fleet.broker_probe_max_s", 2.0))
    t0 = time.monotonic()
    backoff = base_s
    for attempt in range(int(retries) + 1):
        if probe_broker(address, timeout_s=timeout_s):
            return True
        if attempt < int(retries):
            time.sleep(backoff)
            backoff = min(backoff * 2.0, max_s)
    waited = time.monotonic() - t0
    emit_event("broker_unreachable", "serving", address=address,
               retries=int(retries), waited_s=round(waited, 3))
    logger.warning("broker %s unreachable after %d probes (%.2fs)",
                   address, int(retries) + 1, waited)
    return False
