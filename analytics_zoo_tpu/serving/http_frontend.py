"""HTTP frontend: /predict + observability routes over the serving queues.

The analog of the akka-http frontend (ref: zoo/.../serving/http/
FrontEndApp.scala:40-130 -- a /predict route that XADDs the request into
Redis, awaits the result stream, and a /metrics route exposing timer
percentiles). Here: a stdlib ``ThreadingHTTPServer``; each /predict POST
enqueues into the InputQueue with a fresh uri, a router thread drains the
OutputQueue into per-uri mailboxes, and the handler blocks on its mailbox
with a deadline. Dependency-free wire format:

  POST /predict       {"inputs": {"x": [[...]]}}         -> {"predictions": ...}
  POST /predict       {"instances": [{"x": [...]}, ...]} -> {"predictions": [...]}
  GET  /metrics       Prometheus text exposition (process registry)
  GET  /metrics.json  JSON snapshot: registry + frontend/worker summaries
  GET  /healthz       liveness (200, or 503 when the worker thread died)
  GET  /trace         Chrome trace-event JSON of collected request spans
  GET  /debug/events  structured event-log tail (?n=&type=&subsystem=)
  GET  /debug/vars    resolved config + build/uptime/process info

Unknown paths get a 404 with a JSON error body. With
``zoo.obs.trace.enabled`` each /predict carries a fresh trace id through
the queue blobs, so its worker-side decode/dispatch/finalize spans join
the frontend's ``http_request`` span under one id (docs/observability.md).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import parse_qs

import numpy as np

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.obs import tracing
from analytics_zoo_tpu.obs.events import emit as emit_event
from analytics_zoo_tpu.obs.events import get_event_log, to_jsonable
from analytics_zoo_tpu.obs.flight import get_inflight
from analytics_zoo_tpu.obs.metrics import get_registry
from analytics_zoo_tpu.serving.protocol import (
    DRAINING_PREFIX, ERROR_KEY, error_status)
from analytics_zoo_tpu.serving.timer import Timer

logger = get_logger(__name__)

_REG = get_registry()
_M_HTTP_STAGE = _REG.histogram(
    "zoo_http_stage_duration_seconds",
    "HTTP frontend stage latency (predict_request, ...)",
    labelnames=("stage",))
_M_HTTP_REQS = _REG.counter(
    "zoo_http_requests_total", "HTTP requests served, by route and "
    "status code", labelnames=("route", "code"))
_M_HTTP_DROPPED = _REG.counter(
    "zoo_http_dropped_results_total",
    "Results dropped for abandoned (timed-out) requests")

# label-cardinality guard: only known routes get their own label value;
# everything else (scanners probing arbitrary 404 paths) collapses to
# "other" so client-supplied URLs cannot grow the registry unboundedly
_KNOWN_ROUTES = frozenset(
    ("/predict", "/metrics", "/metrics.json", "/healthz", "/trace",
     "/debug/events", "/debug/vars", "/"))


class _ResultRouter:
    """Drains the OutputQueue into per-uri mailboxes. Only uris
    registered as pending get a mailbox; results for abandoned uris
    (request already timed out) are dropped, so timeouts don't leak."""

    def __init__(self, output_queue):
        self._q = output_queue
        self._pending: set = set()
        self._results: Dict[str, Dict[str, np.ndarray]] = {}
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self, join_timeout: float = 5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(join_timeout)
            self._thread = None

    def _loop(self):
        while not self._stop.is_set():
            item = self._q.dequeue(timeout=0.05)
            if item is None:
                continue
            uri, tensors = item
            with self._cv:
                if uri in self._pending:
                    self._results[uri] = tensors
                    self._cv.notify_all()
                else:
                    _M_HTTP_DROPPED.inc()
                    logger.warning("dropping result for abandoned "
                                   "request %s", uri)

    def register(self, uri: str) -> None:
        with self._cv:
            self._pending.add(uri)

    def unregister(self, uri: str) -> None:
        """Abandon a registered uri (request failed before/without its
        wait): drop the mailbox so late results can't accumulate."""
        with self._cv:
            self._pending.discard(uri)
            self._results.pop(uri, None)

    def wait(self, uri: str, timeout: float
             ) -> Optional[Dict[str, np.ndarray]]:
        deadline = time.monotonic() + timeout
        with self._cv:
            try:
                while uri not in self._results:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._cv.wait(remaining)
                return self._results.pop(uri)
            finally:
                self._pending.discard(uri)


def _to_jsonable(tensors: Dict[str, np.ndarray]) -> Any:
    out = {}
    for k, v in tensors.items():
        a = np.asarray(v)
        if a.dtype.kind == "f" and not np.all(np.isfinite(a)):
            # json.dumps would emit bare NaN/Infinity tokens (invalid
            # JSON); strict clients can't parse that. Map to null.
            a = np.where(np.isfinite(a), a.astype(object), None)
        out[k] = a.item() if a.ndim == 0 else a.tolist()
    return out


class HttpFrontend:
    """Serve /predict + /metrics on ``host:port``.

    Args:
      input_queue / output_queue: the serving queues; the frontend OWNS
        the output queue (its router consumes every result).
      worker: optional ServingWorker whose metrics join /metrics.
      request_timeout: /predict deadline in seconds (ref:
        FrontEndApp timeout settings).
    """

    def __init__(self, input_queue, output_queue, host: str = "127.0.0.1",
                 port: int = 0, worker=None,
                 request_timeout: float = 10.0,
                 timer: Optional[Timer] = None,
                 certfile: Optional[str] = None,
                 keyfile: Optional[str] = None):
        self._in = input_queue
        self.router = _ResultRouter(output_queue)
        self.worker = worker
        self.request_timeout = request_timeout
        self.retry_after_s = float(get_config().get(
            "zoo.serving.shed.retry_after_s", 1.0))
        self.timer = timer or Timer(mirror=_M_HTTP_STAGE)
        self._tls = certfile is not None
        self._started_at = time.time()
        # drain state (ISSUE-9): a draining deployment refuses NEW
        # predicts (503 + Retry-After) and fails its health check so
        # the fleet router routes around it, while requests already
        # in flight keep their mailboxes until answered
        self._draining = False
        frontend = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to our logger
                logger.debug("http: " + fmt, *args)

            def _reply(self, code: int, payload: Any,
                       content_type: str = "application/json",
                       headers: Optional[Dict[str, str]] = None):
                # count BEFORE writing: the increment must be visible
                # by the time the client has read the response, and a
                # mid-write disconnect must still count the request
                route = self.path.split("?")[0]
                if route not in _KNOWN_ROUTES:
                    route = "other"
                _M_HTTP_REQS.labels(route=route, code=str(code)).inc()
                body = (payload if isinstance(payload, bytes)
                        else json.dumps(payload).encode())
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                # dispatch ignores the query string (a scrape config's
                # params or a cache-buster must not 404 a known route)
                route = self.path.split("?")[0]
                if route == "/metrics":
                    # Prometheus text exposition of the process-wide
                    # registry (scrape target; format 0.0.4)
                    self._reply(
                        200, get_registry().prometheus_text().encode(),
                        content_type="text/plain; version=0.0.4; "
                                     "charset=utf-8")
                elif route == "/metrics.json":
                    self._reply(200, frontend.metrics())
                elif route == "/healthz":
                    code, payload = frontend.health()
                    self._reply(code, payload)
                elif route == "/trace":
                    self._reply(200, tracing.get_tracer().chrome_trace())
                elif route == "/debug/events":
                    self._reply(200, frontend.debug_events(
                        self.path.partition("?")[2]))
                elif route == "/debug/vars":
                    self._reply(200, frontend.debug_vars())
                elif route == "/":
                    # welcome route (ref: FrontEndApp.scala:40)
                    self._reply(200, {"message": "welcome to analytics "
                                                 "zoo tpu serving"})
                else:
                    self._reply(404, {"error": "not found",
                                      "path": self.path})

            def do_POST(self):
                if self.path.split("?")[0] != "/predict":
                    self._reply(404, {"error": "not found",
                                      "path": self.path})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                with frontend.timer.timing("predict_request"):
                    code, payload = frontend.handle_predict(req)
                headers = None
                if code == 503:
                    # load-shed / backpressure contract: every refused
                    # /predict carries Retry-After so well-behaved
                    # clients back off instead of hammering the queue
                    headers = {"Retry-After": str(max(1, int(
                        frontend.retry_after_s)))}
                self._reply(code, payload, headers=headers)

        if self._tls:
            # HTTPS (ref: FrontEndApp.scala:40-130 supports --https-*
            # with cert+key). The handshake must run in the per-request
            # worker thread, NOT the accept loop: wrapping the listening
            # socket would let one stalled client (open connection, no
            # ClientHello) freeze accept() and starve every other
            # client. get_request only wraps (deferred handshake);
            # finish_request handshakes under the connection timeout.
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=certfile, keyfile=keyfile)

            class TLSServer(ThreadingHTTPServer):
                def get_request(self):
                    conn, addr = self.socket.accept()
                    conn.settimeout(30.0)
                    conn = ctx.wrap_socket(
                        conn, server_side=True,
                        do_handshake_on_connect=False)
                    return conn, addr

                def finish_request(self, request, client_address):
                    try:
                        request.do_handshake()
                    except (ssl.SSLError, OSError) as e:
                        logger.debug("tls handshake failed from %s: %s",
                                     client_address, e)
                        return
                    super().finish_request(request, client_address)

            self._server = TLSServer((host, port), Handler)
        else:
            self._server = ThreadingHTTPServer((host, port), Handler)
        self._server_thread: Optional[threading.Thread] = None

    # --------------------------------------------------------- requests --
    def handle_predict(self, req: Any):
        """Predict with optional end-to-end tracing: when
        ``zoo.obs.trace.enabled``, the whole request runs under a fresh
        trace id (enqueued blobs carry it to the worker stages), an
        ``http_request`` span is recorded, and the response echoes the
        id for client-side correlation."""
        with tracing.maybe_trace("http_request") as trace_id:
            code, payload = self._handle_predict(req)
            if trace_id is not None and isinstance(payload, dict):
                payload = dict(payload)
                payload["trace_id"] = trace_id
            return code, payload

    def _handle_predict(self, req: Any):
        if self._draining:
            # structured refusal, same vocabulary as the wire errors:
            # the caller (fleet router, or a well-behaved client) sees
            # 503 + Retry-After and goes elsewhere
            return 503, {"error": DRAINING_PREFIX,
                         "detail": f"{DRAINING_PREFIX}: deployment "
                                   "is draining for restart",
                         "retry_after_s": self.retry_after_s}
        if not isinstance(req, dict):
            return 400, {"error": "body must be a JSON object"}
        if "instances" in req:
            instances = req["instances"]
            if not isinstance(instances, list):
                return 400, {"error": "'instances' must be a list"}
            single = False
        elif "inputs" in req:
            instances, single = [req["inputs"]], True
        else:
            return 400, {"error": "body must carry 'inputs' or "
                                  "'instances'"}
        # enqueue everything first so the worker's micro-batcher can
        # stack the whole request into device batches, then await; one
        # deadline covers the whole request
        deadline = time.monotonic() + self.request_timeout
        uris: list = []
        try:
            code, payload = self._enqueue_many(instances, uris)
            if code != 200:
                return code, payload
            preds = []
            for i, uri in enumerate(uris):
                code, payload = self._await(uri, deadline)
                uris[i] = None  # awaited: wait() owns the cleanup now
                if code != 200:
                    return code, payload
                preds.append(payload)
            return 200, {"predictions": preds[0] if single else preds}
        finally:
            for uri in uris:  # abandon whatever was never awaited
                if uri is not None:
                    self.router.unregister(uri)

    def _enqueue_many(self, instances, uris: list):
        for inputs in instances:
            if not isinstance(inputs, dict) or not inputs:
                return 400, {"error": "inputs must be a non-empty object"}
            try:
                tensors = {k: self._as_tensor(v)
                           for k, v in inputs.items()}
            except (ValueError, TypeError) as e:
                return 400, {"error": f"bad tensor: {e}"}
            for k, a in tensors.items():
                if a.dtype.kind not in "biufc":
                    return 400, {"error": f"tensor {k!r} is ragged or "
                                          "non-numeric"}
            uri = uuid.uuid4().hex
            self.router.register(uri)
            uris.append(uri)
            if not self._in.enqueue(uri, **tensors):
                # bounded-queue backpressure or admission-control
                # shedding -> 503 (+ Retry-After header added by the
                # handler); the reference surfaces Redis OOM as an
                # error (FrontEndApp/client.py), we tell the client
                # when to come back instead
                return 503, {"error": "overloaded: input queue "
                                      "refused the request",
                             "retry_after_s": self.retry_after_s}
        return 200, None

    @staticmethod
    def _as_tensor(value) -> np.ndarray:
        """JSON value -> tensor. ``{"b64": "..."}`` carries base64 bytes
        (TF-serving convention; the reference's frontend ships base64
        images the same way, FrontEndApp.scala + PreProcessing
        decodeImage) -- delivered as a uint8 byte tensor the worker's
        image sniffer decodes."""
        if isinstance(value, dict) and set(value) == {"b64"}:
            import base64

            raw = base64.b64decode(value["b64"], validate=True)
            return np.frombuffer(raw, np.uint8)
        return np.asarray(value)

    def _await(self, uri: str, deadline: float):
        result = self.router.wait(
            uri, max(0.0, deadline - time.monotonic()))
        if result is None:
            return 504, {"error": "prediction timed out"}
        if ERROR_KEY in result:
            msg = str(result[ERROR_KEY])
            status = error_status(msg)
            if status is not None:
                # structured worker rejection (protocol.ERROR_PREFIXES):
                # deadline_exceeded -> 504 (the client's budget ran
                # out, not a server fault), circuit_open -> 503 (the
                # handler adds Retry-After to every 503 so clients
                # back off while the breaker cools down)
                return status, {"error": msg.split(":", 1)[0],
                                "detail": msg}
            return 500, {"error": msg}
        return 200, _to_jsonable(result)

    # -------------------------------------------------------- lifecycle --
    @property
    def address(self):
        host, port = self._server.server_address[:2]
        scheme = "https" if self._tls else "http"
        return f"{scheme}://{host}:{port}"

    def start(self) -> "HttpFrontend":
        self.router.start()
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._server_thread.start()
        logger.info("serving frontend at %s", self.address)
        emit_event("frontend_start", "serving", address=self.address)
        return self

    def stop(self) -> None:
        emit_event("frontend_stop", "serving")
        self._server.shutdown()
        if self._server_thread is not None:
            self._server_thread.join(5.0)
            self._server_thread = None
        self.router.stop()
        self._server.server_close()

    def metrics(self) -> Dict[str, Any]:
        """The JSON snapshot API (``GET /metrics.json``): historical
        frontend/worker summaries plus the full process registry."""
        out: Dict[str, Any] = {"frontend": self.timer.summary()}
        try:
            out["input_queue_depth"] = len(self._in)
        except TypeError:
            pass
        if self.worker is not None:
            out["worker"] = self.worker.metrics()
        out["registry"] = get_registry().snapshot()
        return out

    def debug_events(self, query: str = "") -> Dict[str, Any]:
        """``GET /debug/events``: the structured event-log tail.
        Query params: ``n`` (default 200), ``type``, ``subsystem`` --
        filters apply before truncation, so ``?n=5&type=compile``
        means the last 5 compiles."""
        qs = parse_qs(query)

        def one(key):
            vals = qs.get(key)
            return vals[-1] if vals else None

        try:
            n = int(one("n") or 200)
        except ValueError:
            n = 200
        log = get_event_log()
        events = log.tail(n, type=one("type"),
                          subsystem=one("subsystem"))
        # scalar-coerce the fields (numpy values, exceptions): an
        # arbitrary emitter object must not 500 a debug endpoint
        return {"events": [to_jsonable(e) for e in events],
                "ring_len": len(log)}

    def debug_vars(self) -> Dict[str, Any]:
        """``GET /debug/vars``: resolved config + build/process info
        (the expvar convention) -- what you diff first when two
        deployments behave differently."""
        out: Dict[str, Any] = {
            "config": {k: v for k, v in sorted(
                get_config().as_dict().items())},
            "build": {
                "python": sys.version.split()[0],
                "platform": sys.platform,
            },
            "process": {
                "pid": os.getpid(),
                "argv": list(sys.argv),
                "uptime_s": round(time.time() - self._started_at, 3),
                "threads": len(threading.enumerate()),
            },
            "inflight_requests": get_inflight().snapshot(),
        }
        # protocol-visible shard info: what you diff when one
        # deployment serves sharded and another doesn't (mode "off" is
        # the explicit single-chip answer, not an absent block)
        shard_plan = getattr(getattr(self.worker, "model", None),
                             "shard_plan", None)
        out["serving_shard"] = (shard_plan.describe()
                                if shard_plan is not None
                                else {"mode": "off"})
        try:
            import jax

            out["build"]["jax"] = jax.__version__
            out["build"]["backend"] = jax.default_backend()
        except Exception as e:
            # jax-free frontend processes stay served; the debug page
            # just omits the backend block (but says why in the log)
            logger.debug("debug endpoint: jax info unavailable: %s", e)
        return out

    def set_draining(self) -> None:
        """Flip the deployment into drain mode (one-way; the process
        is on its way out): health goes 503 ``draining`` so the fleet
        router stops routing here, /predict refuses new work."""
        self._draining = True

    def health(self):
        """Liveness for ``GET /healthz``: 503 once a started worker's
        serving thread has died (a stopped or inline-run worker is not
        a failure -- there is no thread to have died), or while the
        deployment is draining (in-flight work finishing; no new
        traffic wanted)."""
        worker = self.worker
        thread = getattr(worker, "_thread", None)
        alive = thread is None or thread.is_alive()
        status = (DRAINING_PREFIX if self._draining
                  else "ok" if alive else "worker_dead")
        payload = {
            "status": status,
            "uptime_s": round(time.time() - self._started_at, 3),
        }
        if worker is not None:
            payload["served"] = worker.served
            payload["pipelined"] = worker.pipelined
        return (200 if alive and not self._draining else 503), payload
