"""HTTP frontend: /predict + observability routes over the serving queues.

The analog of the akka-http frontend (ref: zoo/.../serving/http/
FrontEndApp.scala:40-130 -- a /predict route that XADDs the request into
Redis, awaits the result stream, and a /metrics route exposing timer
percentiles). Here: a stdlib ``ThreadingHTTPServer``; each /predict POST
enqueues into the InputQueue with a fresh uri, a router thread drains the
OutputQueue into per-uri mailboxes, and the handler blocks on its mailbox
with a deadline. Dependency-free wire format:

  POST /predict       {"inputs": {"x": [[...]]}}         -> {"predictions": ...}
  POST /predict       {"instances": [{"x": [...]}, ...]} -> {"predictions": [...]}
  GET  /metrics       Prometheus text exposition (process registry)
  GET  /metrics.json  JSON snapshot: registry + frontend/worker summaries
  GET  /healthz       liveness (200, or 503 when the worker thread died)
  GET  /trace         Chrome trace-event JSON of collected request spans
  GET  /debug/events  structured event-log tail (?n=&type=&subsystem=)
  GET  /debug/vars    resolved config + build/uptime/process info

Unknown paths get a 404 with a JSON error body. With
``zoo.obs.trace.enabled`` each /predict carries a fresh trace id through
the queue blobs, so its worker-side decode/dispatch/finalize spans join
the frontend's ``http_request`` span under one id (docs/observability.md).
"""

from __future__ import annotations

import json
import os
import queue as _pyqueue
import sys
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import parse_qs

import numpy as np

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.obs import tracing
from analytics_zoo_tpu.obs.events import emit as emit_event
from analytics_zoo_tpu.obs.events import get_event_log, to_jsonable
from analytics_zoo_tpu.obs.flight import get_inflight
from analytics_zoo_tpu.obs.metrics import get_registry
from analytics_zoo_tpu.serving.protocol import (
    DEADLINE_PREFIX, DRAINING_PREFIX, ERROR_KEY, PRIORITY_CLASSES,
    PRIORITY_KEY, SHED_PREFIX, STREAM_KEY, TENANT_KEY, error_status,
    priority_index)
from analytics_zoo_tpu.serving.timer import Timer

logger = get_logger(__name__)

_REG = get_registry()
_M_HTTP_STAGE = _REG.histogram(
    "zoo_http_stage_duration_seconds",
    "HTTP frontend stage latency (predict_request, ...)",
    labelnames=("stage",))
_M_HTTP_REQS = _REG.counter(
    "zoo_http_requests_total", "HTTP requests served, by route and "
    "status code", labelnames=("route", "code"))
_M_HTTP_DROPPED = _REG.counter(
    "zoo_http_dropped_results_total",
    "Results dropped for abandoned (timed-out) requests")

# label-cardinality guard: only known routes get their own label value;
# everything else (scanners probing arbitrary 404 paths) collapses to
# "other" so client-supplied URLs cannot grow the registry unboundedly
_KNOWN_ROUTES = frozenset(
    ("/predict", "/generate", "/metrics", "/metrics.json", "/healthz",
     "/trace", "/debug/events", "/debug/vars", "/"))


class _ResultRouter:
    """Drains the OutputQueue into per-uri mailboxes. Only uris
    registered as pending get a mailbox; results for abandoned uris
    (request already timed out) are dropped, so timeouts don't leak.

    Two mailbox kinds: one-shot results (predict -- one blob, then the
    waiter owns cleanup) and *stream* mailboxes (generate, ISSUE-10 --
    a Queue of chunks, recognized by ``__stream__`` riding the reply
    blob; a stream stays registered until its handler unregisters it,
    so a multi-chunk reply never races its own registration)."""

    def __init__(self, output_queue):
        self._q = output_queue
        self._pending: set = set()
        self._results: Dict[str, Dict[str, np.ndarray]] = {}
        self._streams: Dict[str, _pyqueue.Queue] = {}
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self, join_timeout: float = 5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(join_timeout)
            self._thread = None

    def _loop(self):
        while not self._stop.is_set():
            item = self._q.dequeue(timeout=0.05)
            if item is None:
                continue
            uri, tensors = item
            if STREAM_KEY in tensors:
                # generation chunk: route into the stream mailbox
                # (debug-level drop log -- an abandoned stream keeps
                # producing chunks until the worker finishes it, and a
                # warning per chunk would flood the log)
                with self._cv:
                    sq = self._streams.get(uri)
                if sq is not None:
                    sq.put(tensors)
                else:
                    _M_HTTP_DROPPED.inc()
                    logger.debug("dropping chunk for abandoned "
                                 "stream %s", uri)
                continue
            with self._cv:
                if uri in self._pending:
                    self._results[uri] = tensors
                    self._cv.notify_all()
                else:
                    _M_HTTP_DROPPED.inc()
                    logger.warning("dropping result for abandoned "
                                   "request %s", uri)

    def register(self, uri: str) -> None:
        with self._cv:
            self._pending.add(uri)

    def register_stream(self, uri: str) -> _pyqueue.Queue:
        """Open a stream mailbox; every chunk blob for ``uri`` lands
        in the returned Queue until :meth:`unregister_stream`."""
        sq: _pyqueue.Queue = _pyqueue.Queue()
        with self._cv:
            self._streams[uri] = sq
        return sq

    def unregister_stream(self, uri: str) -> None:
        with self._cv:
            self._streams.pop(uri, None)

    def unregister(self, uri: str) -> None:
        """Abandon a registered uri (request failed before/without its
        wait): drop the mailbox so late results can't accumulate."""
        with self._cv:
            self._pending.discard(uri)
            self._results.pop(uri, None)

    def wait(self, uri: str, timeout: float
             ) -> Optional[Dict[str, np.ndarray]]:
        deadline = time.monotonic() + timeout
        with self._cv:
            try:
                while uri not in self._results:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._cv.wait(remaining)
                return self._results.pop(uri)
            finally:
                self._pending.discard(uri)


def _to_jsonable(tensors: Dict[str, np.ndarray]) -> Any:
    out = {}
    for k, v in tensors.items():
        a = np.asarray(v)
        if a.dtype.kind == "f" and not np.all(np.isfinite(a)):
            # json.dumps would emit bare NaN/Infinity tokens (invalid
            # JSON); strict clients can't parse that. Map to null.
            a = np.where(np.isfinite(a), a.astype(object), None)
        out[k] = a.item() if a.ndim == 0 else a.tolist()
    return out


class HttpFrontend:
    """Serve /predict + /metrics on ``host:port``.

    Args:
      input_queue / output_queue: the serving queues; the frontend OWNS
        the output queue (its router consumes every result).
      worker: optional ServingWorker whose metrics join /metrics.
      request_timeout: /predict deadline in seconds (ref:
        FrontEndApp timeout settings).
    """

    def __init__(self, input_queue, output_queue,
                 host: Optional[str] = None,
                 port: int = 0, worker=None,
                 request_timeout: float = 10.0,
                 timer: Optional[Timer] = None,
                 certfile: Optional[str] = None,
                 keyfile: Optional[str] = None,
                 gen_queue=None, gen_worker=None):
        if host is None:
            # cross-host fleets bind 0.0.0.0 via
            # zoo.serving.fleet.bind_host (ISSUE-20); loopback stays
            # the default
            host = str(get_config().get(
                "zoo.serving.fleet.bind_host", "127.0.0.1"))
        self._in = input_queue
        self.router = _ResultRouter(output_queue)
        self.worker = worker
        # generation serving (ISSUE-10): the generate-request input
        # queue and worker; None = POST /generate answers 404. Chunks
        # arrive on the SAME output queue the router drains (routed by
        # the __stream__ key), so there is still exactly one drainer.
        self._gen_in = gen_queue
        self.gen_worker = gen_worker
        self.request_timeout = request_timeout
        self.retry_after_s = float(get_config().get(
            "zoo.serving.shed.retry_after_s", 1.0))
        self.timer = timer or Timer(mirror=_M_HTTP_STAGE)
        self._tls = certfile is not None
        self._started_at = time.time()
        # drain state (ISSUE-9): a draining deployment refuses NEW
        # predicts (503 + Retry-After) and fails its health check so
        # the fleet router routes around it, while requests already
        # in flight keep their mailboxes until answered
        self._draining = False
        frontend = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1: chunked transfer encoding for streamed
            # /generate responses (every non-streamed reply still
            # carries Content-Length, so keep-alive stays correct)
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route to our logger
                logger.debug("http: " + fmt, *args)

            def _reply(self, code: int, payload: Any,
                       content_type: str = "application/json",
                       headers: Optional[Dict[str, str]] = None):
                # count BEFORE writing: the increment must be visible
                # by the time the client has read the response, and a
                # mid-write disconnect must still count the request
                route = self.path.split("?")[0]
                if route not in _KNOWN_ROUTES:
                    route = "other"
                _M_HTTP_REQS.labels(route=route, code=str(code)).inc()
                body = (payload if isinstance(payload, bytes)
                        else json.dumps(payload).encode())
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                # dispatch ignores the query string (a scrape config's
                # params or a cache-buster must not 404 a known route)
                route = self.path.split("?")[0]
                if route == "/metrics":
                    # Prometheus text exposition of the process-wide
                    # registry (scrape target; format 0.0.4)
                    self._reply(
                        200, get_registry().prometheus_text().encode(),
                        content_type="text/plain; version=0.0.4; "
                                     "charset=utf-8")
                elif route == "/metrics.json":
                    self._reply(200, frontend.metrics())
                elif route == "/healthz":
                    code, payload = frontend.health()
                    self._reply(code, payload)
                elif route == "/trace":
                    self._reply(200, tracing.get_tracer().chrome_trace())
                elif route == "/debug/events":
                    self._reply(200, frontend.debug_events(
                        self.path.partition("?")[2]))
                elif route == "/debug/vars":
                    self._reply(200, frontend.debug_vars())
                elif route == "/":
                    # welcome route (ref: FrontEndApp.scala:40)
                    self._reply(200, {"message": "welcome to analytics "
                                                 "zoo tpu serving"})
                else:
                    self._reply(404, {"error": "not found",
                                      "path": self.path})

            def do_POST(self):
                route = self.path.split("?")[0]
                if route not in ("/predict", "/generate"):
                    self._reply(404, {"error": "not found",
                                      "path": self.path})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError) as e:
                    self._reply(400, {"error": f"bad request: {e}"})
                    return
                if route == "/generate":
                    frontend.handle_generate(self, req)
                    return
                with frontend.timer.timing("predict_request"):
                    code, payload = frontend.handle_predict(
                        req, priority=self.headers.get("X-Priority"))
                self._reply(code, payload,
                            headers=frontend._retry_headers(code))

            # ------------------------- chunked stream helpers -------
            def begin_stream(self) -> None:
                """Response head of a streamed /generate: chunked
                transfer, SSE content type. Counted here -- _reply
                never runs for a streamed response."""
                _M_HTTP_REQS.labels(route="/generate",
                                    code="200").inc()
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

            def write_event(self, obj: Any) -> bool:
                """One SSE event as one HTTP chunk; False = client
                went away (the caller stops relaying)."""
                data = b"data: " + json.dumps(obj).encode() + b"\n\n"
                try:
                    self.wfile.write(b"%X\r\n" % len(data) + data
                                     + b"\r\n")
                    self.wfile.flush()
                    return True
                except (ConnectionError, BrokenPipeError, OSError):
                    return False

            def end_stream(self) -> None:
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except (ConnectionError, BrokenPipeError, OSError) as e:
                    logger.debug("stream close failed: %s", e)
                self.close_connection = True

        if self._tls:
            # HTTPS (ref: FrontEndApp.scala:40-130 supports --https-*
            # with cert+key). The handshake must run in the per-request
            # worker thread, NOT the accept loop: wrapping the listening
            # socket would let one stalled client (open connection, no
            # ClientHello) freeze accept() and starve every other
            # client. get_request only wraps (deferred handshake);
            # finish_request handshakes under the connection timeout.
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=certfile, keyfile=keyfile)

            class TLSServer(ThreadingHTTPServer):
                def get_request(self):
                    conn, addr = self.socket.accept()
                    conn.settimeout(30.0)
                    conn = ctx.wrap_socket(
                        conn, server_side=True,
                        do_handshake_on_connect=False)
                    return conn, addr

                def finish_request(self, request, client_address):
                    try:
                        request.do_handshake()
                    except (ssl.SSLError, OSError) as e:
                        logger.debug("tls handshake failed from %s: %s",
                                     client_address, e)
                        return
                    super().finish_request(request, client_address)

            self._server = TLSServer((host, port), Handler)
        else:
            self._server = ThreadingHTTPServer((host, port), Handler)
        self._server_thread: Optional[threading.Thread] = None

    # --------------------------------------------------------- requests --
    def handle_predict(self, req: Any, priority=None):
        """Predict with optional end-to-end tracing: when
        ``zoo.obs.trace.enabled``, the whole request runs under a fresh
        trace id (enqueued blobs carry it to the worker stages), an
        ``http_request`` span is recorded, and the response echoes the
        id for client-side correlation. ``priority`` is the request's
        admission class (the ``X-Priority`` header; a per-input
        ``__priority__`` JSON key overrides it)."""
        with tracing.maybe_trace("http_request") as trace_id:
            code, payload = self._handle_predict(req, priority)
            if trace_id is not None and isinstance(payload, dict):
                payload = dict(payload)
                payload["trace_id"] = trace_id
            return code, payload

    def _handle_predict(self, req: Any, priority=None):
        if self._draining:
            # structured refusal, same vocabulary as the wire errors:
            # the caller (fleet router, or a well-behaved client) sees
            # 503 + Retry-After and goes elsewhere
            return 503, {"error": DRAINING_PREFIX,
                         "detail": f"{DRAINING_PREFIX}: deployment "
                                   "is draining for restart",
                         "retry_after_s": self.retry_after_s}
        if priority is not None and priority_index(priority) is None:
            return 400, {"error": "unknown priority class "
                                  f"{priority!r}; expected one of "
                                  + ", ".join(PRIORITY_CLASSES)}
        if not isinstance(req, dict):
            return 400, {"error": "body must be a JSON object"}
        if "instances" in req:
            instances = req["instances"]
            if not isinstance(instances, list):
                return 400, {"error": "'instances' must be a list"}
            single = False
        elif "inputs" in req:
            instances, single = [req["inputs"]], True
        else:
            return 400, {"error": "body must carry 'inputs' or "
                                  "'instances'"}
        # enqueue everything first so the worker's micro-batcher can
        # stack the whole request into device batches, then await; one
        # deadline covers the whole request
        deadline = time.monotonic() + self.request_timeout
        uris: list = []
        try:
            code, payload = self._enqueue_many(instances, uris,
                                               priority)
            if code != 200:
                return code, payload
            preds = []
            for i, uri in enumerate(uris):
                code, payload = self._await(uri, deadline)
                uris[i] = None  # awaited: wait() owns the cleanup now
                if code != 200:
                    return code, payload
                preds.append(payload)
            return 200, {"predictions": preds[0] if single else preds}
        finally:
            for uri in uris:  # abandon whatever was never awaited
                if uri is not None:
                    self.router.unregister(uri)

    def _enqueue_many(self, instances, uris: list, priority=None):
        for inputs in instances:
            if not isinstance(inputs, dict) or not inputs:
                return 400, {"error": "inputs must be a non-empty object"}
            # __tenant__ / __priority__ ride the JSON inputs next to
            # the tensors and are lifted onto the wire blob's
            # out-of-band keys, never into the tensor dict (ISSUE-13
            # parameter lanes, ISSUE-15 admission classes)
            inputs = dict(inputs)
            tenant = inputs.pop(TENANT_KEY, None)
            if tenant is not None and not isinstance(tenant, int):
                return 400, {"error": f"{TENANT_KEY} must be an "
                                      "integer lane id"}
            pri = inputs.pop(PRIORITY_KEY, priority)
            if pri is not None and priority_index(pri) is None:
                return 400, {"error": f"{PRIORITY_KEY} must name a "
                                      "priority class: "
                                      + ", ".join(PRIORITY_CLASSES)}
            if not inputs:
                return 400, {"error": "inputs must carry at least one "
                                      "tensor besides " + TENANT_KEY}
            try:
                tensors = {k: self._as_tensor(v)
                           for k, v in inputs.items()}
            except (ValueError, TypeError) as e:
                return 400, {"error": f"bad tensor: {e}"}
            for k, a in tensors.items():
                if a.dtype.kind not in "biufc":
                    return 400, {"error": f"tensor {k!r} is ragged or "
                                          "non-numeric"}
            uri = uuid.uuid4().hex
            self.router.register(uri)
            uris.append(uri)
            if not self._in.enqueue(uri, tenant=tenant, priority=pri,
                                    **tensors):
                # bounded-queue backpressure or admission-control
                # shedding -> 503 (+ Retry-After header added by the
                # handler); the reference surfaces Redis OOM as an
                # error (FrontEndApp/client.py), we tell the client
                # when to come back instead -- with a backoff that
                # scales with current shed pressure
                return 503, {"error": SHED_PREFIX,
                             "detail": f"{SHED_PREFIX}: input queue "
                                       "refused the request",
                             "retry_after_s": self._retry_after_s()}
        return 200, None

    @staticmethod
    def _as_tensor(value) -> np.ndarray:
        """JSON value -> tensor. ``{"b64": "..."}`` carries base64 bytes
        (TF-serving convention; the reference's frontend ships base64
        images the same way, FrontEndApp.scala + PreProcessing
        decodeImage) -- delivered as a uint8 byte tensor the worker's
        image sniffer decodes."""
        if isinstance(value, dict) and set(value) == {"b64"}:
            import base64

            raw = base64.b64decode(value["b64"], validate=True)
            return np.frombuffer(raw, np.uint8)
        return np.asarray(value)

    def _await(self, uri: str, deadline: float):
        result = self.router.wait(
            uri, max(0.0, deadline - time.monotonic()))
        if result is None:
            return 504, {"error": "prediction timed out"}
        if ERROR_KEY in result:
            msg = str(result[ERROR_KEY])
            status = error_status(msg)
            if status is not None:
                # structured worker rejection (protocol.ERROR_PREFIXES):
                # deadline_exceeded -> 504 (the client's budget ran
                # out, not a server fault), circuit_open -> 503 (the
                # handler adds Retry-After to every 503 so clients
                # back off while the breaker cools down)
                return status, {"error": msg.split(":", 1)[0],
                                "detail": msg}
            return 500, {"error": msg}
        return 200, _to_jsonable(result)

    def _retry_after_s(self, queue=None) -> float:
        """The backoff to advertise on a shed 503: the refusing
        queue's adaptive value (EWMA shed pressure, ISSUE-15) when it
        exposes one, never below the configured floor."""
        q = self._in if queue is None else queue
        fn = getattr(q, "retry_after_s", None)
        if callable(fn):
            try:
                return max(self.retry_after_s, float(fn()))
            except (TypeError, ValueError):
                pass
        return self.retry_after_s

    def _retry_headers(self, code: int) -> Optional[Dict[str, str]]:
        """Every 503 carries Retry-After (the load-shed / drain /
        overflow backoff contract shared by /predict and /generate).
        The advertised seconds track shed pressure: the configured
        retry_after_s is the floor, consecutive sheds raise it."""
        if code != 503:
            return None
        return {"Retry-After": str(max(1, int(self._retry_after_s())))}

    # ------------------------------------------------------ generation --
    def handle_generate(self, handler, req: Any) -> None:
        """``POST /generate`` (ISSUE-10): enqueue a generate request
        and relay its chunk stream. ``stream: true`` (default) answers
        chunked SSE -- one ``data: {...}`` event per token chunk, a
        terminal event carrying ``finish_reason`` (or a structured
        ``error``); ``stream: false`` collects the whole stream into
        one JSON reply. The per-request deadline is honored across the
        stream: expiry mid-stream produces a structured
        ``deadline_exceeded`` terminal event, never a silent close."""
        with tracing.maybe_trace("http_generate") as trace_id:
            hdrs = getattr(handler, "headers", None)
            code, err, uri, stream_q, streaming = \
                self._generate_setup(
                    req, priority=(hdrs.get("X-Priority")
                                   if hdrs is not None else None))
            if uri is None:
                handler._reply(code, err,
                               headers=self._retry_headers(code))
                return
            try:
                if streaming:
                    self._stream_generate(handler, uri, stream_q,
                                          trace_id)
                else:
                    code, payload = self._collect_generate(
                        uri, stream_q, trace_id)
                    handler._reply(code, payload,
                                   headers=self._retry_headers(code))
            finally:
                self.router.unregister_stream(uri)

    def _generate_setup(self, req: Any, priority=None):
        """Validate + enqueue; returns (code, error_payload, uri,
        stream_queue, streaming) with uri None on refusal. A
        ``priority`` body field overrides the X-Priority header."""
        if self._gen_in is None:
            return 404, {"error": "generation serving is not enabled "
                                  "on this deployment"}, None, None, \
                False
        if self._draining:
            return 503, {"error": DRAINING_PREFIX,
                         "detail": f"{DRAINING_PREFIX}: deployment "
                                   "is draining for restart",
                         "retry_after_s": self.retry_after_s}, \
                None, None, False
        if not isinstance(req, dict):
            return 400, {"error": "body must be a JSON object"}, \
                None, None, False
        prompt = req.get("prompt", req.get("tokens"))
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) and not isinstance(
                    t, bool) for t in prompt)):
            return 400, {"error": "'prompt' must be a non-empty list "
                                  "of token ids"}, None, None, False
        max_tokens = req.get("max_tokens")
        eos = req.get("eos")
        for name, v in (("max_tokens", max_tokens), ("eos", eos)):
            if v is not None and (isinstance(v, bool)
                                  or not isinstance(v, int)):
                return 400, {"error": f"'{name}' must be an int"}, \
                    None, None, False
        if max_tokens is not None and max_tokens < 1:
            # admission always yields the prefill's first token, so a
            # <1 budget cannot be honored -- refuse up front instead
            # of billing a prefill for a token nobody asked for
            return 400, {"error": "'max_tokens' must be >= 1"}, \
                None, None, False
        pri = req.get("priority", priority)
        if pri is not None and priority_index(pri) is None:
            return 400, {"error": "'priority' must name a class: "
                                  + ", ".join(PRIORITY_CLASSES)}, \
                None, None, False
        streaming = bool(req.get("stream", True))
        uri = uuid.uuid4().hex
        stream_q = self.router.register_stream(uri)
        if not self._gen_in.enqueue_generation(
                uri, np.asarray(prompt, np.int32),
                max_tokens=max_tokens, eos=eos, priority=pri):
            self.router.unregister_stream(uri)
            return 503, {"error": SHED_PREFIX,
                         "detail": f"{SHED_PREFIX}: generation queue "
                                   "refused the request",
                         "retry_after_s":
                             self._retry_after_s(self._gen_in)}, \
                None, None, False
        return 200, None, uri, stream_q, streaming

    @staticmethod
    def _parse_chunk(tensors: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """Wire chunk -> event dict: {seq, token?, finish_reason?,
        n_tokens?} or {seq, error, detail}."""
        ev: Dict[str, Any] = {"seq": int(np.asarray(
            tensors[STREAM_KEY]).reshape(()))}
        if ERROR_KEY in tensors:
            msg = str(np.asarray(tensors[ERROR_KEY]).reshape(()))
            ev["error"] = msg.split(":", 1)[0]
            ev["detail"] = msg
            return ev
        if "token" in tensors:
            ev["token"] = [int(t) for t in
                           np.asarray(tensors["token"]).reshape(-1)]
        if "finish_reason" in tensors:
            ev["finish_reason"] = str(np.asarray(
                tensors["finish_reason"]).reshape(()))
            ev["n_tokens"] = int(np.asarray(
                tensors.get("n_tokens", 0)).reshape(()))
        return ev

    def _next_chunk(self, stream_q, deadline: float
                    ) -> Optional[Dict[str, Any]]:
        """Next parsed chunk event, or None when the request deadline
        expired first."""
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                tensors = stream_q.get(timeout=min(remaining, 0.25))
            except _pyqueue.Empty:
                continue
            return self._parse_chunk(tensors)

    def _stream_generate(self, handler, uri: str, stream_q,
                         trace_id: Optional[str]) -> None:
        handler.begin_stream()
        meta: Dict[str, Any] = {"uri": uri}
        if trace_id is not None:
            meta["trace_id"] = trace_id
        alive = handler.write_event(meta)
        last_seq = -1
        while alive:
            # request_timeout here is an inter-chunk STALL detector
            # (reset per chunk): the TOTAL stream budget is the wire
            # deadline (zoo.serving.deadline_ms), which the worker
            # enforces with its own structured terminal chunk -- a
            # healthy long stream must not be killed mid-flow by the
            # frontend's (predict-sized) total timeout
            ev = self._next_chunk(
                stream_q, time.monotonic() + self.request_timeout)
            if ev is None:
                # chunks stopped arriving -> STRUCTURED terminal
                # chunk, not a silent close (the /generate contract)
                handler.write_event(
                    {"error": DEADLINE_PREFIX,
                     "detail": f"{DEADLINE_PREFIX}: stream stalled "
                               "(no chunk inside the request "
                               "timeout)"})
                break
            if "error" in ev:
                handler.write_event(ev)
                break
            if ev["seq"] <= last_seq:
                continue  # supervisor-restart replay: already relayed
            last_seq = ev["seq"]
            alive = handler.write_event(ev)
            if "finish_reason" in ev:
                break
        handler.end_stream()

    def _collect_generate(self, uri: str, stream_q,
                          trace_id: Optional[str]):
        """``stream: false``: assemble the chunk stream into one JSON
        reply (error prefixes map to HTTP statuses exactly like
        /predict error replies). Same inter-chunk STALL semantics as
        the streaming path -- a healthy long stream must not 504 just
        because its total exceeds the predict-sized request_timeout
        (the total budget is the wire deadline's job)."""
        toks: list = []
        last_seq = -1
        while True:
            ev = self._next_chunk(
                stream_q, time.monotonic() + self.request_timeout)
            if ev is None:
                return 504, {"error": "generation stalled (no chunk "
                                      "inside the request timeout)"}
            if "error" in ev:
                status = error_status(ev["detail"])
                return ((status, {"error": ev["error"],
                                  "detail": ev["detail"],
                                  "retry_after_s": self.retry_after_s})
                        if status is not None
                        else (500, {"error": ev["detail"]}))
            if ev["seq"] <= last_seq:
                continue
            last_seq = ev["seq"]
            toks.extend(ev.get("token", ()))
            if "finish_reason" in ev:
                out = {"tokens": toks,
                       "finish_reason": ev["finish_reason"],
                       "n_tokens": ev["n_tokens"]}
                if trace_id is not None:
                    out["trace_id"] = trace_id
                return 200, out

    # -------------------------------------------------------- lifecycle --
    @property
    def address(self):
        host, port = self._server.server_address[:2]
        scheme = "https" if self._tls else "http"
        return f"{scheme}://{host}:{port}"

    def start(self) -> "HttpFrontend":
        self.router.start()
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._server_thread.start()
        logger.info("serving frontend at %s", self.address)
        emit_event("frontend_start", "serving", address=self.address)
        return self

    def stop(self) -> None:
        emit_event("frontend_stop", "serving")
        self._server.shutdown()
        if self._server_thread is not None:
            self._server_thread.join(5.0)
            self._server_thread = None
        self.router.stop()
        self._server.server_close()

    def metrics(self) -> Dict[str, Any]:
        """The JSON snapshot API (``GET /metrics.json``): historical
        frontend/worker summaries plus the full process registry."""
        out: Dict[str, Any] = {"frontend": self.timer.summary()}
        try:
            out["input_queue_depth"] = len(self._in)
        except TypeError:
            pass
        if self.worker is not None:
            out["worker"] = self.worker.metrics()
        if self.gen_worker is not None:
            out["generation"] = self.gen_worker.metrics()
        out["registry"] = get_registry().snapshot()
        return out

    def debug_events(self, query: str = "") -> Dict[str, Any]:
        """``GET /debug/events``: the structured event-log tail.
        Query params: ``n`` (default 200), ``type``, ``subsystem`` --
        filters apply before truncation, so ``?n=5&type=compile``
        means the last 5 compiles."""
        qs = parse_qs(query)

        def one(key):
            vals = qs.get(key)
            return vals[-1] if vals else None

        try:
            n = int(one("n") or 200)
        except ValueError:
            n = 200
        log = get_event_log()
        events = log.tail(n, type=one("type"),
                          subsystem=one("subsystem"))
        # scalar-coerce the fields (numpy values, exceptions): an
        # arbitrary emitter object must not 500 a debug endpoint
        return {"events": [to_jsonable(e) for e in events],
                "ring_len": len(log)}

    def debug_vars(self) -> Dict[str, Any]:
        """``GET /debug/vars``: resolved config + build/process info
        (the expvar convention) -- what you diff first when two
        deployments behave differently."""
        out: Dict[str, Any] = {
            "config": {k: v for k, v in sorted(
                get_config().as_dict().items())},
            "build": {
                "python": sys.version.split()[0],
                "platform": sys.platform,
            },
            "process": {
                "pid": os.getpid(),
                "argv": list(sys.argv),
                "uptime_s": round(time.time() - self._started_at, 3),
                "threads": len(threading.enumerate()),
            },
            "inflight_requests": get_inflight().snapshot(),
        }
        # protocol-visible shard info: what you diff when one
        # deployment serves sharded and another doesn't (mode "off" is
        # the explicit single-chip answer, not an absent block)
        shard_plan = getattr(getattr(self.worker, "model", None),
                             "shard_plan", None)
        out["serving_shard"] = (shard_plan.describe()
                                if shard_plan is not None
                                else {"mode": "off"})
        try:
            import jax

            out["build"]["jax"] = jax.__version__
            out["build"]["backend"] = jax.default_backend()
        except Exception as e:
            # jax-free frontend processes stay served; the debug page
            # just omits the backend block (but says why in the log)
            logger.debug("debug endpoint: jax info unavailable: %s", e)
        return out

    def set_draining(self) -> None:
        """Flip the deployment into drain mode (one-way; the process
        is on its way out): health goes 503 ``draining`` so the fleet
        router stops routing here, /predict refuses new work."""
        self._draining = True

    def health(self):
        """Liveness for ``GET /healthz``: 503 once a started worker's
        serving thread has died (a stopped or inline-run worker is not
        a failure -- there is no thread to have died), or while the
        deployment is draining (in-flight work finishing; no new
        traffic wanted). A deployment hosting both data planes is
        healthy only when BOTH workers' threads live."""
        worker = self.worker
        thread = getattr(worker, "_thread", None)
        alive = thread is None or thread.is_alive()
        gen = self.gen_worker
        gen_thread = getattr(gen, "_thread", None)
        alive = alive and (gen_thread is None or gen_thread.is_alive())
        status = (DRAINING_PREFIX if self._draining
                  else "ok" if alive else "worker_dead")
        payload = {
            "status": status,
            "uptime_s": round(time.time() - self._started_at, 3),
        }
        if worker is not None:
            payload["served"] = worker.served
            payload["pipelined"] = worker.pipelined
        if gen is not None:
            payload["generation_served"] = gen.served
        return (200 if alive and not self._draining else 503), payload
