"""Priority-ordered admission control: brownout shedding (ISSUE-15).

The single ``zoo.serving.shed.queue_depth`` threshold of ISSUE-5 shed
every class of traffic at the same depth -- under overload a
background bulk export and an interactive request died together. This
module replaces it with a brownout LADDER over the protocol's
``PRIORITY_CLASSES``:

- ``interactive`` admits while depth < ``queue_depth`` (the historical
  threshold, so priority-less deployments behave byte-identically);
- ``batch`` admits while depth < ``queue_depth * batch_fraction``;
- ``background`` admits while depth < ``queue_depth *
  background_fraction``.

The ladder is clamped monotone non-increasing, so the no-inversion
contract holds *by construction*: at any queue depth, a class is
admitted whenever any lower class would be -- there is no interleaving
of decisions that refuses ``interactive`` while admitting ``batch``
(property-tested over randomized sequences in
``tests/test_overload.py``).

Generation admissions carry a COST: ``ceil(max_tokens /
zoo.serving.shed.gen_cost_tokens)`` queue slots, so a request asking
for a 4096-token stream is charged like the long occupancy it is and
cannot starve interactive traffic by slipping under the depth bar one
blob at a time.

Retry-After adapts to pressure: an EWMA over admission decisions
(1 = shed, 0 = admitted; ``zoo.serving.shed.ewma_alpha`` smoothing)
interpolates between ``zoo.serving.shed.retry_after_s`` (the floor)
and ``zoo.serving.shed.retry_after_max_s``. Rising shed pressure
monotonically raises the advertised backoff; recovery decays it back
to the floor. Decision-indexed (not wall-clock) smoothing keeps the
controller deterministic and directly testable.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Optional

from analytics_zoo_tpu.obs.events import emit as emit_event
from analytics_zoo_tpu.obs.metrics import get_registry as _get_registry
from analytics_zoo_tpu.serving.protocol import (
    PRIORITY_CLASSES, priority_name)

# THE registration site for the shed family (moved here from queues.py
# when it grew the per-class label): one counter, labeled by admission
# class, so dashboards separate "background browned out as designed"
# from "interactive is being refused" without a new metric name.
_REG = _get_registry()
_M_SHED = _REG.counter(
    "zoo_serving_shed_total",
    "Requests refused by priority-ordered admission control "
    "(zoo.serving.shed.*; class = admission class refused)",
    labelnames=("class",))


class AdmissionController:
    """Shed-or-admit decisions for one input queue.

    Thread-safe; every mutable piece sits behind one lock (enqueue
    paths are already serialized per producer, but several producer
    threads may share one InputQueue).
    """

    def __init__(self, queue_depth: int,
                 batch_fraction: Optional[float] = None,
                 background_fraction: Optional[float] = None,
                 retry_after_s: Optional[float] = None,
                 retry_after_max_s: Optional[float] = None,
                 ewma_alpha: Optional[float] = None):
        from analytics_zoo_tpu.common.config import get_config

        cfg = get_config()
        if batch_fraction is None:
            batch_fraction = float(
                cfg.get("zoo.serving.shed.batch_fraction", 0.6))
        if background_fraction is None:
            background_fraction = float(
                cfg.get("zoo.serving.shed.background_fraction", 0.3))
        if retry_after_s is None:
            retry_after_s = float(
                cfg.get("zoo.serving.shed.retry_after_s", 1.0))
        if retry_after_max_s is None:
            retry_after_max_s = float(
                cfg.get("zoo.serving.shed.retry_after_max_s", 30.0))
        if ewma_alpha is None:
            ewma_alpha = float(
                cfg.get("zoo.serving.shed.ewma_alpha", 0.2))
        self.queue_depth = int(queue_depth)
        self.thresholds = self._ladder(
            self.queue_depth, (1.0, batch_fraction, background_fraction))
        self.floor_s = float(retry_after_s)
        self.max_s = max(float(retry_after_max_s), self.floor_s)
        self.alpha = min(max(float(ewma_alpha), 0.0), 1.0)
        self._lock = threading.Lock()
        self._pressure = 0.0  # EWMA of the shed fraction, in [0, 1]
        self._retry_s = self.floor_s
        self._shed_counts = [0] * len(PRIORITY_CLASSES)
        self._episode = [False] * len(PRIORITY_CLASSES)

    @staticmethod
    def _ladder(queue_depth: int, fractions) -> tuple:
        """Per-class depth thresholds, clamped monotone non-increasing
        from the highest class down -- the no-inversion invariant."""
        out = []
        prev = None
        for frac in fractions:
            t = int(math.ceil(queue_depth * min(max(frac, 0.0), 1.0)))
            if prev is not None:
                t = min(t, prev)
            out.append(t)
            prev = t
        return tuple(out)

    @property
    def enabled(self) -> bool:
        return self.queue_depth > 0

    def admit(self, depth: int, priority: Optional[int],
              cost: int = 1) -> bool:
        """One admission decision. ``depth`` is the observed backlog,
        ``priority`` an index into PRIORITY_CLASSES (None / out of
        range clamps to the lowest class -- garbage must never
        promote), ``cost`` how many queue slots this request is
        charged (>= 1; generation streams weigh their token budget).

        Admits iff ``depth + cost - 1 < threshold[class]`` -- with
        cost 1 exactly the historical ``depth < shed_depth`` rule, so
        an all-interactive deployment is decision-identical to the
        pre-ladder controller.
        """
        if not self.enabled:
            return True
        pri = priority if (isinstance(priority, int)
                           and 0 <= priority < len(self.thresholds)
                           ) else len(self.thresholds) - 1
        cost = max(1, int(cost))
        ok = depth + cost - 1 < self.thresholds[pri]
        with self._lock:
            if not ok:
                # advertise the backoff as of pressure BEFORE this
                # refusal: the first shed of a calm queue says exactly
                # the configured floor, and each consecutive shed says
                # strictly more (monotone, capped at max_s)
                self._retry_s = (self.floor_s
                                 + (self.max_s - self.floor_s)
                                 * self._pressure)
            self._pressure += self.alpha * ((0.0 if ok else 1.0)
                                            - self._pressure)
            if ok:
                self._episode[pri] = False
            else:
                self._shed_counts[pri] += 1
                first = not self._episode[pri]
                self._episode[pri] = True
        if not ok:
            name = priority_name(pri)
            _M_SHED.labels(**{"class": name}).inc()
            if first:
                # one event per shed EPISODE per class -- a sustained
                # overload must not churn the event ring with copies
                # of the same fact
                emit_event("request_shed", "serving", depth=depth,
                           shed_depth=self.thresholds[pri],
                           priority=name, cost=cost)
        return ok

    def retry_after_s(self) -> float:
        """Advertised client backoff: the value stamped at the most
        recent refusal (the configured floor when nothing has been
        refused). Consecutive refusals raise it monotonically toward
        ``retry_after_max_s``; admitted traffic decays the pressure
        behind it back down."""
        with self._lock:
            return self._retry_s

    def pressure(self) -> float:
        with self._lock:
            return self._pressure

    def shed_counts(self) -> Dict[str, int]:
        """Per-class refusals since construction (stats surface)."""
        with self._lock:
            return {priority_name(i): c
                    for i, c in enumerate(self._shed_counts)}
