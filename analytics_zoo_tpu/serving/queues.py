"""Serving queues: the Redis-Streams role, dependency-free.

The client API mirrors the reference's ``InputQueue``/``OutputQueue``
(ref: pyzoo/zoo/serving/client.py:52-250 -- enqueue XADDs base64-encoded
tensors; dequeue reads the result stream). Backends:

- ``MemQueue``: in-process deque (single-process serving, tests);
- ``DirQueue``: a spool directory; each item is one ``.npz`` file,
  consumers claim atomically with ``os.rename`` -- cross-process safe
  with no broker, and items survive crashes (the durability Redis
  provided in the reference).
"""

from __future__ import annotations

import collections
import io
import os
import struct
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.obs import tracing as _tracing
from analytics_zoo_tpu.obs.metrics import get_registry as _get_registry
from analytics_zoo_tpu.serving.admission import AdmissionController
from analytics_zoo_tpu.serving.protocol import (
    DEADLINE_KEY, EOS_KEY, HANDOFF_KEY, MAX_TOKENS_KEY, PRIORITY_KEY,
    REPLY_KEY, TENANT_KEY, TRACE_KEY, URI_KEY, WIRE_KEYS,
    priority_index)

# client-side data-plane counters (the queues' entry in the unified
# registry): offered load, backpressure rejections, drained results.
# The shed family (zoo_serving_shed_total) moved to admission.py when
# it grew the per-class label (ISSUE-15).
_REG = _get_registry()
_M_ENQ = _REG.counter(
    "zoo_serving_enqueue_total",
    "Requests offered to the serving input queue")
_M_ENQ_REJECTED = _REG.counter(
    "zoo_serving_enqueue_rejected_total",
    "Requests rejected by input-queue backpressure (queue full)")
_M_DEQ = _REG.counter(
    "zoo_serving_dequeue_total",
    "Results drained from the serving output queue")

# Wire format. v1 was np.savez (one zip archive per request): simple,
# but the zip machinery costs ~260 us per request round-trip -- it was
# the single largest host cost of the serving cycle (measured on the
# ISSUE-1 pipeline bench; see BENCH_NOTES.md). v2 ("AZT1") frames raw
# ndarray buffers with a dtype/shape header: ~15 us round-trip, no
# pickle surface, and decode still accepts v1 blobs (zip magic) so
# spooled items from older deployments keep draining.
_MAGIC = b"AZT1"
_ZIP_MAGIC = b"PK"  # np.savez container (legacy v1 blobs)


def _encode(uri: str, payload: Dict[str, np.ndarray],
            reply_to: Optional[str] = None,
            trace_id: Optional[str] = None,
            deadline: Optional[float] = None,
            max_tokens: Optional[int] = None,
            eos: Optional[int] = None,
            tenant: Optional[int] = None,
            priority: Optional[int] = None) -> bytes:
    items = [(URI_KEY, np.asarray(uri))]
    if reply_to:
        # reply-to stream for brokered deployments: the worker that
        # serves the request routes the result back to the REQUESTER'S
        # result stream (several frontends can share one broker)
        items.append((REPLY_KEY, np.asarray(reply_to)))
    if trace_id:
        # end-to-end tracing (obs.tracing): the id rides the blob so
        # worker stages can span against it; absent when tracing is off
        items.append((TRACE_KEY, np.asarray(trace_id)))
    if max_tokens is not None:
        # generation budget (ISSUE-10): the worker stops the stream
        # after this many new tokens (absent on predict requests)
        items.append((MAX_TOKENS_KEY,
                      np.asarray(int(max_tokens), np.int32)))
    if eos is not None:
        # generation stop token id (-1 = none)
        items.append((EOS_KEY, np.asarray(int(eos), np.int32)))
    if tenant is not None:
        # parameter-lane id (ISSUE-13): which member of a population-
        # backed model's stacked tree answers this request
        items.append((TENANT_KEY, np.asarray(int(tenant), np.int32)))
    if priority is not None:
        # admission class index (ISSUE-15): rides the blob so a
        # requeued/restarted request keeps its brownout class exactly
        # like __tenant__ keeps its lane; absent -> the
        # zoo.serving.priority.default_class at the decoder
        items.append((PRIORITY_KEY,
                      np.asarray(int(priority), np.int32)))
    if deadline is not None:
        # absolute epoch-seconds deadline (zoo.serving.deadline_ms,
        # stamped at enqueue): the worker rejects expired requests at
        # decode/dispatch/finalize with a structured deadline_exceeded
        # error. Wall-clock, not monotonic -- the blob may cross
        # processes/hosts, and skew only shifts the budget by clock
        # error, which deadline granularity (>= tens of ms) tolerates
        items.append((DEADLINE_KEY, np.asarray(float(deadline))))
    for k, v in payload.items():
        a = np.asarray(v)
        if not a.flags["C_CONTIGUOUS"]:
            # NOT np.ascontiguousarray: that promotes 0-d to (1,),
            # silently changing scalar tensors' round-tripped shape
            # (0-d arrays are already contiguous and skip this)
            a = np.ascontiguousarray(a)
        items.append((k, a))
    parts = [_MAGIC, struct.pack("<I", len(items))]
    for name, a in items:
        if a.dtype.hasobject:
            raise ValueError(
                f"tensor {name!r} has object dtype; only plain "
                "numeric/string arrays go on the serving wire")
        nb = name.encode("utf-8")
        db = a.dtype.str.encode("ascii")
        body = a.tobytes()
        parts.append(struct.pack("<HBB", len(nb), len(db), a.ndim))
        parts.append(nb)
        parts.append(db)
        parts.append(struct.pack("<%dq" % a.ndim, *a.shape))
        parts.append(struct.pack("<Q", len(body)))
        parts.append(body)
    return b"".join(parts)


_META_KEYS = WIRE_KEYS  # historical alias for the codec below


def _decode(blob: bytes) -> Tuple[str, Dict[str, np.ndarray]]:
    uri, tensors, _ = _decode_full(blob)
    return uri, tensors


def _decode_raw(blob: bytes) -> Dict[str, np.ndarray]:
    (count,) = struct.unpack_from("<I", blob, 4)
    off = 8
    out: Dict[str, np.ndarray] = {}
    for _ in range(count):
        nlen, dlen, ndim = struct.unpack_from("<HBB", blob, off)
        off += 4
        name = blob[off:off + nlen].decode("utf-8")
        off += nlen
        dtype = np.dtype(blob[off:off + dlen].decode("ascii"))
        off += dlen
        shape = struct.unpack_from("<%dq" % ndim, blob, off)
        off += 8 * ndim
        (nbytes,) = struct.unpack_from("<Q", blob, off)
        off += 8
        n = 1
        for s in shape:
            n *= s
        # .copy(): frombuffer views are read-only; requests keep the
        # writable-array contract the npz decoder gave user hooks
        out[name] = np.frombuffer(
            blob, dtype=dtype, count=n,
            offset=off).reshape(shape).copy()
        off += nbytes
    return out


def _decode_full(blob: bytes
                 ) -> Tuple[str, Dict[str, np.ndarray], Optional[str]]:
    uri, tensors, reply, _ = _decode_traced(blob)
    return uri, tensors, reply


def _decode_traced(blob: bytes) -> Tuple[str, Dict[str, np.ndarray],
                                         Optional[str], Optional[str]]:
    """Full decode incl. the trace id meta key (``_decode_full`` keeps
    the historical 3-tuple; the worker uses ``_decode_request``)."""
    uri, tensors, reply, trace, _ = _decode_request(blob)
    return uri, tensors, reply, trace


def _decode_to_dict(blob: bytes) -> Dict[str, np.ndarray]:
    """Framing dispatch, THE one place the blob container format is
    recognized: AZT1 raw-buffer framing, or the legacy np.savez (zip)
    container -- both -> {name: array}. Every decoder (predict,
    generation) goes through here, so a future framing change has one
    home."""
    if blob[:4] == _MAGIC:
        return _decode_raw(blob)
    if not blob.startswith(_ZIP_MAGIC):
        raise ValueError("not a serving wire blob (neither AZT1 nor "
                         "legacy npz framing)")
    with np.load(io.BytesIO(blob), allow_pickle=False) as z:  # legacy v1
        return {k: z[k] for k in z.files}


def _request_meta(z: Dict[str, np.ndarray]
                  ) -> Tuple[str, Optional[str], Optional[str],
                             Optional[float]]:
    """(uri, reply_to, trace_id, deadline) out of a decoded blob dict
    -- the meta keys every request carries regardless of data plane."""
    uri = str(z[URI_KEY].reshape(())) if URI_KEY in z else ""
    reply = str(z[REPLY_KEY].reshape(())) if REPLY_KEY in z else None
    trace = str(z[TRACE_KEY].reshape(())) if TRACE_KEY in z else None
    deadline = (float(z[DEADLINE_KEY].reshape(()))
                if DEADLINE_KEY in z else None)
    return uri, reply, trace, deadline


def _decode_request(blob: bytes
                    ) -> Tuple[str, Dict[str, np.ndarray],
                               Optional[str], Optional[str],
                               Optional[float]]:
    """The worker's decode: (uri, tensors, reply_to, trace_id,
    deadline) with every meta key stripped from the tensor dict."""
    z = _decode_to_dict(blob)
    uri, reply, trace, deadline = _request_meta(z)
    return uri, {k: v for k, v in z.items()
                 if k not in _META_KEYS}, reply, trace, deadline


def _decode_predict(blob: bytes
                    ) -> Tuple[str, Dict[str, np.ndarray],
                               Optional[str], Optional[str],
                               Optional[float], Optional[int],
                               Optional[int]]:
    """The predict worker's decode: ``_decode_request``'s 5-tuple plus
    the ``__tenant__`` parameter-lane id and the ``__priority__``
    admission class (None when the request names neither). A separate
    function -- NOT a new arity for ``_decode_request`` -- because
    that 5-tuple is unpacked outside this module (resilience requeue,
    redis adapter, tests)."""
    z = _decode_to_dict(blob)
    uri, reply, trace, deadline = _request_meta(z)
    tenant = (int(z[TENANT_KEY].reshape(()))
              if TENANT_KEY in z else None)
    priority = (int(z[PRIORITY_KEY].reshape(()))
                if PRIORITY_KEY in z else None)
    tensors = {k: v for k, v in z.items() if k not in _META_KEYS}
    return uri, tensors, reply, trace, deadline, tenant, priority


def _decode_generation(blob: bytes
                       ) -> Tuple[str, Dict[str, np.ndarray],
                                  Optional[str], Optional[str],
                                  Optional[float], Optional[int],
                                  Optional[int], Optional[int]]:
    """The generation worker's decode: ``_decode_request``'s 5-tuple
    plus ``(max_tokens, eos, priority)`` (None when the request
    omitted them -- the worker falls back to the ``zoo.generation.*``
    / ``zoo.serving.priority.*`` defaults)."""
    z = _decode_to_dict(blob)
    uri, reply, trace, deadline = _request_meta(z)
    max_tokens = (int(z[MAX_TOKENS_KEY].reshape(()))
                  if MAX_TOKENS_KEY in z else None)
    eos = int(z[EOS_KEY].reshape(())) if EOS_KEY in z else None
    priority = (int(z[PRIORITY_KEY].reshape(()))
                if PRIORITY_KEY in z else None)
    tensors = {k: v for k, v in z.items() if k not in _META_KEYS}
    return (uri, tensors, reply, trace, deadline, max_tokens, eos,
            priority)


# ------------------------------------------------- stream handoff --
# ISSUE-20 (disaggregated prefill/decode pools): a prefill replica
# publishes one handoff blob per admitted stream on the broker's
# handoff stream; a decode replica imports it and continues the
# stream. The blob carries the full replay state -- the prompt (for
# deterministic regeneration when the KV snapshot was dropped or died
# with its host), the page-aligned KV snapshot when it fits
# ``max_bytes``, and the slot registers + chunk-seq counters that keep
# re-served chunks dedupable at the client.

def _encode_handoff(uri: str, prompt: np.ndarray,
                    state: Dict[str, int],
                    snapshot: Optional[Dict[str, Any]] = None,
                    reply_to: Optional[str] = None,
                    trace_id: Optional[str] = None,
                    deadline: Optional[float] = None,
                    max_tokens: Optional[int] = None,
                    eos: Optional[int] = None,
                    priority: Optional[int] = None,
                    max_bytes: int = 0) -> bytes:
    """Encode a prefill->decode stream handoff. ``state`` carries the
    slot registers: ``next_token`` (the token the next decode step
    consumes), ``position`` (its write position), ``produced`` (output
    tokens already delivered), ``seq`` (next chunk sequence number)
    and ``emitted`` (whether ``next_token`` already reached the
    client). A snapshot larger than ``max_bytes`` (> 0) is dropped --
    the importer then re-prefills deterministically from the prompt."""
    payload: Dict[str, np.ndarray] = {
        HANDOFF_KEY: np.asarray(1, np.int32),
        "prompt": np.asarray(prompt, np.int32).reshape(-1),
    }
    for key in ("next_token", "position", "produced", "seq",
                "emitted"):
        payload[key] = np.asarray(int(state[key]), np.int32)
    if snapshot is not None:
        kv = np.asarray(snapshot["kv"])
        if not (max_bytes and kv.nbytes > max_bytes):
            payload["kv"] = kv
            payload["kv_length"] = np.asarray(
                int(snapshot["length"]), np.int32)
            payload["kv_reserve"] = np.asarray(
                int(snapshot["reserve"]), np.int32)
    return _encode(uri, payload, reply_to=reply_to, trace_id=trace_id,
                   deadline=deadline, max_tokens=max_tokens, eos=eos,
                   priority=priority)


def _decode_handoff(blob: bytes
                    ) -> Tuple[str, Dict[str, Any], Optional[str],
                               Optional[str], Optional[float],
                               Optional[int], Optional[int],
                               Optional[int]]:
    """The decode replica's decode: ``(uri, handoff, reply, trace,
    deadline, max_tokens, eos, priority)`` where ``handoff`` holds the
    prompt, the slot-register state, and ``snapshot`` (an
    ``import_pages``-shaped dict, or None when the KV pages were
    dropped at publish time). Raises ValueError on a blob that is not
    a handoff (no ``__handoff__`` marker) -- a client request on the
    handoff stream is a routing bug, not a soft error."""
    z = _decode_to_dict(blob)
    if HANDOFF_KEY not in z:
        raise ValueError("not a handoff blob (no __handoff__ marker)")
    uri, reply, trace, deadline = _request_meta(z)
    max_tokens = (int(z[MAX_TOKENS_KEY].reshape(()))
                  if MAX_TOKENS_KEY in z else None)
    eos = int(z[EOS_KEY].reshape(())) if EOS_KEY in z else None
    priority = (int(z[PRIORITY_KEY].reshape(()))
                if PRIORITY_KEY in z else None)
    handoff: Dict[str, Any] = {
        "prompt": np.asarray(z["prompt"], np.int32).reshape(-1),
        "snapshot": None,
    }
    for key in ("next_token", "position", "produced", "seq",
                "emitted"):
        handoff[key] = int(z[key].reshape(()))
    if "kv" in z:
        handoff["snapshot"] = {
            "kv": z["kv"],
            "length": int(z["kv_length"].reshape(())),
            "reserve": int(z["kv_reserve"].reshape(())),
            "next_token": handoff["next_token"],
            "position": handoff["position"],
            "rng": None,
        }
    return (uri, handoff, reply, trace, deadline, max_tokens, eos,
            priority)


def _discard_handoff(snapshot: Optional[Dict[str, Any]]) -> None:
    """Abandon an exported KV snapshot that will never reach the wire
    (encode failed before publish). The pages themselves still live in
    the engine slot the exporter holds, so dropping the copy frees
    nothing -- this exists (and is registered as the kv-handoff
    release verb in zoolint's lifecycle registry) so an abandonment is
    a visible decision on the failure path, not a silent leak."""
    return None


class MemQueue:
    def __init__(self, maxlen: Optional[int] = None):
        self._q: collections.deque = collections.deque()
        self._maxlen = maxlen
        self._cv = threading.Condition()

    def put(self, item: bytes) -> bool:
        with self._cv:
            if self._maxlen is not None and len(self._q) >= self._maxlen:
                return False  # backpressure signal
            self._q.append(item)
            self._cv.notify()
            return True

    def get(self, timeout: Optional[float] = None) -> Optional[bytes]:
        with self._cv:
            if timeout is None:  # shared contract: None = block forever
                while not self._q:
                    self._cv.wait()
            elif not self._q:
                self._cv.wait(timeout)
            if not self._q:
                return None
            return self._q.popleft()

    def get_many(self, n: int) -> List[bytes]:
        """Drain up to ``n`` items without blocking -- one lock
        acquisition instead of ``n`` condvar round-trips (the batcher's
        deep-backlog fast path)."""
        with self._cv:
            k = min(n, len(self._q))
            return [self._q.popleft() for _ in range(k)]

    def put_many(self, items: List[bytes]) -> int:
        """Append up to capacity in one lock trip; returns how many
        were accepted (the finalize stage pushes whole batches --
        per-item lock/notify costs add up at adaptive batch sizes)."""
        with self._cv:
            if self._maxlen is None:
                self._q.extend(items)
                accepted = len(items)
            else:
                room = max(0, self._maxlen - len(self._q))
                accepted = min(room, len(items))
                self._q.extend(items[:accepted])
            if accepted:
                self._cv.notify(accepted)
            return accepted

    def __len__(self) -> int:
        with self._cv:
            return len(self._q)


class DirQueue:
    """Spool-directory queue; items ordered by (timestamp, uuid) name."""

    def __init__(self, path: str, maxlen: Optional[int] = None):
        self.path = path
        self._maxlen = maxlen
        os.makedirs(path, exist_ok=True)

    def put(self, item: bytes) -> bool:
        if self._maxlen is not None and len(self) >= self._maxlen:
            return False
        name = f"{time.time_ns():020d}-{uuid.uuid4().hex}"
        tmp = os.path.join(self.path, f".{name}.tmp")
        with open(tmp, "wb") as f:
            f.write(item)
        os.replace(tmp, os.path.join(self.path, name + ".item"))
        return True

    def get(self, timeout: Optional[float] = None) -> Optional[bytes]:
        deadline = (None if timeout is None  # None = block forever
                    else time.time() + timeout)
        while True:
            for name in sorted(os.listdir(self.path)):
                if not name.endswith(".item"):
                    continue
                src = os.path.join(self.path, name)
                claimed = os.path.join(self.path, name + ".claimed")
                try:
                    os.rename(src, claimed)  # atomic claim
                except OSError:
                    continue  # another consumer won
                with open(claimed, "rb") as f:
                    data = f.read()
                os.unlink(claimed)
                return data
            if deadline is not None and time.time() >= deadline:
                return None
            time.sleep(0.005)

    def get_many(self, n: int) -> List[bytes]:
        """Claim up to ``n`` items in one directory scan (non-blocking;
        losing a claim race to another consumer just skips that item)."""
        out: List[bytes] = []
        for name in sorted(os.listdir(self.path)):
            if len(out) >= n:
                break
            if not name.endswith(".item"):
                continue
            src = os.path.join(self.path, name)
            claimed = src + ".claimed"
            try:
                os.rename(src, claimed)
            except OSError:
                continue
            with open(claimed, "rb") as f:
                out.append(f.read())
            os.unlink(claimed)
        return out

    def __len__(self) -> int:
        return sum(1 for n in os.listdir(self.path)
                   if n.endswith(".item"))


class TcpQueueServer:
    """A tiny stream broker: named MemQueues served over TCP.

    The cross-host data plane the reference delegated to Redis Streams
    (ref: serving/engine/FlinkRedisSource.scala XREADGROUP consumer
    groups): one broker process per serving deployment, any number of
    producer/consumer hosts. Framed request/response per connection:

      request  = op:1 (P/G/L) | name_len:2 | name | arg:4 | payload
      response = status:1 (K/E/N) | payload_len:4 | payload

    P(ut): arg = payload length, K/E(full) back. G(et): arg = timeout
    in ms, K+payload or N(othing). L(en): K + 4-byte count.
    """

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 maxlen: Optional[int] = 10000):
        import socket

        self._maxlen = maxlen
        self._queues: Dict[str, MemQueue] = {}
        self._qlock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        host, port = self._sock.getsockname()[:2]
        return f"tcp://{host}:{port}"

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def _queue(self, name: str) -> MemQueue:
        with self._qlock:
            if name not in self._queues:
                self._queues[name] = MemQueue(self._maxlen)
            return self._queues[name]

    def start(self) -> "TcpQueueServer":
        self._stop.clear()
        self._sock.settimeout(0.2)
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        self._sock.close()

    def _accept_loop(self):
        import socket

        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        import struct as _struct

        try:
            conn.settimeout(None)
            while not self._stop.is_set():
                head = _recv_exact(conn, 7)
                if head is None:
                    return
                op = chr(head[0])
                (nlen,) = _struct.unpack(">H", head[1:3])
                (arg,) = _struct.unpack(">I", head[3:7])
                name = _recv_exact(conn, nlen)
                if name is None:
                    return
                q = self._queue(name.decode())
                if op == "P":
                    payload = _recv_exact(conn, arg)
                    if payload is None:
                        return
                    ok = q.put(payload)
                    conn.sendall((b"K" if ok else b"E")
                                 + _struct.pack(">I", 0))
                elif op == "G":
                    blob = q.get(timeout=arg / 1000.0)
                    if blob is None:
                        conn.sendall(b"N" + _struct.pack(">I", 0))
                    else:
                        conn.sendall(b"K" + _struct.pack(">I", len(blob))
                                     + blob)
                elif op == "L":
                    n = _struct.pack(">I", len(q))
                    conn.sendall(b"K" + _struct.pack(">I", 4) + n)
                else:
                    return
        except OSError:
            pass
        finally:
            conn.close()


def _recv_exact(conn, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class TcpQueue:
    """Client backend for :class:`TcpQueueServer`; address
    ``tcp://host:port`` plus a stream name. Reconnects per failure,
    thread-safe via one lock (a connection carries one in-flight
    request at a time)."""

    def __init__(self, address: str, name: str = "serving_stream"):
        if address.startswith("tcp://"):
            address = address[len("tcp://"):]
        host, port = address.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self._name = name.encode()
        # separate channels: a blocking G wait (up to _GET_SLICE_S)
        # must not hold up P/L callers sharing this object
        self._chan = {"main": [None, threading.Lock()],
                      "get": [None, threading.Lock()]}

    # server-side wait per G request; long client timeouts poll in
    # slices so the socket deadline always exceeds the blocking wait
    # and an abandoned request can't strand an item on a dead socket
    _GET_SLICE_S = 2.0

    def _request(self, op: bytes, arg: int, payload: bytes = b"",
                 retry: bool = True, wait_s: float = 0.0,
                 channel: str = "main"):
        import socket
        import struct as _struct

        chan = self._chan[channel]
        with chan[1]:
            for attempt in (0, 1):
                try:
                    if chan[0] is None:
                        chan[0] = socket.create_connection(
                            (self._host, self._port), timeout=30.0)
                    conn = chan[0]
                    # recv deadline must cover the server-side wait
                    conn.settimeout(30.0 + wait_s)
                    conn.sendall(op + _struct.pack(">H", len(self._name))
                                 + _struct.pack(">I", arg)
                                 + self._name + payload)
                    head = _recv_exact(conn, 5)
                    if head is None:
                        raise OSError("connection closed")
                    status = chr(head[0])
                    (plen,) = _struct.unpack(">I", head[1:5])
                    body = _recv_exact(conn, plen) if plen else b""
                    if plen and body is None:
                        raise OSError("connection closed mid-body")
                    return status, body
                except OSError:
                    chan[0] = None
                    if attempt or not retry:
                        raise
        raise OSError("unreachable")

    def put(self, item: bytes) -> bool:
        status, _ = self._request(b"P", len(item), item)
        return status == "K"

    def get(self, timeout: Optional[float] = None) -> Optional[bytes]:
        deadline = (None if timeout is None  # None = block forever
                    else time.monotonic() + max(0.0, timeout))
        while True:
            remaining = (self._GET_SLICE_S if deadline is None
                         else deadline - time.monotonic())
            wait = min(max(remaining, 0.0), self._GET_SLICE_S)
            # no blind retry on G: a re-sent request after a half-done
            # one could pop an item onto a dead connection
            status, body = self._request(b"G", int(wait * 1000),
                                         retry=False, wait_s=wait,
                                         channel="get")
            if status == "K":
                return body
            if deadline is not None and time.monotonic() >= deadline:
                return None

    def __len__(self) -> int:
        import struct as _struct

        status, body = self._request(b"L", 0)
        return _struct.unpack(">I", body)[0] if status == "K" else 0

    def for_stream(self, name: str) -> "TcpQueue":
        """Handle on another stream of the same broker (the worker's
        reply-to routing; brokered backends share this protocol)."""
        return TcpQueue(f"tcp://{self._host}:{self._port}", name=name)


def _make_backend(backend, path: Optional[str], maxlen: Optional[int],
                  name: str = "serving_stream",
                  group: Optional[str] = None,
                  consumer: Optional[str] = None,
                  autoack: bool = False):
    if isinstance(backend, str) and backend.startswith("redis://"):
        # fleet data plane: a consumer-group stream on the RESP2
        # broker (redis_adapter) -- N workers passing the same group
        # shard the stream, claims ride the pending list until the
        # worker acks them on reply (lazy import: redis_adapter
        # imports this module for the wire codec)
        from analytics_zoo_tpu.serving.redis_adapter import (
            RedisStreamQueue)

        return RedisStreamQueue(backend, stream=name, group=group,
                                consumer=consumer, autoack=autoack)
    if isinstance(backend, str) and backend.startswith("tcp://"):
        return TcpQueue(backend, name=name)
    if backend == "tcp":
        if not path or "://" not in str(path) and ":" not in str(path):
            raise ValueError('backend "tcp" needs path "host:port"')
        return TcpQueue(str(path), name=name)
    if backend == "memory" or (backend is None and path is None):
        return MemQueue(maxlen)
    if backend == "dir" or path is not None:
        return DirQueue(path, maxlen)
    raise ValueError(f"unknown backend {backend!r}")


class InputQueue:
    """(ref: client.py InputQueue.enqueue/predict). ``backend`` may be
    a ``tcp://host:port`` broker address (cross-host data plane);
    ``name`` is the stream on that broker (ref: serving_stream)."""

    def __init__(self, backend=None, path: Optional[str] = None,
                 maxlen: Optional[int] = 10000, queue=None,
                 name: str = "serving_stream",
                 reply_stream: Optional[str] = None,
                 shed_depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 group: Optional[str] = None,
                 consumer: Optional[str] = None):
        self._q = queue if queue is not None else _make_backend(
            backend, path, maxlen, name=name, group=group,
            consumer=consumer)
        # when set, every request carries this reply-to stream so the
        # serving worker routes its result back to THIS producer's
        # result stream (brokered multi-frontend deployments)
        self.reply_stream = reply_stream
        # admission control (ISSUE-5), resolved ONCE at construction
        # so the disabled path stays one int/float compare per
        # enqueue: shed_depth refuses new work above a backlog depth
        # (softer than maxlen -- the queue still absorbs in-flight
        # producers, the frontend turns the refusal into 503 +
        # Retry-After); deadline_ms stamps each blob with an absolute
        # deadline the worker enforces at every stage
        from analytics_zoo_tpu.common.config import get_config

        cfg = get_config()
        self.shed_depth = int(
            cfg.get("zoo.serving.shed.queue_depth", 0)
            if shed_depth is None else shed_depth)
        self.deadline_ms = float(
            cfg.get("zoo.serving.deadline_ms", 0.0)
            if deadline_ms is None else deadline_ms)
        # brownout ladder (ISSUE-15): the controller owns per-class
        # thresholds, shed counters/events, and the adaptive
        # Retry-After; requests without an explicit class admit as
        # zoo.serving.priority.default_class
        self._admission = AdmissionController(self.shed_depth)
        self.default_priority = priority_index(
            cfg.get("zoo.serving.priority.default_class",
                    "interactive")) or 0
        # generation admission cost: one queue slot per this many
        # budgeted tokens (long streams are charged like the long
        # occupancy they are)
        self._gen_cost_tokens = int(
            cfg.get("zoo.serving.shed.gen_cost_tokens", 16))
        self._gen_default_tokens = int(
            cfg.get("zoo.generation.max_tokens", 64))

    @property
    def queue(self):
        return self._q

    def enqueue(self, uri: str, tenant: Optional[int] = None,
                priority=None, **tensors) -> bool:
        """False means the queue refused the request -- full (hard
        backpressure; the reference surfaces Redis OOM errors here,
        client.py:176-192) or shedding (the brownout ladder refused
        this request's class at the observed depth). A trace context
        open on this thread (obs.tracing) rides the blob as
        ``__trace__`` -- one thread-local read when tracing is off.
        ``tenant`` selects a parameter lane of a population-backed
        model (ISSUE-13; rides the blob as ``__tenant__``);
        ``priority`` is a class name or index (ISSUE-15; rides the
        blob as ``__priority__``, absent when the caller names none).
        """
        pri = priority_index(priority)
        if self.shed_depth and self._shed(
                self.default_priority if pri is None else pri):
            return False
        deadline = (time.time() + self.deadline_ms / 1000.0
                    if self.deadline_ms else None)
        ok = self._q.put(_encode(uri, tensors,
                                 reply_to=self.reply_stream,
                                 trace_id=_tracing.current_trace_id(),
                                 deadline=deadline, tenant=tenant,
                                 priority=pri))
        _M_ENQ.inc()
        if not ok:
            _M_ENQ_REJECTED.inc()
        return ok

    def _shed(self, priority: int, cost: int = 1) -> bool:
        """Shed-or-admit; the depth probe costs one len() per enqueue
        (a broker RPC on TcpQueue backends), which is why shedding is
        opt-in via ``zoo.serving.shed.queue_depth``."""
        try:
            depth = len(self._q)
        except (TypeError, OSError):
            return False  # depth-less backend: cannot shed on depth
        if self._admission.admit(depth, priority, cost=cost):
            return False
        _M_ENQ.inc()  # a shed request still counts as offered load
        return True

    def retry_after_s(self) -> float:
        """The adaptive Retry-After the frontend should advertise on
        shed 503s (floor = zoo.serving.shed.retry_after_s, scaled by
        current shed pressure up to retry_after_max_s)."""
        return self._admission.retry_after_s()

    @property
    def admission(self) -> AdmissionController:
        return self._admission

    def enqueue_generation(self, uri: str, tokens,
                           max_tokens: Optional[int] = None,
                           eos: Optional[int] = None,
                           priority=None) -> bool:
        """Enqueue a *generate* request (ISSUE-10): ``tokens`` is the
        1-D int prompt; ``max_tokens``/``eos`` ride the blob as
        reserved wire keys next to the deadline. Same admission
        control / shedding / False-means-refused contract as
        :meth:`enqueue`, except the admission COST is max_tokens-
        weighted (ceil(budget / zoo.serving.shed.gen_cost_tokens)) so
        one long stream cannot starve interactive traffic."""
        pri = priority_index(priority)
        budget = (self._gen_default_tokens if max_tokens is None
                  else max(1, int(max_tokens)))
        cost = max(1, -(-budget // max(1, self._gen_cost_tokens)))
        if self.shed_depth and self._shed(
                self.default_priority if pri is None else pri,
                cost=cost):
            return False
        deadline = (time.time() + self.deadline_ms / 1000.0
                    if self.deadline_ms else None)
        ok = self._q.put(_encode(
            uri, {"tokens": np.asarray(tokens, np.int32).reshape(-1)},
            reply_to=self.reply_stream,
            trace_id=_tracing.current_trace_id(),
            deadline=deadline, max_tokens=max_tokens, eos=eos,
            priority=pri))
        _M_ENQ.inc()
        if not ok:
            _M_ENQ_REJECTED.inc()
        return ok

    def enqueue_image(self, uri: str, data, key: str = "image") -> bool:
        """Enqueue a COMPRESSED image (JPEG/PNG file path or bytes);
        the serving worker decodes it host-side (the reference client's
        base64-image enqueue, ref: client.py enqueue_image +
        PreProcessing.decodeImage). ~10-20x less wire payload than the
        raw pixel tensor."""
        if isinstance(data, (bytes, bytearray)):
            raw = bytes(data)
        else:
            with open(data, "rb") as f:
                raw = f.read()
        return self.enqueue(uri, **{key: np.frombuffer(raw, np.uint8)})

    def __len__(self):
        return len(self._q)


class OutputQueue:
    """(ref: client.py OutputQueue.dequeue/query). ``backend`` may be a
    ``tcp://host:port`` broker address; ``name`` defaults to the result
    stream (ref: result XADD stream)."""

    def __init__(self, backend=None, path: Optional[str] = None,
                 maxlen: Optional[int] = None, queue=None,
                 name: str = "result_stream",
                 group: Optional[str] = None,
                 consumer: Optional[str] = None):
        # result consumers are each their stream's sole owner, so a
        # brokered group consumes destructively (autoack) -- the PEL's
        # exactly-once machinery is the REQUEST stream's concern
        self._q = queue if queue is not None else _make_backend(
            backend, path, maxlen, name=name, group=group,
            consumer=consumer, autoack=True)

    @property
    def queue(self):
        return self._q

    def dequeue(self, timeout: Optional[float] = None
                ) -> Optional[Tuple[str, Dict[str, np.ndarray]]]:
        """Pop one result. ``timeout=None`` blocks until an item
        arrives (uniform across memory/dir/tcp backends); ``timeout=0``
        polls; a positive timeout waits up to that many seconds and
        returns None on expiry."""
        blob = self._q.get(timeout)
        if blob is None:
            return None
        _M_DEQ.inc()
        return _decode(blob)

    def dequeue_all(self) -> List[Tuple[str, Dict[str, np.ndarray]]]:
        if hasattr(self._q, "get_many"):
            out = []
            while True:  # batched drain: one lock trip per chunk
                blobs = self._q.get_many(256)
                out.extend(_decode(b) for b in blobs)
                if len(blobs) < 256:
                    if out:
                        _M_DEQ.inc(len(out))
                    return out
        out = []
        while True:
            item = self.dequeue(timeout=0)
            if item is None:
                return out
            out.append(item)
