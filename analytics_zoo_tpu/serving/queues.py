"""Serving queues: the Redis-Streams role, dependency-free.

The client API mirrors the reference's ``InputQueue``/``OutputQueue``
(ref: pyzoo/zoo/serving/client.py:52-250 -- enqueue XADDs base64-encoded
tensors; dequeue reads the result stream). Backends:

- ``MemQueue``: in-process deque (single-process serving, tests);
- ``DirQueue``: a spool directory; each item is one ``.npz`` file,
  consumers claim atomically with ``os.rename`` -- cross-process safe
  with no broker, and items survive crashes (the durability Redis
  provided in the reference).
"""

from __future__ import annotations

import collections
import io
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


def _encode(uri: str, payload: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, __uri__=np.asarray(uri),
             **{k: np.asarray(v) for k, v in payload.items()})
    return buf.getvalue()


def _decode(blob: bytes) -> Tuple[str, Dict[str, np.ndarray]]:
    with np.load(io.BytesIO(blob), allow_pickle=False) as z:
        uri = str(z["__uri__"])
        return uri, {k: z[k] for k in z.files if k != "__uri__"}


class MemQueue:
    def __init__(self, maxlen: Optional[int] = None):
        self._q: collections.deque = collections.deque()
        self._maxlen = maxlen
        self._cv = threading.Condition()

    def put(self, item: bytes) -> bool:
        with self._cv:
            if self._maxlen is not None and len(self._q) >= self._maxlen:
                return False  # backpressure signal
            self._q.append(item)
            self._cv.notify()
            return True

    def get(self, timeout: Optional[float] = None) -> Optional[bytes]:
        with self._cv:
            if not self._q:
                self._cv.wait(timeout)
            if not self._q:
                return None
            return self._q.popleft()

    def __len__(self) -> int:
        with self._cv:
            return len(self._q)


class DirQueue:
    """Spool-directory queue; items ordered by (timestamp, uuid) name."""

    def __init__(self, path: str, maxlen: Optional[int] = None):
        self.path = path
        self._maxlen = maxlen
        os.makedirs(path, exist_ok=True)

    def put(self, item: bytes) -> bool:
        if self._maxlen is not None and len(self) >= self._maxlen:
            return False
        name = f"{time.time_ns():020d}-{uuid.uuid4().hex}"
        tmp = os.path.join(self.path, f".{name}.tmp")
        with open(tmp, "wb") as f:
            f.write(item)
        os.replace(tmp, os.path.join(self.path, name + ".item"))
        return True

    def get(self, timeout: Optional[float] = None) -> Optional[bytes]:
        deadline = time.time() + (timeout or 0)
        while True:
            for name in sorted(os.listdir(self.path)):
                if not name.endswith(".item"):
                    continue
                src = os.path.join(self.path, name)
                claimed = os.path.join(self.path, name + ".claimed")
                try:
                    os.rename(src, claimed)  # atomic claim
                except OSError:
                    continue  # another consumer won
                with open(claimed, "rb") as f:
                    data = f.read()
                os.unlink(claimed)
                return data
            if timeout is None or time.time() >= deadline:
                return None
            time.sleep(0.005)

    def __len__(self) -> int:
        return sum(1 for n in os.listdir(self.path)
                   if n.endswith(".item"))


def _make_backend(backend, path: Optional[str], maxlen: Optional[int]):
    if backend == "memory" or (backend is None and path is None):
        return MemQueue(maxlen)
    if backend == "dir" or path is not None:
        return DirQueue(path, maxlen)
    raise ValueError(f"unknown backend {backend!r}")


class InputQueue:
    """(ref: client.py InputQueue.enqueue/predict)."""

    def __init__(self, backend=None, path: Optional[str] = None,
                 maxlen: Optional[int] = 10000, queue=None):
        self._q = queue if queue is not None else _make_backend(
            backend, path, maxlen)

    @property
    def queue(self):
        return self._q

    def enqueue(self, uri: str, **tensors) -> bool:
        """False means the queue is full (backpressure; the reference
        surfaces Redis OOM errors here, client.py:176-192)."""
        return self._q.put(_encode(uri, tensors))

    def __len__(self):
        return len(self._q)


class OutputQueue:
    """(ref: client.py OutputQueue.dequeue/query)."""

    def __init__(self, backend=None, path: Optional[str] = None,
                 maxlen: Optional[int] = None, queue=None):
        self._q = queue if queue is not None else _make_backend(
            backend, path, maxlen)

    @property
    def queue(self):
        return self._q

    def dequeue(self, timeout: Optional[float] = None
                ) -> Optional[Tuple[str, Dict[str, np.ndarray]]]:
        blob = self._q.get(timeout)
        return None if blob is None else _decode(blob)

    def dequeue_all(self) -> List[Tuple[str, Dict[str, np.ndarray]]]:
        out = []
        while True:
            item = self.dequeue(timeout=0)
            if item is None:
                return out
            out.append(item)
