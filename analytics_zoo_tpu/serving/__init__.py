"""Streaming model serving.

The analog of Cluster Serving (ref: zoo/.../serving -- Flink job reading
Redis Streams, micro-batching into an InferenceModel, akka-http frontend;
SURVEY.md sections 2.1/3.5). The TPU-native redesign replaces
Flink TM + Redis + akka with: a dependency-free durable queue (directory
backend, atomic claim via rename; or in-memory for single-process),
a micro-batcher with bounded backpressure, a serving worker around
``InferenceModel``, and a stdlib HTTP frontend with /predict + /metrics.
Resilience (supervised restarts, circuit breaker, deadlines, load
shedding) lives in ``resilience``; the deterministic fault-injection
harness that proves it lives in ``chaos``. ``fleet`` scales all of it
horizontally: N replica launcher processes sharding one consumer-group
stream (``redis_adapter`` stream mode) behind a health-checking HTTP
router, with drain-based rolling restarts and a metrics-driven
autoscaler. ``generation`` adds the token-streaming data plane
(ISSUE-10): prefill/decode split over a paged KV cache
(``inference.kv_cache``), slot-based continuous batching, and chunked
``POST /generate`` streams -- same supervisor/drain/chaos/fleet seams
as the predict worker. The wire vocabulary -- reserved blob keys and
structured error prefixes -- has ONE declaring module, ``protocol``
(lint-enforced by zoolint's protocol family).
"""

from analytics_zoo_tpu.serving.queues import (  # noqa: F401
    InputQueue,
    OutputQueue,
    DirQueue,
    MemQueue,
)
from analytics_zoo_tpu.serving.batcher import (  # noqa: F401
    AdaptiveBatcher,
    MicroBatcher,
)
from analytics_zoo_tpu.serving.worker import ServingWorker  # noqa: F401
from analytics_zoo_tpu.serving.generation import (  # noqa: F401
    ContinuousBatcher,
    DecodeEngine,
    GenerationWorker,
    GenModelConfig,
    TinyGenLM,
)
from analytics_zoo_tpu.serving.launcher import (  # noqa: F401
    ServingApp,
    launch,
    launch_from_yaml,
)
from analytics_zoo_tpu.serving.timer import Timer  # noqa: F401
from analytics_zoo_tpu.serving.http_frontend import (  # noqa: F401
    HttpFrontend,
)
from analytics_zoo_tpu.serving.redis_adapter import (  # noqa: F401
    RedisFrontend,
    RedisStreamQueue,
    StreamStore,
)
from analytics_zoo_tpu.serving.fleet import (  # noqa: F401
    Autoscaler,
    FleetController,
    FleetRouter,
)
from analytics_zoo_tpu.serving.resilience import (  # noqa: F401
    CircuitBreaker,
    RequestLedger,
    Supervisor,
)
from analytics_zoo_tpu.serving.chaos import (  # noqa: F401
    ChaosInjector,
    parse_spec,
)
from analytics_zoo_tpu.serving.protocol import (  # noqa: F401
    ERROR_PREFIXES,
    WIRE_KEYS,
    error_status,
)
