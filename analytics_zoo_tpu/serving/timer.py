"""Per-stage timing statistics.

The analog of serving ``Timer`` (ref: zoo/.../serving/engine/Timer.scala:
24-90 -- total/avg/max/min/top-10 per stage, printed periodically) and the
``Supportive.timing`` wrapper (ref: zoo/.../serving/utils/Supportive.scala).
"""

from __future__ import annotations

import heapq
import threading
import time
from contextlib import contextmanager
from typing import Dict, List


class _StageStat:
    __slots__ = ("count", "total", "max", "min", "top")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.min = float("inf")
        self.top: List[float] = []  # min-heap of the 10 largest

    def record(self, dt: float) -> None:
        self.count += 1
        self.total += dt
        self.max = max(self.max, dt)
        self.min = min(self.min, dt)
        if len(self.top) < 10:
            heapq.heappush(self.top, dt)
        else:
            heapq.heappushpop(self.top, dt)


class Timer:
    def __init__(self):
        self._stats: Dict[str, _StageStat] = {}
        self._lock = threading.Lock()

    @contextmanager
    def timing(self, name: str, batch: int = 1):
        """(ref: Supportive.scala timing)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._stats.setdefault(name, _StageStat()).record(dt)

    def summary(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            out = {}
            for name, s in self._stats.items():
                if not s.count:
                    continue
                out[name] = {
                    "count": s.count,
                    "total_s": s.total,
                    "avg_s": s.total / s.count,
                    "max_s": s.max,
                    "min_s": s.min,
                    "top10_avg_s": (sum(s.top) / len(s.top)
                                    if s.top else 0.0),
                }
            return out

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
