"""Per-stage timing statistics (thin shim over the obs registry).

The analog of serving ``Timer`` (ref: zoo/.../serving/engine/Timer.scala:
24-90 -- total/avg/max/min/top-10 per stage, printed periodically) and the
``Supportive.timing`` wrapper (ref: zoo/.../serving/utils/Supportive.scala).

Since ISSUE-2 the stat math lives in one place --
:class:`analytics_zoo_tpu.obs.metrics.StatCore` -- and a Timer can
*mirror* every stage duration into a labelled registry histogram family
(``mirror=``), which is how the serving worker's stage summaries appear
in ``GET /metrics`` Prometheus exposition while ``summary()`` keeps its
historical per-instance dict shape.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

from analytics_zoo_tpu.obs.metrics import Histogram, StatCore


class Timer:
    """``keep_samples``: per-stage raw-sample ring size; when > 0 the
    summary gains p50_s/p99_s percentiles (the reference prints only
    total/avg/max/min/top-10, Timer.scala:24-90; percentiles are what
    the serving bench needs to split worker service time from client
    latency). ``mirror``: an obs registry :class:`Histogram` family
    labelled by ``stage`` -- every duration recorded here is also
    observed there, so per-instance summaries and the process-wide
    scrape surface stay in lockstep."""

    def __init__(self, keep_samples: int = 0,
                 mirror: Optional[Histogram] = None):
        self._stats: Dict[str, StatCore] = {}
        self._gauges: Dict[str, StatCore] = {}
        self._keep = keep_samples
        self._lock = threading.Lock()
        self._mirror = mirror

    @contextmanager
    def timing(self, name: str, batch: int = 1):
        """(ref: Supportive.scala timing)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - t0)

    def record(self, name: str, dt: float) -> None:
        """Record an externally-measured duration (spans that cross
        function boundaries, e.g. the worker's pipelined batch
        service time)."""
        with self._lock:
            stat = self._stats.get(name)
            if stat is None:
                stat = self._stats[name] = StatCore(self._keep)
            stat.observe(dt)
        if self._mirror is not None:
            self._mirror.labels(stage=name).observe(dt)

    def gauge(self, name: str, value: float) -> None:
        """Record a sampled VALUE (queue depth, batch occupancy,
        in-flight count) rather than a duration; summarized under the
        ``gauges`` key of :meth:`summary` with unit-less stat names."""
        with self._lock:
            stat = self._gauges.get(name)
            if stat is None:
                stat = self._gauges[name] = StatCore(self._keep)
            stat.observe(float(value))

    def summary(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            out = {}
            for name, s in self._stats.items():
                if not s.count:
                    continue
                out[name] = s.summary("_s")
            gauges = {}
            for name, s in self._gauges.items():
                if not s.count:
                    continue
                g = {"count": s.count, "avg": s.avg, "max": s.max,
                     "min": s.min}
                p50 = s.percentile(0.50)
                if p50 is not None:
                    g["p50"] = p50
                    g["p99"] = s.percentile(0.99)
                gauges[name] = g
            if gauges:
                out["gauges"] = gauges
            return out

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._gauges.clear()
