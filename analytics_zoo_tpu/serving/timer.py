"""Per-stage timing statistics.

The analog of serving ``Timer`` (ref: zoo/.../serving/engine/Timer.scala:
24-90 -- total/avg/max/min/top-10 per stage, printed periodically) and the
``Supportive.timing`` wrapper (ref: zoo/.../serving/utils/Supportive.scala).
"""

from __future__ import annotations

import heapq
import threading
import time
from contextlib import contextmanager
from typing import Dict, List


class _StageStat:
    __slots__ = ("count", "total", "max", "min", "top", "samples",
                 "_cap")

    def __init__(self, keep_samples: int = 0):
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.min = float("inf")
        self.top: List[float] = []  # min-heap of the 10 largest
        # raw sample ring (percentiles); 0 disables
        self.samples: List[float] = [] if keep_samples else None
        self._cap = keep_samples

    def record(self, dt: float) -> None:
        self.count += 1
        self.total += dt
        self.max = max(self.max, dt)
        self.min = min(self.min, dt)
        if len(self.top) < 10:
            heapq.heappush(self.top, dt)
        else:
            heapq.heappushpop(self.top, dt)
        if self.samples is not None:
            if len(self.samples) >= self._cap:
                self.samples[self.count % self._cap] = dt
            else:
                self.samples.append(dt)


class Timer:
    """``keep_samples``: per-stage raw-sample ring size; when > 0 the
    summary gains p50_s/p99_s percentiles (the reference prints only
    total/avg/max/min/top-10, Timer.scala:24-90; percentiles are what
    the serving bench needs to split worker service time from client
    latency)."""

    def __init__(self, keep_samples: int = 0):
        self._stats: Dict[str, _StageStat] = {}
        self._gauges: Dict[str, _StageStat] = {}
        self._keep = keep_samples
        self._lock = threading.Lock()

    @contextmanager
    def timing(self, name: str, batch: int = 1):
        """(ref: Supportive.scala timing)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._stats.setdefault(
                    name, _StageStat(self._keep)).record(dt)

    def record(self, name: str, dt: float) -> None:
        """Record an externally-measured duration (spans that cross
        function boundaries, e.g. the worker's pipelined batch
        service time)."""
        with self._lock:
            self._stats.setdefault(
                name, _StageStat(self._keep)).record(dt)

    def gauge(self, name: str, value: float) -> None:
        """Record a sampled VALUE (queue depth, batch occupancy,
        in-flight count) rather than a duration; summarized under the
        ``gauges`` key of :meth:`summary` with unit-less stat names."""
        with self._lock:
            self._gauges.setdefault(
                name, _StageStat(self._keep)).record(float(value))

    def summary(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            out = {}
            for name, s in self._stats.items():
                if not s.count:
                    continue
                out[name] = {
                    "count": s.count,
                    "total_s": s.total,
                    "avg_s": s.total / s.count,
                    "max_s": s.max,
                    "min_s": s.min,
                    "top10_avg_s": (sum(s.top) / len(s.top)
                                    if s.top else 0.0),
                }
                if s.samples:
                    ordered = sorted(s.samples)
                    out[name]["p50_s"] = ordered[len(ordered) // 2]
                    out[name]["p99_s"] = ordered[
                        min(len(ordered) - 1, int(len(ordered) * 0.99))]
            gauges = {}
            for name, s in self._gauges.items():
                if not s.count:
                    continue
                gauges[name] = {
                    "count": s.count,
                    "avg": s.total / s.count,
                    "max": s.max,
                    "min": s.min,
                }
                if s.samples:
                    ordered = sorted(s.samples)
                    gauges[name]["p50"] = ordered[len(ordered) // 2]
                    gauges[name]["p99"] = ordered[
                        min(len(ordered) - 1, int(len(ordered) * 0.99))]
            if gauges:
                out["gauges"] = gauges
            return out

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._gauges.clear()
