"""Serving resilience: supervised workers, request ledger, breaker.

The reference platform's Cluster Serving inherited its recovery story
from the execution engines underneath it -- Spark's driver re-schedules
a failed task, Flink restarts an operator from its checkpoint
(PAPER.md, arXiv:1804.05839). This stack owns its threads, so the
recovery machinery has to live here:

- :class:`Supervisor` -- owns a :class:`~.worker.ServingWorker`'s
  lifecycle: detects *death* (the serving thread exited while its stop
  event was never set) and *wedge* (heartbeat stale beyond
  ``zoo.serving.supervisor.heartbeat_timeout_s`` while the thread is
  still alive), then restarts the engine with capped exponential
  backoff + seeded jitter. Requests the dead run had pulled but not
  answered are re-queued from the :class:`RequestLedger` **exactly
  once** per request id; a request whose re-run also dies gets one
  structured error reply instead of a third run -- so every admitted
  request produces exactly one reply (result or error), never zero,
  and duplicates are confined to the wedge case (an abandoned thread
  that wakes mid-push cannot be un-scheduled; crash recovery is
  exactly-once because a dead thread pushes nothing).
- :class:`RequestLedger` -- uri -> wire-blob for every request decoded
  but not yet answered. The worker records at decode and settles on
  reply (both are one dict op per request); the supervisor drains it
  on restart. Bounded: beyond ``max_entries`` the oldest entries are
  dropped from requeue coverage (never from serving).
- :class:`CircuitBreaker` -- around backend dispatch: ``threshold``
  consecutive predict failures open it (dispatches fast-fail with a
  structured error instead of burning a device slot), after
  ``cooldown_s`` one half-open probe is allowed through; the probe's
  finalize-time success closes the breaker, its failure re-opens it.
  State transitions emit ``circuit_open`` / ``circuit_half_open`` /
  ``circuit_closed`` events and keep the per-state metrics current.

Everything here is opt-in at the worker level (a bare ``ServingWorker``
has ``ledger is None`` / ``breaker is None`` and pays nothing); the
launcher wires the Supervisor by default
(``zoo.serving.supervisor.enabled``).
"""

from __future__ import annotations

import collections
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.obs.events import emit as emit_event
from analytics_zoo_tpu.obs.metrics import get_registry

logger = get_logger(__name__)

_REG = get_registry()
_M_RESTARTS = _REG.counter(
    "zoo_serving_worker_restarts_total",
    "Supervisor restarts of the serving worker, by reason",
    labelnames=("reason",))
_M_REQUEUED = _REG.counter(
    "zoo_serving_requeued_total",
    "In-flight requests re-queued by the supervisor after a restart")
_M_BREAKER_STATE = _REG.gauge(
    "zoo_serving_breaker_state_info",
    "Circuit-breaker state (0 = closed, 1 = half-open, 2 = open)")
_M_BREAKER_TRANSITIONS = _REG.counter(
    "zoo_serving_breaker_transitions_total",
    "Circuit-breaker state transitions, by state entered",
    labelnames=("state",))
_M_BREAKER_REJECTED = _REG.counter(
    "zoo_serving_breaker_rejected_total",
    "Requests fast-failed while the circuit breaker was open")


class RequestLedger:
    """uri -> wire blob for decoded-but-unanswered requests.

    ``record`` overwrites (a re-queued request decodes again),
    ``settle`` is idempotent, and :meth:`take_for_requeue` implements
    the exactly-once policy: the first drain returns an entry for
    re-queueing and remembers it; a second drain (the re-run died too)
    returns it as *dead* -- the caller answers it with a structured
    error and it leaves the ledger for good.

    The record/settle pairing is the runtime half of the exactly-once
    contract; zoolint's lifecycle engine is the static half -- worker
    stage methods declared in ``ZOOLINT_REPLY_OBLIGATED`` are proven
    to reach exactly one of {reply, error-reply, requeue, handoff} on
    every CFG path (``reply-missing-on-path`` /
    ``reply-duplicated-on-path``, docs/zoolint.md)."""

    def __init__(self, max_entries: int = 4096):
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[str, bytes]" = (
            collections.OrderedDict())
        self._requeued: set = set()
        self._max = int(max_entries)
        self.dropped = 0  # aged out of requeue coverage (bound)

    def record(self, uri: str, blob: bytes) -> None:
        with self._lock:
            self._entries[uri] = blob
            self._entries.move_to_end(uri)
            while len(self._entries) > self._max:
                self._entries.popitem(last=False)
                self.dropped += 1

    def settle(self, uris) -> None:
        with self._lock:
            for uri in uris:
                self._entries.pop(uri, None)
                self._requeued.discard(uri)

    def take_for_requeue(self
                         ) -> Tuple[List[Tuple[str, bytes]],
                                    List[Tuple[str, bytes]]]:
        """(fresh, dead): fresh entries are marked requeued and stay
        in the ledger (they will re-decode and settle on answer); dead
        entries (already requeued once) are removed -- the caller owes
        each one an error reply."""
        with self._lock:
            fresh = [(u, b) for u, b in self._entries.items()
                     if u not in self._requeued]
            dead = [(u, b) for u, b in self._entries.items()
                    if u in self._requeued]
            for u, _ in fresh:
                self._requeued.add(u)
            for u, _ in dead:
                self._entries.pop(u, None)
                self._requeued.discard(u)
        return fresh, dead

    def outstanding(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class CircuitBreaker:
    """Consecutive-failure breaker around backend dispatch.

    The worker calls :meth:`allow` before dispatching a batch,
    :meth:`record_failure` on a predict dispatch/fetch exception and
    :meth:`record_success` on a successful finalize. ``clock`` is
    injectable for deterministic tests."""

    CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
    _STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    def __init__(self, threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None, clock=None):
        cfg = get_config()
        self.threshold = int(cfg.get("zoo.serving.breaker.threshold", 5)
                             if threshold is None else threshold)
        self.cooldown_s = float(
            cfg.get("zoo.serving.breaker.cooldown_s", 5.0)
            if cooldown_s is None else cooldown_s)
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._probe_started = 0.0
        _M_BREAKER_STATE.set(0)

    # ------------------------------------------------------ transitions --
    def _enter(self, state: str) -> None:
        # under self._lock (callers hold it)
        self._state = state
        _M_BREAKER_STATE.set(self._STATE_GAUGE[state])
        _M_BREAKER_TRANSITIONS.labels(state=state).inc()

    def allow(self) -> bool:
        """May a batch dispatch right now? Open -> False (fast-fail);
        open past cooldown -> one half-open probe slips through."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if (self._clock() - self._opened_at
                        < self.cooldown_s):
                    return False
                self._enter(self.HALF_OPEN)
                self._probe_inflight = True
                self._probe_started = self._clock()
                emit_event("circuit_half_open", "serving")
                return True
            # HALF_OPEN: one probe at a time -- but a probe that never
            # reported back (its thread crashed, or it failed outside
            # the predict path, where record_* is never called) must
            # not wedge the breaker half-open forever: after another
            # cooldown the probe slot re-arms
            if (self._probe_inflight
                    and self._clock() - self._probe_started
                    < self.cooldown_s):
                return False
            self._probe_inflight = True
            self._probe_started = self._clock()
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != self.CLOSED:
                self._enter(self.CLOSED)
                emit_event("circuit_closed", "serving")
                logger.info("circuit breaker closed (probe succeeded)")

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probe_inflight = False
            tripped = (self._state == self.HALF_OPEN
                       or (self._state == self.CLOSED
                           and self._failures >= self.threshold))
            if tripped and self._state != self.OPEN:
                self._enter(self.OPEN)
                self._opened_at = self._clock()
                emit_event("circuit_open", "serving",
                           failures=self._failures)
                logger.warning(
                    "circuit breaker OPEN after %d consecutive "
                    "backend failures; dispatch suspended for %.1fs",
                    self._failures, self.cooldown_s)

    def rejected(self, n: int = 1) -> None:
        """Account ``n`` requests fast-failed while open."""
        _M_BREAKER_REJECTED.inc(n)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"state": self._state, "failures": self._failures,
                    "threshold": self.threshold,
                    "cooldown_s": self.cooldown_s}


class Supervisor:
    """Watches one ServingWorker and restarts it on death or wedge.

    Death: the serving thread exited but its stop event was never set
    (an uncaught exception killed it -- ``worker_crash`` in the event
    log). Wedge: the thread is alive but ``worker.heartbeat`` has not
    moved for ``heartbeat_timeout_s`` (a stage stuck in a syscall, a
    backend hang). Either way: emit ``worker_restart``, re-queue the
    ledger's outstanding requests (exactly once each; twice-crashed
    requests get one error reply), back off with capped exponential +
    seeded jitter, and start a fresh engine run. The worker's stop
    event is per-run, so an abandoned wedged thread that later wakes
    finds *its* event set and exits instead of double-serving.

    Restart supervision only ever touches the worker between runs; a
    healthy worker pays one attribute read per poll interval."""

    def __init__(self, worker, poll_interval_s: Optional[float] = None,
                 heartbeat_timeout_s: Optional[float] = None,
                 backoff_base_s: Optional[float] = None,
                 backoff_max_s: Optional[float] = None,
                 max_restarts: Optional[int] = None,
                 seed: int = 0, requeue: bool = True):
        cfg = get_config()
        self.worker = worker
        self.poll_interval_s = float(
            cfg.get("zoo.serving.supervisor.poll_interval_s", 0.5)
            if poll_interval_s is None else poll_interval_s)
        self.heartbeat_timeout_s = float(
            cfg.get("zoo.serving.supervisor.heartbeat_timeout_s", 30.0)
            if heartbeat_timeout_s is None else heartbeat_timeout_s)
        self.backoff_base_s = float(
            cfg.get("zoo.serving.supervisor.backoff_base_s", 0.1)
            if backoff_base_s is None else backoff_base_s)
        self.backoff_max_s = float(
            cfg.get("zoo.serving.supervisor.backoff_max_s", 30.0)
            if backoff_max_s is None else backoff_max_s)
        self.max_restarts = int(
            cfg.get("zoo.serving.supervisor.max_restarts", 0)
            if max_restarts is None else max_restarts)
        self.ledger: Optional[RequestLedger] = None
        if requeue:
            self.ledger = RequestLedger()
            worker.ledger = self.ledger
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.restarts = 0

    # -------------------------------------------------------- lifecycle --
    def start(self) -> "Supervisor":
        self._stop.clear()
        self._thread = threading.Thread(target=self._monitor,
                                        daemon=True,
                                        name="serving-supervisor")
        self._thread.start()
        return self

    def stop(self, join_timeout: float = 5.0) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(join_timeout)
            self._thread = None
        if self.ledger is not None and self.worker.ledger is self.ledger:
            self.worker.ledger = None

    # ---------------------------------------------------------- monitor --
    def _monitor(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                reason = self._diagnose()
            except Exception as e:  # a probe bug must not kill the
                logger.exception(   # supervisor itself
                    "supervisor probe failed: %s", e)
                continue
            if reason is None:
                continue
            try:
                self._restart(reason)
            except Exception as e:
                logger.exception("supervisor restart failed: %s", e)

    def _diagnose(self) -> Optional[str]:
        """"crashed" / "wedged" / None (healthy, stopped, or never
        started)."""
        worker = self.worker
        thread = getattr(worker, "_thread", None)
        if thread is None:
            return None  # not running (or operator stopped it)
        if worker._stop.is_set():
            return None  # orderly shutdown in progress
        drain = getattr(worker, "_drain", None)
        if drain is not None and drain.is_set():
            # graceful drain (ISSUE-9): the run exiting with its stop
            # event unset is the POINT, not a crash to restart
            return None
        if not thread.is_alive():
            return "crashed"
        now = time.monotonic()
        hb = getattr(worker, "heartbeat", None)
        if (hb is not None
                and now - hb > self.heartbeat_timeout_s):
            return "wedged"
        # the decode stage heartbeats separately (None = no decode
        # thread running): a pull stuck in a hung broker recv starves
        # the engine without ever staling the driver's heartbeat
        hb_decode = getattr(worker, "heartbeat_decode", None)
        if (hb_decode is not None
                and now - hb_decode > self.heartbeat_timeout_s):
            return "wedged"
        return None

    # ---------------------------------------------------------- restart --
    def _restart(self, reason: str) -> None:
        if self.max_restarts and self.restarts >= self.max_restarts:
            emit_event("supervisor_giveup", "serving",
                       restarts=self.restarts)
            logger.error("supervisor giving up after %d restarts; "
                         "worker stays down", self.restarts)
            # the final run's in-flight requests still get their one
            # structured error reply -- giving up on the WORKER must
            # not strand its CLIENTS waiting on timeouts
            self._flush_ledger_with_errors(
                "request failed: serving worker gave up after "
                f"{self.restarts} restarts")
            self._stop.set()
            return
        self.restarts += 1
        backoff = min(self.backoff_max_s,
                      self.backoff_base_s * (2 ** (self.restarts - 1)))
        backoff *= 0.5 + self._rng.random() * 0.5  # jitter: no
        # thundering herd when N hosts restart off the same outage
        _M_RESTARTS.labels(reason=reason).inc()
        # reap the old run: for a crash the thread is already dead and
        # stop() just joins + flushes; for a wedge it times out and we
        # abandon the thread -- its per-run stop event is now set, so
        # if it ever wakes it exits instead of double-serving
        self.worker.stop(join_timeout=1.0)
        self.worker._thread = None
        self.worker._inflight.clear()  # stale sync-engine records
        requeued = self._requeue()
        emit_event("worker_restart", "serving", reason=reason,
                   restarts=self.restarts,
                   backoff_s=round(backoff, 4), requeued=requeued)
        logger.warning("supervisor restarting %s serving worker "
                       "(restart #%d, backoff %.3fs, %d requests "
                       "re-queued)", reason, self.restarts, backoff,
                       requeued)
        if self._stop.wait(backoff):
            return  # supervisor stopped during backoff
        self.worker.start()

    def _requeue(self) -> int:
        """Drain the ledger: fresh entries go back on the input queue
        (once per request id), twice-crashed entries get one error
        reply. Returns the requeued count."""
        if self.ledger is None:
            return 0
        fresh, dead = self.ledger.take_for_requeue()
        # consumer-group input (the fleet data plane): the BROKER
        # still owns the dead run's claims -- they re-deliver via
        # XAUTOCLAIM after the idle threshold. A local re-put here
        # would add a second copy of each entry and race the reclaim
        # into duplicate replies, so ownership stays with the broker;
        # the ledger still marks them (a second crash during the
        # re-serve takes the one-error-reply exit as before).
        broker_owned = getattr(self.worker, "_acker", None) is not None
        requeued = 0
        for uri, blob in fresh:
            if broker_owned:
                continue
            try:
                ok = self.worker._in.put(blob)
            except Exception as e:
                logger.warning("requeue of %s failed: %s", uri, e)
                ok = False
            if ok:
                requeued += 1
            else:
                self._reply_error(uri, blob,
                                  "request lost: re-queue failed "
                                  "during worker restart")
        for uri, blob in dead:
            self._reply_error(uri, blob,
                              "request failed: worker died twice "
                              "while serving it")
        if requeued:
            _M_REQUEUED.inc(requeued)
        return requeued

    def _flush_ledger_with_errors(self, message: str) -> None:
        """Answer every outstanding ledger entry with one structured
        error (the give-up path: no further run will serve them)."""
        if self.ledger is None:
            return
        fresh, dead = self.ledger.take_for_requeue()
        for uri, blob in fresh + dead:
            self._reply_error(uri, blob, message)
        self.ledger.settle([u for u, _ in fresh])

    def _reply_error(self, uri: str, blob: bytes, message: str) -> None:
        from analytics_zoo_tpu.serving.queues import _decode_request

        try:
            reply = _decode_request(blob)[2]
        except Exception:
            reply = None  # undecodable blob: default result stream
        try:
            self.worker._push_error(uri, reply, message)
        except Exception as e:
            logger.warning("error reply for %s failed: %s", uri, e)

    def stats(self) -> Dict[str, Any]:
        return {"restarts": self.restarts,
                "outstanding": (len(self.ledger)
                                if self.ledger is not None else 0),
                "max_restarts": self.max_restarts}
