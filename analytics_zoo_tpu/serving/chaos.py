"""Deterministic fault injection for the serving data plane.

The resilience layer (serving/resilience.py) is only trustworthy if it
is *exercised*: BigDL inherited fault tolerance from Spark re-running
failed tasks (arXiv:1804.05839) and could lean on that machinery's own
test surface; our TPU-native engine owns its threads, so this module is
the crash lab -- seeded injectors wired behind the exact seams the
Supervisor watches, so tier-1 tests can kill the dispatch thread
mid-batch on the Nth call and assert full recovery, every run, same
schedule.

Seams (one ``chaos_point(seam)`` call per *batch*, never per request,
so the disabled path costs one global read + ``is None`` check):

========  ====================================================
seam      where it fires
========  ====================================================
pull      top of ``AdaptiveBatcher.next_batch`` (queue stall)
decode    top of ``ServingWorker._decode_stage``
dispatch  top of ``ServingWorker._dispatch_group``
finalize  top of ``ServingWorker._finalize_record``
push      result push (returns True = drop this reply)
replica   fleet controller, once per routed/observed result
          (returns True = SIGKILL a whole replica process)
========  ====================================================

Injector kinds:

- ``crash``: raise :class:`ChaosCrash` (a ``BaseException`` -- it
  escapes the worker's per-batch ``except Exception`` guards and kills
  the stage thread, the way a real segfaulting callback or interpreter
  error would);
- ``error``: raise :class:`ChaosError` (an ``Exception`` -- exercises
  the per-request error mapping, not supervision);
- ``sleep``: block the stage for ``dur`` seconds (wedge / slow
  backend / queue stall depending on the seam);
- ``drop``: at the ``push`` seam, swallow the reply (lost-result
  path; clients observe a timeout);
- ``kill``: at the ``replica`` seam only (ISSUE-9) -- tells the fleet
  controller to SIGKILL one whole replica process mid-run, the
  process-granular fault PR 5's in-process harness could not model.

Spec grammar (``zoo.serving.chaos.spec``, entries ``;``-separated)::

    kind:seam[:key=val]*
    crash:dispatch:at=3          # the 3rd dispatch, exactly once
    sleep:decode:every=5:dur=0.2 # every 5th decode stalls 200 ms
    error:finalize:p=0.05        # 5% of finalizes, seeded RNG
    drop:push:p=0.01

Triggers: ``at=N`` fires on exactly the Nth call at that seam (once,
counters are process-lifetime so restarts don't reset the schedule);
``every=N`` fires on every Nth call; ``p=F`` fires with probability F
from the injector's seeded RNG. Gated by ``zoo.serving.chaos.enabled``
(default false) + ``zoo.serving.chaos.seed``; tests install an
injector programmatically with :func:`install`.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.obs.events import emit as emit_event
from analytics_zoo_tpu.obs.metrics import get_registry

logger = get_logger(__name__)

_M_INJECTED = get_registry().counter(
    "zoo_serving_chaos_injected_total",
    "Chaos faults injected, by seam and kind",
    labelnames=("seam", "kind"))

SEAMS = ("pull", "decode", "dispatch", "finalize", "push", "replica")
KINDS = ("crash", "error", "sleep", "drop", "kill")


class ChaosError(Exception):
    """Injected *recoverable* fault: subclasses Exception so the
    worker's per-batch guards map it to per-request error replies."""


class ChaosCrash(BaseException):
    """Injected *fatal* fault: subclasses BaseException so it escapes
    every ``except Exception`` guard and kills the stage thread -- the
    seam the Supervisor exists to cover."""


class ChaosRule:
    """One parsed spec entry; see the module docstring grammar."""

    def __init__(self, kind: str, seam: str, at: Optional[int] = None,
                 every: Optional[int] = None, p: float = 0.0,
                 dur: float = 0.1):
        if kind not in KINDS:
            raise ValueError(f"unknown chaos kind {kind!r} "
                             f"(one of {', '.join(KINDS)})")
        if seam not in SEAMS:
            raise ValueError(f"unknown chaos seam {seam!r} "
                             f"(one of {', '.join(SEAMS)})")
        if kind == "drop" and seam != "push":
            raise ValueError("drop rules only apply to the push seam")
        # replica-level chaos (ISSUE-9) is process-granular: only the
        # fleet controller can act on it, and in-process kinds make no
        # sense there -- the pairing is exclusive both ways
        if (kind == "kill") != (seam == "replica"):
            raise ValueError(
                "kill rules pair exclusively with the replica seam "
                "(kill:replica:at=N -- the fleet controller SIGKILLs "
                "a whole replica process)")
        self.kind = kind
        self.seam = seam
        self.at = at
        self.every = every
        self.p = float(p)
        self.dur = float(dur)

    def __repr__(self):
        return (f"ChaosRule({self.kind}:{self.seam} at={self.at} "
                f"every={self.every} p={self.p} dur={self.dur})")


def parse_spec(spec: str) -> List[ChaosRule]:
    """``"crash:dispatch:at=3;sleep:decode:p=0.1:dur=0.2"`` -> rules.
    Raises ValueError on malformed entries -- a typo'd chaos schedule
    silently injecting nothing would vacuously pass every drill."""
    rules: List[ChaosRule] = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2:
            raise ValueError(f"chaos spec entry {entry!r} needs at "
                             "least kind:seam")
        kwargs: Dict[str, float] = {}
        for kv in parts[2:]:
            key, sep, val = kv.partition("=")
            if not sep or key not in ("at", "every", "p", "dur"):
                raise ValueError(
                    f"chaos spec entry {entry!r}: bad trigger {kv!r} "
                    "(keys: at=, every=, p=, dur=)")
            kwargs[key] = (int(val) if key in ("at", "every")
                           else float(val))
        rules.append(ChaosRule(parts[0], parts[1], **kwargs))
    return rules


class ChaosInjector:
    """Seeded rule engine behind :func:`chaos_point`.

    Counters are per-seam and process-lifetime (a supervisor restart
    must not reset the schedule -- "crash the 2nd dispatch" has to
    mean the 2nd dispatch *ever*, or a crash-loop drill would re-crash
    forever). ``fire`` is thread-safe; the RNG draw order is
    deterministic per seam because each seam is only called from its
    own stage thread."""

    def __init__(self, rules: List[ChaosRule], seed: int = 0):
        self.rules = list(rules)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._calls: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}

    def fire(self, seam: str) -> bool:
        """Evaluate every rule on ``seam``; returns True when a reply
        should be dropped (push seam). May raise ChaosError/ChaosCrash
        or sleep, per the matching rule."""
        drop = False
        actions = []
        with self._lock:
            n = self._calls.get(seam, 0) + 1
            self._calls[seam] = n
            for rule in self.rules:
                if rule.seam != seam:
                    continue
                hit = ((rule.at is not None and n == rule.at)
                       or (rule.every is not None
                           and n % rule.every == 0)
                       or (rule.p > 0.0
                           and self._rng.random() < rule.p))
                if hit:
                    actions.append(rule)
                    self._fired[f"{seam}:{rule.kind}"] = (
                        self._fired.get(f"{seam}:{rule.kind}", 0) + 1)
        for rule in actions:  # act OUTSIDE the lock: sleeps/raises
            _M_INJECTED.labels(seam=seam, kind=rule.kind).inc()
            emit_event("chaos_injected", "serving", seam=seam,
                       kind=rule.kind)
            logger.warning("chaos: injecting %s at %s (call %d)",
                           rule.kind, seam, n)
            if rule.kind == "sleep":
                time.sleep(rule.dur)
            elif rule.kind == "error":
                raise ChaosError(f"chaos: injected error at {seam} "
                                 f"(call {n})")
            elif rule.kind == "crash":
                raise ChaosCrash(f"chaos: injected crash at {seam} "
                                 f"(call {n})")
            elif rule.kind in ("drop", "kill"):
                # both are act-by-return-value kinds: the caller knows
                # its seam -- push drops the reply it was about to
                # send, the fleet controller SIGKILLs a replica
                drop = True
        return drop

    def counts(self) -> Dict[str, int]:
        """{"<seam>:<kind>": fired} -- what actually triggered (soak
        driver summary + test assertions)."""
        with self._lock:
            return dict(self._fired)


_injector: Optional[ChaosInjector] = None


def install(injector: ChaosInjector) -> ChaosInjector:
    """Arm the process-wide injector (tests, soak driver)."""
    global _injector
    _injector = injector
    return injector


def uninstall() -> None:
    global _injector
    _injector = None


def get_injector() -> Optional[ChaosInjector]:
    return _injector


def maybe_install_from_config() -> Optional[ChaosInjector]:
    """Arm from ``zoo.serving.chaos.*`` when enabled (the launcher
    calls this); returns the injector or None. An armed injector is
    left alone -- a test's programmatic install wins."""
    if _injector is not None:
        return _injector
    cfg = get_config()
    if not bool(cfg.get("zoo.serving.chaos.enabled", False)):
        return None
    rules = parse_spec(str(cfg.get("zoo.serving.chaos.spec", "")))
    if not rules:
        logger.warning("zoo.serving.chaos.enabled is set but the spec "
                       "is empty; nothing will be injected")
    return install(ChaosInjector(
        rules, seed=int(cfg.get("zoo.serving.chaos.seed", 0))))


def chaos_point(seam: str) -> bool:
    """The seam hook. One global read + None check when chaos is off
    (the always-on cost of being injectable); returns True when the
    caller should drop the reply it was about to push."""
    inj = _injector
    if inj is None:
        return False
    return inj.fire(seam)
