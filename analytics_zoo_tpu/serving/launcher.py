"""Config-driven serving launcher.

The analog of the reference's YAML-configured serving deployment
(ref: scripts/cluster-serving/config.yaml parsed by
zoo/.../serving/utils/ClusterServingHelper.scala; job lifecycle in
ClusterServingManager). One YAML describes the model, the queue, the
batching params and the HTTP frontend; ``launch(config)`` (or
``python -m analytics_zoo_tpu.serving.launcher -c config.yaml``)
assembles InferenceModel + ServingWorker + HttpFrontend and runs until
stopped.

Config schema (defaults in parentheses)::

    model:
      path: /path/to/saved_zoo_model     # ZooModel.save_model dir
      encrypted: false                   # load_encrypted_zoo
      secret: null                       #   its AES secret
    data:
      queue: memory | dir | tcp://host:port | redis://host:port (memory)
      path: null                         # dir-queue directory, or
                                         # host:port when queue: tcp/redis
      maxlen: 10000
      group: serving                     # redis: consumer-group name --
      consumer: null                     #   N replicas sharing a group
                                         #   shard the stream; consumer
                                         #   names this member's claims
      stream: serving_stream             # redis: request stream
      result_stream: result_stream       # redis: worker default output
    params:
      batch_size: 8                      # base micro-batch cap (core_number)
      timeout_ms: 5.0                    # max linger per batch
      min_timeout_ms: 1.0                # adaptive linger floor (shallow queue)
      max_batch_size: 0                  # backlog growth cap (0 = 4x batch_size
                                         # bucket); growth stays on the ladder
      top_n: null                        # classes/scores of top-N
      pipelined: null                    # null = zoo.serving.pipeline.enabled;
                                         # false restores the synchronous engine
      pipeline_depth: 2                  # in-flight predict batches
                                         # (1 disables overlap)
      warm_batch_sizes: [1, 8]           # pre-compiled buckets (uses the
                                         # model's example input)
    http:
      enabled: true
      host: 127.0.0.1
      port: 0                            # 0 = pick a free port
      certfile: null                     # both set -> HTTPS (ref:
      keyfile: null                      #   FrontEndApp https options)
    generation:                          # token streaming (ISSUE-10);
      enabled: true                      #   presence enables it. With
      model:                             #   no model: block the app
        vocab: 64                        #   serves generation ONLY.
        dim: 32                          # GenModelConfig fields (the
        heads: 2                         #   seeded builtin LM)
        head_dim: 16
        layers: 2
        seed: 0
      stream: generation_stream          # brokered request stream
      slots: null                        # null = zoo.generation.*
      page_size: null                    #   defaults; per-launch
      num_pages: null                    #   overrides otherwise
      max_len: null
      max_tokens: null                   # default new-token budget
      eos: null                          # default stop token id
      stream_chunk_tokens: null          # tokens per streamed chunk
      role: unified                      # unified | prefill | decode
                                         #   (ISSUE-20): prefill admits
                                         #   + prefills, hands streams
                                         #   to the decode pool over
                                         #   the broker handoff stream;
                                         #   decode consumes ONLY that
                                         #   stream. Non-unified roles
                                         #   need data.queue redis://
      handoff_stream: generation_handoff_stream

``queue: tcp://...`` points every host's worker at one TcpQueueServer
broker -- the cross-host data plane (the reference's Redis role): run N
workers on N hosts against the same broker address. ``queue:
redis://...`` is the FLEET data plane (ISSUE-9): the worker becomes
one consumer-group member on a stream broker (redis_adapter stream
mode) -- claims are acked on reply and a dead member's claims are
reclaimed by survivors (serving/fleet.py drives N such deployments).

With ``http.enabled`` the frontend OWNS the result stream (its router
consumes every worker result, HttpFrontend's contract) -- direct queue
clients should deploy with ``http.enabled: false`` and read
``app.output_queue`` themselves.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import threading
import time
from typing import Any, Dict, Optional

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.inference.inference_model import InferenceModel
from analytics_zoo_tpu.obs.events import emit as emit_event
from analytics_zoo_tpu.obs.metrics import get_registry
from analytics_zoo_tpu.serving.http_frontend import HttpFrontend
from analytics_zoo_tpu.serving.queues import InputQueue, OutputQueue
from analytics_zoo_tpu.serving.worker import ServingWorker

logger = get_logger(__name__)

_M_DRAIN = get_registry().histogram(
    "zoo_serving_drain_duration_seconds",
    "Graceful-drain wait: from drain_begin until the engine finished "
    "its in-flight work (or the deadline expired)")


class ServingApp:
    """A running serving deployment: model + worker + optional HTTP.

    With a ``generation:`` config block the deployment also (or, when
    ``model:`` is omitted, *only*) hosts a
    :class:`~analytics_zoo_tpu.serving.generation.worker.GenerationWorker`
    -- same supervisor, drain, chaos and fleet seams as the predict
    worker, one frontend serving both ``/predict`` and ``/generate``.
    """

    def __init__(self, model: Optional[InferenceModel],
                 worker: Optional[ServingWorker],
                 input_queue: InputQueue, output_queue: OutputQueue,
                 frontend: Optional[HttpFrontend],
                 redis_frontend=None, reporter=None, supervisor=None,
                 gen_worker=None, gen_supervisor=None,
                 gen_input_queue=None):
        self.model = model
        self.worker = worker
        self.input_queue = input_queue
        self.output_queue = output_queue
        self.frontend = frontend
        self.redis_frontend = redis_frontend
        self.reporter = reporter
        self.supervisor = supervisor
        self.gen_worker = gen_worker
        self.gen_supervisor = gen_supervisor
        self.gen_input_queue = gen_input_queue

    @property
    def address(self) -> Optional[str]:
        return self.frontend.address if self.frontend else None

    def drain(self, deadline_ms: Optional[float] = None) -> bool:
        """Graceful drain (ISSUE-9): refuse new work, finish what is
        already in flight, within ``zoo.serving.drain.deadline_ms``.
        The SIGTERM handler and each rolling-restart step run this
        before ``stop()``; returns True when the engine fully drained
        inside the budget. Safe to call once per app."""
        if deadline_ms is None:
            deadline_ms = float(get_config().get(
                "zoo.serving.drain.deadline_ms", 10000.0))
        emit_event("drain_begin", "serving", deadline_ms=deadline_ms)
        t0 = time.monotonic()
        # supervisors first: a draining worker's thread exits with its
        # stop event unset, which must not read as a crash to restart
        if self.supervisor is not None:
            self.supervisor.stop()
        if self.gen_supervisor is not None:
            self.gen_supervisor.stop()
        if self.frontend is not None:
            # health goes 503 "draining" -> the fleet router (and any
            # LB honoring /healthz) stops sending traffic here; new
            # direct /predicts get a structured 503 + Retry-After
            self.frontend.set_draining()
        ok = True
        if self.worker is not None:
            ok = self.worker.drain(deadline_s=deadline_ms / 1000.0)
        if self.gen_worker is not None:
            # in-flight token STREAMS finish too: the generation drain
            # admits nothing new and steps until every live slot
            # reached its terminal chunk (each plane gets the full
            # budget -- they drain concurrently-started work, not a
            # shared quantity)
            ok = self.gen_worker.drain(
                deadline_s=deadline_ms / 1000.0) and ok
        waited = time.monotonic() - t0
        _M_DRAIN.observe(waited)
        emit_event("drain_complete", "serving", ok=ok,
                   waited_s=round(waited, 3))
        if not ok:
            logger.warning(
                "drain deadline (%.0f ms) expired with in-flight work "
                "remaining; stop() will cut it loose", deadline_ms)
        return ok

    def stop(self) -> None:
        # supervisors FIRST: they exist to restart a stopping worker,
        # which is exactly what an orderly shutdown must not fight
        if self.supervisor is not None:
            self.supervisor.stop()
        if self.gen_supervisor is not None:
            self.gen_supervisor.stop()
        if self.frontend is not None:
            self.frontend.stop()
        if self.redis_frontend is not None:
            self.redis_frontend.stop()
        if self.worker is not None:
            self.worker.stop()
        if self.gen_worker is not None:
            self.gen_worker.stop()
        if self.reporter is not None:
            self.reporter.stop()
        emit_event("serving_stop", "serving")
        logger.info("serving stopped")


def _load_model(cfg: Dict[str, Any]) -> InferenceModel:
    mcfg = cfg.get("model") or {}
    path = mcfg.get("path")
    if not path:
        raise ValueError("config needs model.path")
    model = InferenceModel()
    if mcfg.get("encrypted"):
        secret = mcfg.get("secret")
        if not secret:
            raise ValueError("model.encrypted needs model.secret")
        model.load_encrypted_zoo(path, secret)
    else:
        model.load_zoo(path)
    return model


def launch(config: Dict[str, Any], model: Any = None) -> ServingApp:
    """Assemble and start a deployment from a parsed config dict.

    ``model`` injects a pre-built model object instead of loading one
    from ``model.path`` -- the population path (ISSUE-13): a
    :class:`~analytics_zoo_tpu.inference.population.
    PopulationInferenceModel` is built in-process from a trained
    ``PopulationEstimator`` (``from_estimator``), not from a saved
    directory, and rides the same worker / drain / supervisor /
    frontend assembly as a loaded ``InferenceModel``. Any object
    honoring the ``predict_async(x) -> (outputs, n)`` contract works.
    """
    # fail fast on a bad conf file / AZT_* env var: every spec'd
    # zoo.* key's resolved value is checked against the type/range
    # metadata (common.config._SPECS) before any thread starts
    from analytics_zoo_tpu.common.config import validate_config

    validate_config()
    # black box first: a deployment that dies during model load /
    # warm-up should already leave a postmortem bundle. Library-level
    # install (no signal hook -- launch() may run off the main thread);
    # main() adds the SIGTERM bundle.
    if get_config().get("zoo.obs.flight.enabled", True):
        from analytics_zoo_tpu.obs.flight import install_flight_recorder

        install_flight_recorder()
    # chaos drills arm BEFORE the worker exists so launch-time seams
    # are covered too (no-op unless zoo.serving.chaos.enabled)
    from analytics_zoo_tpu.serving.chaos import maybe_install_from_config

    maybe_install_from_config()
    # generation block (ISSUE-10): presence enables the token-
    # streaming data plane (unless `enabled: false`); a deployment may
    # host generation ONLY, in which case model.path is not required
    gen_cfg = dict(config.get("generation") or {})
    # PRESENCE of the block enables the plane (a bare `generation:`
    # with every sub-key defaulted is valid), `enabled: false` opts out
    gen_enabled = ("generation" in config
                   and bool(gen_cfg.get("enabled", True)))
    if model is None:
        model = (None if gen_enabled and not config.get("model")
                 else _load_model(config))
    data = config.get("data") or {}
    params = config.get("params") or {}
    http = config.get("http") or {}

    # mesh routing (inference/sharded.py): an optional `shard:` YAML
    # block rides into plan resolution as PER-LAUNCH overrides of the
    # zoo.serving.shard.* keys -- never written into the process-global
    # config, so a later launch() in this process cannot inherit this
    # deployment's sharding. The resolved plan attaches BEFORE warm-up
    # so the bucket ladder compiles under the active mesh.
    shard_cfg = config.get("shard") or {}
    _shard_yaml_keys = {
        "mode": "zoo.serving.shard.mode",
        "recipe": "zoo.serving.shard.recipe",
        "quantized_collectives":
            "zoo.serving.shard.quantized_collectives",
        "devices": "zoo.serving.shard.devices",
    }
    from analytics_zoo_tpu.common.config import validate_config_value

    # set() is deliberately permissive and validate_config() already
    # ran above -- values arriving through the shard block must pass
    # the same launch-time spec check or the fail-fast guarantee has a
    # YAML-shaped hole
    shard_overrides = {
        cfg_key: validate_config_value(cfg_key, shard_cfg[yaml_key])
        for yaml_key, cfg_key in _shard_yaml_keys.items()
        if yaml_key in shard_cfg}
    from analytics_zoo_tpu.inference.sharded import (
        maybe_shard_from_config)

    shard_plan = (maybe_shard_from_config(model,
                                          overrides=shard_overrides)
                  if model is not None else None)

    if data.get("queue") == "dir" and not data.get("path"):
        raise ValueError('data.queue "dir" needs data.path')
    queue_kind = data.get("queue")
    # set only by the redis branch: the frontend drains its own reply
    # stream instead of the worker's default output queue
    frontend_out_q: Optional[OutputQueue] = None
    if queue_kind == "tcp":  # docstring form: queue: tcp + path: host:port
        if not data.get("path"):
            raise ValueError('data.queue "tcp" needs data.path '
                             '"host:port"')
        queue_kind = "tcp://" + str(data["path"])
    if queue_kind == "redis":  # same form: queue: redis + path: host:port
        if not data.get("path"):
            raise ValueError('data.queue "redis" needs data.path '
                             '"host:port"')
        queue_kind = "redis://" + str(data["path"])
    if isinstance(queue_kind, str) and queue_kind.startswith("redis://"):
        # fleet data plane (ISSUE-9): this deployment is ONE consumer-
        # group member on a shared stream broker (redis_adapter in
        # stream mode) -- N replicas with the same data.group shard
        # the stream; per-replica data.consumer names the PEL owner so
        # a dead replica's claims are reclaimable
        group = str(data.get("group", "serving"))
        consumer = str(data.get("consumer") or f"replica-{os.getpid()}")
        # remote replicas (ISSUE-20): the broker may live on another
        # host and may still be binding when the controller spawns us.
        # Probe it with capped backoff BEFORE building queues -- a
        # replica that cannot reach its data plane should die loudly
        # (controller sees the exit, backs off) rather than wedge in
        # a connect loop that looks like a slow start.
        from analytics_zoo_tpu.serving.redis_adapter import wait_broker

        if not wait_broker(queue_kind[len("redis://"):]):
            raise RuntimeError(
                f"fleet broker unreachable at {queue_kind} (see "
                "broker_unreachable event); refusing to start")
        in_q = InputQueue(backend=queue_kind,
                          name=str(data.get("stream", "serving_stream")),
                          group=group, consumer=consumer)
        # the worker's DEFAULT output is the broker's shared result
        # stream (the controller's drain consumes it into the
        # KEYS/HGETALL result table) -- direct stream clients get
        # their answers there no matter which replica served them
        out_q = OutputQueue(
            backend=queue_kind,
            name=str(data.get("result_stream", "result_stream")))
        if http.get("enabled", True):
            # this replica's frontend owns its own reply stream on
            # the broker (its requests carry it as reply-to, the
            # worker's _reply_backend routes results there) -- unlike
            # the tcp branch, the frontend drains ONLY that stream,
            # so direct stream traffic and HTTP traffic coexist on
            # one fleet. The name derives from the STABLE consumer
            # name, not a fresh uuid: a restarted replica re-attaches
            # to the same stream and drains what its predecessor left
            # behind -- a crash-looping replica must not mint an
            # orphaned stream (never consumed, never trimmed) per
            # cycle. Results for requests the dead frontend owned are
            # drained-and-dropped as abandoned, which is their fate
            # either way.
            reply = f"reply_{consumer}"
            in_q.reply_stream = reply
            frontend_out_q = OutputQueue(
                backend=queue_kind, name=reply,
                group=f"{reply}_g", consumer=consumer)
    elif isinstance(queue_kind, str) and queue_kind.startswith("tcp://"):
        in_q = InputQueue(backend=queue_kind)
        if http.get("enabled", True):
            # each deployment's frontend owns a UNIQUE result stream on
            # the broker (requests carry it as reply-to): N frontends
            # sharing one broker would otherwise race on one result
            # stream and drop each other's results
            import uuid as _uuid

            reply = f"result_{_uuid.uuid4().hex[:12]}"
            in_q.reply_stream = reply
            out_q = OutputQueue(backend=queue_kind, name=reply)
        else:
            out_q = OutputQueue(backend=queue_kind)
    else:
        # backend=None lets the queues module infer dir-backing from path
        in_q = InputQueue(backend=queue_kind,
                          path=data.get("path"),
                          maxlen=data.get("maxlen", 10000))
        out_q = OutputQueue(backend=queue_kind,
                            path=(data.get("path") + ".out"
                                  if data.get("path") else None))
    supervise = bool(
        get_config().get("zoo.serving.supervisor.enabled", True))
    worker = None
    supervisor = None
    if model is not None:
        worker = ServingWorker(
            model, in_q, out_q, batch_size=params.get("batch_size"),
            timeout_ms=params.get("timeout_ms"),
            top_n=params.get("top_n"),
            pipeline_depth=params.get("pipeline_depth"),
            pipelined=params.get("pipelined"),
            min_timeout_ms=params.get("min_timeout_ms"),
            max_batch_size=params.get("max_batch_size"))
        from analytics_zoo_tpu.inference.inference_model import (
            bucket_ladder)

        # default: every power-of-two bucket the batcher can emit --
        # up to its backlog GROWTH cap, not just the base size -- so
        # no request ever pays a live XLA compile, least of all at the
        # first backlog spike (exactly when a multi-second compile
        # stall hurts most). Cap growth-warming with
        # params.max_batch_size for deployments that cannot afford the
        # extra startup compiles.
        warm_cap = getattr(worker.batcher, "max_batch_size",
                           worker.batcher.batch_size)
        warm = params.get("warm_batch_sizes", bucket_ladder(warm_cap))
        if warm:
            warm_example = params.get(
                "warm_example", getattr(model, "example_input", None))
            if warm_example is not None:
                model.warm_up(warm_example, batch_sizes=tuple(warm))
            else:
                logger.warning(
                    "warm_batch_sizes set but no example input is "
                    "available; skipping warm-up")
        worker.start()
        if supervise:
            # the recovery story (ISSUE-5): restart a dead/wedged
            # worker with backoff, re-queue its in-flight requests
            # exactly once
            from analytics_zoo_tpu.serving.resilience import Supervisor

            supervisor = Supervisor(worker).start()
    gen_worker = None
    gen_supervisor = None
    gen_in = None
    frontend = None
    redis_fe = None
    reporter = None
    try:
        if gen_enabled:
            # generation data plane (ISSUE-10): its OWN request
            # stream (brokered backends shard it across fleet
            # replicas through the same consumer group as the predict
            # stream), the shared default result stream, and the same
            # supervisor/drain machinery as the predict worker
            from analytics_zoo_tpu.serving.generation.engine import (
                engine_from_config)
            from analytics_zoo_tpu.serving.generation.worker import (
                GenerationWorker)

            gen_stream = str(gen_cfg.get("stream", "generation_stream"))
            # disaggregated pools (ISSUE-20): a prefill replica admits
            # + prefills and hands each stream to the decode pool over
            # the broker's handoff stream; a decode replica consumes
            # ONLY that stream. The handoff stream is consumer-grouped
            # like the request stream, so a SIGKILLed decode replica's
            # unfinished handoffs are reclaimed by survivors.
            gen_role = str(gen_cfg.get("role", "unified"))
            handoff_stream = str(gen_cfg.get(
                "handoff_stream", "generation_handoff_stream"))
            handoff_out = None
            if gen_role != "unified" and not (
                    isinstance(queue_kind, str)
                    and queue_kind.startswith("redis://")):
                raise ValueError(
                    f"generation.role {gen_role!r} needs data.queue "
                    "redis:// -- the prefill->decode handoff stream "
                    "lives on the fleet broker")
            if isinstance(queue_kind, str) and (
                    queue_kind.startswith("tcp://")
                    or queue_kind.startswith("redis://")):
                if queue_kind.startswith("redis://"):
                    gen_group = str(data.get("group", "serving"))
                    gen_consumer = str(data.get("consumer")
                                       or f"replica-{os.getpid()}")
                    if gen_role == "decode":
                        # the decode pool shards the HANDOFF stream
                        # under its own group (prefill replicas share
                        # the request-stream group); a dead member's
                        # pending handoffs ride the PEL to a survivor
                        gen_in = InputQueue(
                            backend=queue_kind, name=handoff_stream,
                            group=f"{gen_group}_decode",
                            consumer=gen_consumer)
                    else:
                        gen_in = InputQueue(
                            backend=queue_kind, name=gen_stream,
                            group=gen_group, consumer=gen_consumer)
                    if gen_role in ("prefill", "decode"):
                        # prefill PUBLISHES handoffs; decode publishes
                        # too, at drain time, to move its live streams
                        # to a pool survivor before exiting
                        handoff_out = OutputQueue(
                            backend=queue_kind, name=handoff_stream)
                else:
                    gen_in = InputQueue(backend=queue_kind,
                                        name=gen_stream)
                # chunks route back to THIS frontend's reply stream,
                # exactly like predict results
                gen_in.reply_stream = in_q.reply_stream
            elif data.get("queue") == "dir" and data.get("path"):
                # cross-process spool deployments keep their contract:
                # a sibling spool directory, so external producers can
                # enqueue generate requests the same way they enqueue
                # predicts (a silent in-memory fallback would strand
                # them with no consumer)
                gen_in = InputQueue(backend="dir",
                                    path=str(data["path"]) + ".gen",
                                    maxlen=data.get("maxlen", 10000))
            else:
                gen_in = InputQueue(backend="memory",
                                    maxlen=data.get("maxlen", 10000))
            engine = engine_from_config(gen_cfg)
            # the generate path's warm-up contract: compile the whole
            # prefill ladder + the decode step before traffic, so a
            # launch mints zero storm-eligible compiles
            engine.warm_up()
            gen_worker = GenerationWorker(
                engine, gen_in, out_q,
                max_tokens=gen_cfg.get("max_tokens"),
                eos=gen_cfg.get("eos"),
                stream_chunk_tokens=gen_cfg.get(
                    "stream_chunk_tokens"),
                role=gen_role, handoff_queue=handoff_out).start()
            if supervise:
                from analytics_zoo_tpu.serving.resilience import (
                    Supervisor)

                gen_supervisor = Supervisor(gen_worker).start()
        if http.get("enabled", True):
            port = http.get("port")
            if port is None:
                # zoo.serving.http_port (0 = pick a free port); the
                # YAML's http.port wins when present
                port = int(get_config().get("zoo.serving.http_port", 0))
            frontend = HttpFrontend(
                in_q,
                out_q if frontend_out_q is None else frontend_out_q,
                # no YAML host -> zoo.serving.fleet.bind_host (the
                # frontend's config-driven default; loopback unless
                # the deployment opts into a routable bind)
                host=http.get("host"),
                port=port, worker=worker,
                certfile=http.get("certfile"),
                keyfile=http.get("keyfile"),
                gen_queue=gen_in, gen_worker=gen_worker).start()
            logger.info("serving ready at %s", frontend.address)
        redis_cfg = config.get("redis") or {}
        if redis_cfg.get("enabled"):
            # reference-client interop: a RESP2 adapter speaking the
            # cluster-serving Redis-stream + Arrow wire format
            # (redis_adapter.py). The adapter DRAINS the output queue,
            # exactly like the HTTP frontend's result router -- two
            # drainers on one queue would steal each other's results
            # nondeterministically, so the combination is rejected
            # here rather than discovered as hung clients
            if frontend is not None:
                raise ValueError(
                    "redis.enabled requires http.enabled: false -- "
                    "both frontends drain the same result queue (use "
                    "two deployments on a shared tcp broker to serve "
                    "both protocols)")
            from analytics_zoo_tpu.serving.redis_adapter import (
                RedisFrontend)

            redis_fe = RedisFrontend(
                in_q, out_q, host=redis_cfg.get("host"),
                port=int(redis_cfg.get("port", 6379)),
                name=redis_cfg.get("stream", "serving_stream")).serve()
        # config-gated rollup logger (zoo.obs.report.interval seconds;
        # 0 = off): the deployment's periodic rate/latency log line.
        # Inside the guard: a malformed interval value must not leak
        # the already-running worker/frontends
        from analytics_zoo_tpu.obs.reporter import maybe_start_reporter

        reporter = maybe_start_reporter()
    except Exception as e:
        emit_event("launch_failed", "serving", error=repr(e)[:500])
        # no ServingApp handle escapes; don't leak running pieces
        # (supervisors first, or they would restart the workers we
        # stop)
        if supervisor is not None:
            supervisor.stop()
        if gen_supervisor is not None:
            gen_supervisor.stop()
        if frontend is not None:
            frontend.stop()
        if redis_fe is not None:
            redis_fe.stop()
        if worker is not None:
            worker.stop()
        if gen_worker is not None:
            gen_worker.stop()
        raise
    emit_event(
        "serving_launch", "serving",
        queue=str(data.get("queue") or "memory"),
        pipelined=worker.pipelined if worker is not None else False,
        http=bool(http.get("enabled", True)),
        shard_mode=(shard_plan.label if shard_plan is not None
                    else "off"),
        generation=gen_worker is not None,
        address=frontend.address if frontend is not None else None)
    return ServingApp(model, worker, in_q, out_q, frontend,
                      redis_frontend=redis_fe, reporter=reporter,
                      supervisor=supervisor, gen_worker=gen_worker,
                      gen_supervisor=gen_supervisor,
                      gen_input_queue=gen_in)


def launch_from_yaml(path: str) -> ServingApp:
    import yaml

    with open(path) as f:
        return launch(yaml.safe_load(f) or {})


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="analytics_zoo_tpu serving launcher")
    ap.add_argument("-c", "--config", required=True,
                    help="path to the serving YAML config")
    ap.add_argument("--ready-file",
                    help="write {pid, address, started_at} JSON here "
                         "once the deployment is serving (the fleet "
                         "controller's readiness/address channel)")
    args = ap.parse_args(argv)
    app = launch_from_yaml(args.config)
    if args.ready_file:
        address = app.address
        # cross-host fleets (ISSUE-20): the frontend binds
        # zoo.serving.fleet.bind_host (often 0.0.0.0 in a container),
        # but the CONTROLLER must route to an address reachable from
        # its host -- zoo.serving.fleet.advertise_host, when set,
        # replaces the bound host in the readiness address
        adv = str(get_config().get(
            "zoo.serving.fleet.advertise_host", "") or "")
        if adv and address and ":" in address:
            address = f"{adv}:{address.rsplit(':', 1)[1]}"
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"pid": os.getpid(), "address": address,
                       "started_at": time.time()}, f)
        os.replace(tmp, args.ready_file)  # atomic: never half-read
    stop = threading.Event()

    def handler(signum, frame):
        stop.set()

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)
    if get_config().get("zoo.obs.flight.enabled", True):
        # SIGTERM bundle: the recorder's hook writes the postmortem,
        # then chains to `handler` above (installed first) for the
        # graceful drain -- orchestrated kills leave an artifact AND
        # shut down cleanly
        from analytics_zoo_tpu.obs.flight import install_flight_recorder

        install_flight_recorder(signals=True)
    stop.wait()
    # SIGTERM used to stop immediately, abandoning in-flight requests
    # (ISSUE-9 satellite): drain first -- stop pulling, answer what
    # was already accepted -- under zoo.serving.drain.deadline_ms
    # (0 restores the old cut-now behavior); rolling restarts lean on
    # this exact seam
    if float(get_config().get("zoo.serving.drain.deadline_ms",
                              10000.0)) > 0:
        app.drain()
    app.stop()


if __name__ == "__main__":
    main()
