"""Serving fleet: replicated worker processes behind one front tier.

The reference platform's headline serving story is a *fleet*: Flink
fans one Redis stream across many inference consumers and a frontend
load-balances direct traffic (PAPER.md section "serving"; BigDL 2.0,
arXiv:2204.01715). PR 5 made ONE worker process crash-safe; this
module (ISSUE-9) removes the last single point of failure by running
N of them:

- :class:`FleetController` -- spawns N replicas of the supervised
  launcher as separate OS processes (manager.py's /proc-identity
  machinery guards every signal), hosts the shared stream broker
  (``redis_adapter`` in stream mode), restarts dead replicas with
  capped backoff, rolls restarts one replica at a time behind a drain
  (capacity never drops below N-1), and scales the replica set within
  ``[min, max]`` on the :class:`Autoscaler`'s decisions.
- **Stream sharding** -- every replica is one consumer-group member on
  the broker's request stream (``RedisStreamQueue``): each request is
  claimed by exactly one replica, acked when its reply is pushed, and
  reclaimed by a survivor when the claimant dies un-acked
  (XAUTOCLAIM past ``zoo.serving.fleet.reclaim_idle_ms``) -- so a
  SIGKILLed replica loses no requests and answers none twice.
- :class:`FleetRouter` -- the front tier for direct HTTP traffic:
  round-robins /predict over replicas whose ``/healthz`` is green,
  and retries a request that hit a dead replica's socket **exactly
  once** on another replica (PR 5's RequestLedger policy lifted to
  the fleet level: one retry, then one structured
  ``replica_unavailable`` error).
- **Replica-level chaos** -- ``kill:replica:at=N`` in the chaos spec
  makes the controller SIGKILL a whole replica after the Nth observed
  result (seeded, deterministic); ``scripts/fleet_soak.py`` proves
  every request is still answered exactly once.

Everything here runs in the controller process; replicas are plain
``python -m analytics_zoo_tpu.serving.launcher`` deployments (drain on
SIGTERM, supervised worker, own HTTP frontend) -- the fleet is an
arrangement of already-hardened pieces, not a second serving engine.

The exactly-once story this module closes at runtime (claim, ack on
reply, reclaim on death) has a static twin: zoolint's lifecycle
engine proves the worker-side half -- that every claimed request
reaches exactly one reply/requeue on every code path, and that
replica/thread/lock lifecycles pair acquire with release
(docs/zoolint.md, "leakcheck").
"""

from __future__ import annotations

import json
import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.obs.events import emit as emit_event
from analytics_zoo_tpu.obs.metrics import get_registry
from analytics_zoo_tpu.serving.chaos import chaos_point
from analytics_zoo_tpu.serving.protocol import (
    PRIORITY_CLASSES, REPLICA_PREFIX)
from analytics_zoo_tpu.serving.redis_adapter import RedisFrontend
from analytics_zoo_tpu.serving.spawn import (
    SpawnBackend, make_spawn_backend)

logger = get_logger(__name__)

_REG = get_registry()
_M_REPLICAS = _REG.gauge(
    "zoo_fleet_replicas_items",
    "Fleet replica counts, by state (running = process alive, "
    "healthy = /healthz green)", labelnames=("state",))
_M_RESTARTS = _REG.counter(
    "zoo_fleet_replica_restarts_total",
    "Replica processes restarted by the controller, by reason",
    labelnames=("reason",))
_M_ROUTER_REQS = _REG.counter(
    "zoo_fleet_router_requests_total",
    "Front-tier router requests, by HTTP status answered",
    labelnames=("code",))
_M_ROUTER_RETRIES = _REG.counter(
    "zoo_fleet_router_retries_total",
    "Predict requests retried on another replica after a dead "
    "replica's connection failed")
_M_SCALE = _REG.counter(
    "zoo_fleet_scale_actions_total",
    "Autoscaler / scale_to replica-set changes, by direction",
    labelnames=("direction",))


class Replica:
    """One replica process the controller owns: spawn identity (the
    manager's /proc fingerprint, so a recycled pid is never signaled),
    readiness/address channel, and routing state."""

    def __init__(self, name: str, config_path: str, ready_file: str,
                 log_path: str, role: str = "unified"):
        self.name = name
        self.config_path = config_path
        self.ready_file = ready_file
        self.log_path = log_path
        # unified | prefill | decode (ISSUE-20): which pool this
        # replica serves; respawns preserve it (a decode consumer
        # name reborn as prefill would strand its reclaimed handoffs)
        self.role = role
        self.proc: Optional[subprocess.Popen] = None
        self.identity = None
        self.address: Optional[str] = None
        self.state = "starting"   # starting | up | stopping | stopped
        self.healthy = False
        self.quiesced = False     # router must skip (drain prelude)
        self.started_at = 0.0
        self.restarts = 0
        self.kill_reason: Optional[str] = None
        self.respawn_at = 0.0  # while state == "backoff"
        self.reprobe_at = 0.0  # next targeted re-probe (unhealthy)
        self.probe_failures = 0  # consecutive failed probes

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def routable(self) -> bool:
        return (self.state == "up" and self.healthy
                and not self.quiesced and self.address is not None)


class Autoscaler:
    """Hysteresis-gated scaling decisions from fleet load signals.

    Pure decision logic (injectable clock, no I/O) so tests can drive
    oscillating load through it directly. A sample is *overloaded*
    when stream backlog, shed rate, or p99 breaches its high mark, and
    *underloaded* only when every signal is comfortably low; anything
    in between is the dead band that resets both streaks. Scaling
    needs ``up_consecutive`` (resp. ``down_consecutive``) breaches in
    a row AND an expired cooldown -- an oscillating load that never
    holds a breach that long moves nothing (the no-flapping
    property). Bounds clamp to ``[min_replicas, max_replicas]``.

    **SLO mode** (ISSUE-15): with ``zoo.serving.slo.enabled`` the
    overload signal is SLO *attainment*, not raw backlog: a sample is
    overloaded when any configured target (``zoo.serving.slo.p99_ms``
    / ``ttft_ms`` / ``inter_token_ms``; 0 disables a target) is
    breached or the highest priority class is being shed, and
    underloaded only when every target is met with 2x headroom AND the
    backlog is low. The streak/cooldown machinery is shared, so the
    no-flapping property carries over verbatim."""

    def __init__(self, min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 backlog_high: Optional[int] = None,
                 backlog_low: Optional[int] = None,
                 p99_high_ms: Optional[float] = None,
                 up_consecutive: Optional[int] = None,
                 down_consecutive: Optional[int] = None,
                 cooldown_s: Optional[float] = None, clock=None,
                 slo_enabled: Optional[bool] = None,
                 slo_p99_ms: Optional[float] = None,
                 slo_ttft_ms: Optional[float] = None,
                 slo_inter_token_ms: Optional[float] = None):
        cfg = get_config()

        def _get(val, key, cast):
            return cast(cfg.get(key) if val is None else val)

        self.min_replicas = _get(min_replicas,
                                 "zoo.serving.fleet.min_replicas", int)
        self.max_replicas = _get(max_replicas,
                                 "zoo.serving.fleet.max_replicas", int)
        self.backlog_high = _get(
            backlog_high, "zoo.serving.fleet.autoscale.backlog_high",
            int)
        self.backlog_low = _get(
            backlog_low, "zoo.serving.fleet.autoscale.backlog_low",
            int)
        self.p99_high_ms = _get(
            p99_high_ms, "zoo.serving.fleet.autoscale.p99_high_ms",
            float)
        self.up_consecutive = _get(
            up_consecutive,
            "zoo.serving.fleet.autoscale.up_consecutive", int)
        self.down_consecutive = _get(
            down_consecutive,
            "zoo.serving.fleet.autoscale.down_consecutive", int)
        self.cooldown_s = _get(
            cooldown_s, "zoo.serving.fleet.autoscale.cooldown_s",
            float)
        self.slo_enabled = _get(
            slo_enabled, "zoo.serving.slo.enabled", bool)
        self.slo_p99_ms = _get(
            slo_p99_ms, "zoo.serving.slo.p99_ms", float)
        self.slo_ttft_ms = _get(
            slo_ttft_ms, "zoo.serving.slo.ttft_ms", float)
        self.slo_inter_token_ms = _get(
            slo_inter_token_ms, "zoo.serving.slo.inter_token_ms",
            float)
        self._clock = clock or time.monotonic
        self._over = 0
        self._under = 0
        self._last_action = None  # monotonic stamp of the last +-1

    def slo_breaches(self, p99_ms: Optional[float] = None,
                     ttft_p99_ms: Optional[float] = None,
                     inter_token_p99_ms: Optional[float] = None,
                     margin: float = 1.0) -> List[str]:
        """Names of the configured SLO targets the sample breaches
        (``margin`` scales the targets: 0.5 asks "met with 2x
        headroom?"). A target of 0 is not configured; a missing
        sample (None -- no traffic of that kind) cannot breach."""
        out = []
        for name, target, value in (
                ("p99_ms", self.slo_p99_ms, p99_ms),
                ("ttft_ms", self.slo_ttft_ms, ttft_p99_ms),
                ("inter_token_ms", self.slo_inter_token_ms,
                 inter_token_p99_ms)):
            if (target > 0 and value is not None
                    and value > target * margin):
                out.append(name)
        return out

    def decide(self, n_replicas: int, backlog: int,
               shed_rate: float = 0.0,
               p99_ms: Optional[float] = None,
               ttft_p99_ms: Optional[float] = None,
               inter_token_p99_ms: Optional[float] = None,
               high_shed_rate: float = 0.0) -> int:
        """One sample in, one of (-1, 0, +1) out."""
        if self.slo_enabled:
            # SLO attainment drives scaling: breach of any target (or
            # shedding the highest class -- brownout already failed to
            # protect it) is overload; underload needs every target
            # met with 2x headroom and a drained backlog
            over = bool(self.slo_breaches(
                p99_ms, ttft_p99_ms, inter_token_p99_ms)
                or high_shed_rate > 0)
            under = (not over
                     and not self.slo_breaches(
                         p99_ms, ttft_p99_ms, inter_token_p99_ms,
                         margin=0.5)
                     and backlog <= self.backlog_low
                     and shed_rate <= 0)
        else:
            over = (backlog > self.backlog_high or shed_rate > 0
                    or (self.p99_high_ms > 0 and p99_ms is not None
                        and p99_ms > self.p99_high_ms))
            under = (backlog <= self.backlog_low and shed_rate <= 0
                     and (p99_ms is None or self.p99_high_ms <= 0
                          or p99_ms < self.p99_high_ms / 2))
        if over:
            self._over += 1
            self._under = 0
        elif under:
            self._under += 1
            self._over = 0
        else:  # dead band: a load that wobbles around the marks must
            self._over = 0     # re-earn a full streak in either
            self._under = 0    # direction before anything moves
        now = self._clock()
        if (self._last_action is not None
                and now - self._last_action < self.cooldown_s):
            return 0
        if self._over >= self.up_consecutive:
            if n_replicas >= self.max_replicas:
                return 0
            self._over = 0
            self._last_action = now
            return 1
        if self._under >= self.down_consecutive:
            if n_replicas <= self.min_replicas:
                return 0
            self._under = 0
            self._last_action = now
            return -1
        return 0

    def stats(self) -> Dict[str, Any]:
        out = {"over_streak": self._over,
               "under_streak": self._under,
               "min": self.min_replicas, "max": self.max_replicas,
               "slo_enabled": self.slo_enabled}
        if self.slo_enabled:
            out["slo"] = {"p99_ms": self.slo_p99_ms,
                          "ttft_ms": self.slo_ttft_ms,
                          "inter_token_ms": self.slo_inter_token_ms}
        return out


class FleetRouter:
    """Front-tier HTTP router: the one address clients talk to.

    ``POST /predict`` round-robins over routable replicas (healthy,
    not quiesced) and relays the replica's response verbatim. A
    connection-level failure (refused/reset -- the replica died under
    us) marks the replica unhealthy and retries the request on a
    different replica at most ``zoo.serving.fleet.router_retries``
    times (default 1, PR 5's exactly-once retry policy at fleet
    level); a reply timeout is NOT retried -- the request may be
    mid-serve, and a retry would double-serve it. ``GET /healthz``
    summarizes fleet health, ``/metrics`` + ``/metrics.json`` expose
    the controller-process registry and fleet stats."""

    def __init__(self, controller: "FleetController",
                 host: Optional[str] = None, port: int = 0,
                 retries: Optional[int] = None,
                 timeout_s: float = 30.0):
        if host is None:
            # loopback unless the deployment opts into a routable bind
            # (zoo.serving.fleet.bind_host, e.g. 0.0.0.0 in a
            # container) -- single-host fleets keep their closed posture
            host = str(get_config().get(
                "zoo.serving.fleet.bind_host", "127.0.0.1"))
        self.controller = controller
        self.retries = int(
            get_config().get("zoo.serving.fleet.router_retries", 1)
            if retries is None else retries)
        self.timeout_s = float(timeout_s)
        router = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 for the chunked /generate relay; every other
            # reply carries Content-Length so keep-alive stays correct
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                logger.debug("fleet router: " + fmt, *args)

            def _reply(self, code: int, body: bytes,
                       content_type: str = "application/json"):
                _M_ROUTER_REQS.labels(code=str(code)).inc()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                route = self.path.split("?")[0]
                if route not in ("/predict", "/generate"):
                    self._reply(404, json.dumps(
                        {"error": "not found"}).encode())
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                if route == "/generate":
                    router.forward_generate(self, body)
                    return
                code, payload = router.forward_predict(body)
                self._reply(code, payload)

            def do_GET(self):
                route = self.path.split("?")[0]
                if route == "/healthz":
                    code, payload = router.health()
                    self._reply(code, json.dumps(payload).encode())
                elif route == "/metrics":
                    self._reply(
                        200,
                        get_registry().prometheus_text().encode(),
                        content_type="text/plain; version=0.0.4; "
                                     "charset=utf-8")
                elif route == "/metrics.json":
                    self._reply(200, json.dumps(
                        router.metrics()).encode())
                else:
                    self._reply(404, json.dumps(
                        {"error": "not found"}).encode())

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "FleetRouter":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="fleet-router")
        self._thread.start()
        logger.info("fleet router at %s", self.address)
        return self

    def stop(self) -> None:
        self._server.shutdown()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        self._server.server_close()

    # ------------------------------------------------------ forwarding --
    @staticmethod
    def _connect_probe(address: str, timeout_s: float = 2.0) -> None:
        """TCP-connect to the replica before sending the request: a
        connect-phase failure (refused, reset, OR a black-holing dead
        host timing out) provably never delivered anything, so it is
        duplicate-safe to retry on another replica -- unlike a
        reply-phase timeout, where the request may be mid-serve. One
        extra loopback/LAN handshake per forward buys that
        distinction, which urllib's single timeout cannot make."""
        import urllib.parse

        parts = urllib.parse.urlsplit(address)
        sock = socket.create_connection(
            (parts.hostname, parts.port), timeout=timeout_s)
        sock.close()

    def _request_replica(self, path: str, body: bytes):
        """The pre-delivery phase BOTH routes share: pick a routable
        replica, connect-probe it, send the request -- retrying
        pre-delivery failures (probe failure, 503 refusal, connection
        failure) on another replica up to ``retries`` times. Returns
        ``("resp", replica, open_response)`` on success (the caller
        owns closing it: /predict consumes it whole, /generate relays
        it), or ``("reply", status, body_bytes)`` for a verbatim
        terminal answer (replica 4xx/5xx, mid-serve timeout, or
        no-healthy-replica exhaustion).

        Why each branch is (or is not) retried:
        - probe failures (refused, reset, black-hole timeout) are all
          pre-delivery: safe to retry elsewhere;
        - a 503 is a REFUSAL (draining replica caught mid-quiesce,
          shedding, open breaker): provably not served, duplicate-safe
          to retry -- and it closes the quiesce-vs-in-flight race that
          would otherwise leak a 503 through a rolling restart. The
          replica stays healthy: refusing is policy, not death;
        - any other HTTP answer is an application-level response, not
          a dead replica: relay verbatim;
        - a reply-phase timeout may be MID-SERVE: retrying could
          double-serve, so surface the 504 instead."""
        tried: List[str] = []
        # disaggregated pools (ISSUE-20): /generate must land on a
        # PREFILL replica -- its frontend owns the reply stream the
        # decode pool pushes chunks to, and a decode replica's gen
        # input is the handoff stream (a raw client request there is
        # a routing bug by protocol). /predict shards over everyone.
        role = ("prefill"
                if path == "/generate"
                and getattr(self.controller, "disaggregated", False)
                else None)
        for attempt in range(self.retries + 1):
            rep = (self.controller.pick_replica(exclude=tried,
                                                role=role)
                   if role is not None
                   else self.controller.pick_replica(exclude=tried))
            if rep is None:
                break
            tried.append(rep.name)
            try:
                self._connect_probe(rep.address)
            except OSError as e:
                self.controller.mark_unhealthy(
                    rep, f"connect probe failed: {e}")
                if attempt < self.retries:
                    _M_ROUTER_RETRIES.inc()
                    logger.warning(
                        "replica %s unreachable (%s); retrying once "
                        "on another replica", rep.name, e)
                continue
            try:
                req = urllib.request.Request(
                    rep.address + path, data=body,
                    headers={"Content-Type": "application/json"})
                resp = urllib.request.urlopen(req,
                                              timeout=self.timeout_s)
                return "resp", rep, resp
            except urllib.error.HTTPError as e:
                if e.code == 503 and attempt < self.retries:
                    _M_ROUTER_RETRIES.inc()
                    e.read()
                    continue
                return "reply", e.code, e.read()
            except (urllib.error.URLError, ConnectionError,
                    socket.timeout, OSError) as e:
                reason = getattr(e, "reason", e)
                if isinstance(reason, socket.timeout):
                    return "reply", 504, json.dumps(
                        {"error": f"{path.lstrip('/')} timed out at "
                                  f"replica {rep.name}"}).encode()
                self.controller.mark_unhealthy(
                    rep, f"connection failed: {reason}")
                if attempt < self.retries:
                    _M_ROUTER_RETRIES.inc()
                    logger.warning(
                        "replica %s connection failed (%s); retrying "
                        "once on another replica", rep.name, reason)
        return "reply", 503, json.dumps(
            {"error": REPLICA_PREFIX,
             "detail": f"{REPLICA_PREFIX}: no healthy replica "
                       f"answered (tried {tried or 'none'})",
             "retry_after_s": 1}).encode()

    def forward_predict(self, body: bytes):
        kind, a, b = self._request_replica("/predict", body)
        if kind == "reply":
            return a, b
        with b as resp:
            return resp.status, resp.read()

    def forward_generate(self, handler, body: bytes) -> None:
        """Relay ``POST /generate`` to a routable replica, streaming
        the replica's chunked SSE response through verbatim. Retry
        policy is :meth:`_request_replica`'s -- once the first byte of
        the stream has been relayed there is no retry (the stream is
        mid-serve by definition)."""
        kind, a, b = self._request_replica("/generate", body)
        if kind == "reply":
            handler._reply(a, b)
            return
        rep, resp = a, b
        # stream open: relay line-by-line (SSE events are
        # newline-framed) as our own chunked response
        with resp:
            _M_ROUTER_REQS.labels(code="200").inc()
            handler.send_response(200)
            handler.send_header(
                "Content-Type",
                resp.headers.get("Content-Type", "text/event-stream"))
            handler.send_header("Transfer-Encoding", "chunked")
            handler.end_headers()

            def put(data: bytes) -> None:
                handler.wfile.write(
                    b"%X\r\n" % len(data) + data + b"\r\n")
                handler.wfile.flush()

            try:
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    put(line)
                put_close = True
            except (ConnectionError, BrokenPipeError,
                    socket.timeout, OSError) as e:
                # the REPLICA side died/stalled mid-stream: the
                # /generate contract forbids a silent close, so try
                # to hand the client a structured terminal event (and
                # a valid chunked ending) -- unless it was the CLIENT
                # side that went away, in which case these writes
                # fail too and we just log
                logger.warning("generate relay from %s ended "
                               "early: %s", rep.name, e)
                try:
                    put(b"data: " + json.dumps(
                        {"error": REPLICA_PREFIX,
                         "detail": f"{REPLICA_PREFIX}: replica "
                                   f"{rep.name} dropped the stream "
                                   "mid-relay"}).encode() + b"\n\n")
                    put_close = True
                except (ConnectionError, BrokenPipeError, OSError):
                    put_close = False
            if put_close:
                try:
                    handler.wfile.write(b"0\r\n\r\n")
                except (ConnectionError, BrokenPipeError,
                        OSError) as e:
                    logger.debug("relay close failed: %s", e)
            handler.close_connection = True

    def health(self):
        counts = self.controller.replica_states()
        healthy = counts.get("healthy", 0)
        # broker liveness rides the health answer (ISSUE-20): healthy
        # replicas cannot serve stream traffic through a dead data
        # plane, so a failed PING probe is a fleet-level 503 even with
        # green replicas. Throttled to one probe per interval so a
        # health-poll storm does not turn into a connect storm.
        broker_ok = self.controller.probe_broker_cached()
        ok = healthy > 0 and broker_ok
        status = ("ok" if ok
                  else "broker_unreachable" if not broker_ok
                  else "no_healthy_replicas")
        return (200 if ok else 503), {
            "status": status,
            "broker": "ok" if broker_ok else "unreachable",
            "replicas": counts,
        }

    def metrics(self) -> Dict[str, Any]:
        return {"fleet": self.controller.stats(),
                "registry": get_registry().snapshot()}


class FleetController:
    """Owns the fleet: broker, N replica processes, router, scaling.

    ``config`` is the per-replica serving YAML dict (model/params/
    shard); the controller overwrites its ``data:`` block to point at
    the hosted broker with a per-replica consumer name and enables the
    per-replica HTTP frontend on a free port. Replicas report their
    address through the launcher's ``--ready-file``."""

    def __init__(self, config: Dict[str, Any],
                 replicas: Optional[int] = None,
                 work_dir: Optional[str] = None,
                 host: Optional[str] = None, broker_port: int = 0,
                 router_port: int = 0,
                 advertise_host: Optional[str] = None,
                 stream: str = "serving_stream",
                 group: str = "serving",
                 seed: int = 0,
                 autoscale: Optional[bool] = None,
                 autoscaler: Optional[Autoscaler] = None,
                 env: Optional[Dict[str, str]] = None,
                 on_result: Optional[Callable] = None,
                 poll_interval_s: Optional[float] = None,
                 health_interval_s: Optional[float] = None,
                 spawn_backend: Optional[SpawnBackend] = None,
                 prefill_replicas: Optional[int] = None,
                 decode_replicas: Optional[int] = None,
                 prefill_autoscaler: Optional[Autoscaler] = None,
                 decode_autoscaler: Optional[Autoscaler] = None):
        cfg = get_config()
        self.config = dict(config)
        self.n_target = int(cfg.get("zoo.serving.fleet.replicas", 2)
                            if replicas is None else replicas)
        if work_dir is None:
            import tempfile

            work_dir = tempfile.mkdtemp(prefix="zoo-fleet-")
        self.work_dir = work_dir
        os.makedirs(work_dir, exist_ok=True)
        # bind vs advertise (ISSUE-20): the broker/router BIND
        # bind_host (loopback by default; 0.0.0.0 for cross-host
        # fleets); replicas are pointed at advertise_host when set --
        # the address reachable FROM the replica's host, which a
        # 0.0.0.0 bind is not
        self.host = (str(cfg.get("zoo.serving.fleet.bind_host",
                                 "127.0.0.1"))
                     if host is None else host)
        self.advertise_host = (
            str(cfg.get("zoo.serving.fleet.advertise_host", "") or "")
            if advertise_host is None else advertise_host)
        self._broker_port = broker_port
        self._router_port = router_port
        self.stream = stream
        self.group = group
        # disaggregated pools (ISSUE-20): both counts > 0 splits the
        # generation plane into a prefill pool (admission + prefill +
        # KV handoff) and a decode pool (handoff-stream consumers)
        self.prefill_target = int(
            cfg.get("zoo.serving.fleet.prefill_replicas", 0)
            if prefill_replicas is None else prefill_replicas)
        self.decode_target = int(
            cfg.get("zoo.serving.fleet.decode_replicas", 0)
            if decode_replicas is None else decode_replicas)
        self.disaggregated = (self.prefill_target > 0
                              and self.decode_target > 0)
        gen_block = dict(self.config.get("generation") or {})
        self.handoff_stream = str(gen_block.get(
            "handoff_stream", "generation_handoff_stream"))
        self.gen_stream = str(gen_block.get(
            "stream", "generation_stream"))
        if self.disaggregated and "generation" not in self.config:
            raise ValueError(
                "disaggregated pools need a generation: block in the "
                "replica config -- prefill/decode roles are a "
                "generation-plane split")
        self.poll_interval_s = float(
            cfg.get("zoo.serving.fleet.poll_interval_s", 0.5)
            if poll_interval_s is None else poll_interval_s)
        self.health_interval_s = float(
            cfg.get("zoo.serving.fleet.health_interval_s", 1.0)
            if health_interval_s is None else health_interval_s)
        self.autoscale = bool(
            cfg.get("zoo.serving.fleet.autoscale.enabled", False)
            if autoscale is None else autoscale)
        self.autoscaler = autoscaler or (Autoscaler()
                                         if self.autoscale else None)
        # per-pool scaling (ISSUE-20): each pool gets its own
        # streak/cooldown state and its own [min, max] -- prefill
        # demand (admissions) and decode demand (live streams) move
        # independently, so one shared autoscaler would couple them
        if self.disaggregated and (self.autoscale
                                   or prefill_autoscaler is not None):
            self.prefill_autoscaler = prefill_autoscaler or Autoscaler(
                min_replicas=int(cfg.get(
                    "zoo.serving.fleet.prefill_min_replicas", 1)),
                max_replicas=int(cfg.get(
                    "zoo.serving.fleet.prefill_max_replicas", 8)))
            self.decode_autoscaler = decode_autoscaler or Autoscaler(
                min_replicas=int(cfg.get(
                    "zoo.serving.fleet.decode_min_replicas", 1)),
                max_replicas=int(cfg.get(
                    "zoo.serving.fleet.decode_max_replicas", 8)))
        else:
            self.prefill_autoscaler = prefill_autoscaler
            self.decode_autoscaler = decode_autoscaler
        # router-health broker probe cache (one PING per interval)
        self._broker_probe_ok = True
        self._broker_probe_at = 0.0
        self.spawn_backend = spawn_backend or make_spawn_backend()
        self.reprobe_base_s = float(
            cfg.get("zoo.serving.fleet.reprobe_base_s", 0.05))
        self.reprobe_max_s = float(
            cfg.get("zoo.serving.fleet.reprobe_max_s", 2.0))
        self._env = dict(os.environ)
        self._env.update(env or {})
        # replicas run `python -m analytics_zoo_tpu...` from their own
        # cwd: the package root must ride PYTHONPATH explicitly, or
        # spawning only works when the CONTROLLER happens to run from
        # the repo root (python -m puts cwd on sys.path)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        existing = self._env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(os.pathsep):
            self._env["PYTHONPATH"] = (
                pkg_root + (os.pathsep + existing if existing else ""))
        self._on_result = on_result
        self._rng = random.Random(seed)
        self._lock = threading.RLock()
        self._replicas: Dict[str, Replica] = {}
        self._next_idx = 0
        self._rr = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_health = 0.0
        self._last_shed_total = 0.0
        self._last_high_shed_total = 0.0
        self._last_pool_shed: Dict[str, float] = {}
        self._slo_breached = False  # edge-detects the slo_breach event
        self.broker: Optional[RedisFrontend] = None
        self.router: Optional[FleetRouter] = None
        self.results_observed = 0
        self.chaos_kills = 0
        # capacity proof for rolling restarts: while one is active the
        # health tick records the minimum healthy count it saw
        self._rolling = False
        self.min_healthy_during_restart: Optional[int] = None

    # --------------------------------------------------------- lifecycle --
    @property
    def broker_address(self) -> str:
        # replicas connect to the ADVERTISED host (bind_host may be
        # 0.0.0.0, which is a bind target, not a destination)
        host = self.advertise_host or self.host
        if self.broker is None:
            # not started (manifest rendering, tests): the configured
            # endpoint, not a live socket
            return f"{host}:{self._broker_port}"
        return f"{host}:{self.broker.port}"

    def probe_broker_cached(self, max_age_s: float = 1.0) -> bool:
        """Router-health broker liveness: one RESP PING per
        ``max_age_s``, cached in between (every /healthz GET must not
        become a broker connect). Vacuously True with no broker
        started (router-only tests, manifest rendering): absence is
        not unreachability."""
        if self.broker is None:
            return True
        now = time.monotonic()
        if now - self._broker_probe_at >= max_age_s:
            from analytics_zoo_tpu.serving.redis_adapter import (
                probe_broker)

            self._broker_probe_at = now
            self._broker_probe_ok = probe_broker(self.broker_address)
        return self._broker_probe_ok

    def start(self) -> "FleetController":
        self.broker = RedisFrontend(
            host=self.host, port=self._broker_port, name=self.stream,
            result_callback=self._result_observed).serve()
        # fail-fast misconfiguration check (ISSUE-20): the address we
        # are about to hand every replica must answer a PING from
        # HERE. A bad advertise_host otherwise surfaces as N replicas
        # crash-looping on "broker unreachable".
        from analytics_zoo_tpu.serving.redis_adapter import wait_broker

        if not wait_broker(self.broker_address):
            self.broker.stop()
            raise RuntimeError(
                f"fleet broker at {self.broker_address} failed its "
                "own liveness probe -- check "
                "zoo.serving.fleet.advertise_host / bind_host")
        if self.disaggregated:
            # two pools instead of one unified set; n_target tracks
            # the combined size so wait_healthy() keeps its meaning
            self.n_target = self.prefill_target + self.decode_target
            for _ in range(self.prefill_target):
                self._spawn(role="prefill")
            for _ in range(self.decode_target):
                self._spawn(role="decode")
        else:
            for _ in range(self.n_target):
                self._spawn()
        self.router = FleetRouter(self, host=self.host,
                                  port=self._router_port).start()
        self._stop.clear()
        self._thread = threading.Thread(target=self._control_loop,
                                        daemon=True,
                                        name="fleet-controller")
        self._thread.start()
        return self

    def stop(self, drain: bool = False) -> None:
        """Tear the fleet down. ``drain=True`` SIGTERMs replicas and
        lets each finish in-flight work under its drain deadline;
        False is the fast path for tests/soaks that already drained
        the workload."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        if self.router is not None:
            self.router.stop()
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            self._terminate(rep, reason="fleet_stop", drain=drain)
        if self.broker is not None:
            self.broker.stop()
        self._update_gauges()

    # ----------------------------------------------------------- spawn --
    def _replica_config(self, name: str,
                        role: str = "unified") -> Dict[str, Any]:
        cfg = json.loads(json.dumps(self.config))  # deep copy
        cfg["data"] = {"queue": "redis", "path": self.broker_address,
                       "stream": self.stream, "group": self.group,
                       "consumer": name}
        http = dict(cfg.get("http") or {})
        http.setdefault("enabled", True)
        http["port"] = 0  # every replica picks a free port
        cfg["http"] = http
        cfg["name"] = name
        if role != "unified":
            gen = dict(cfg.get("generation") or {})
            gen["role"] = role
            gen["handoff_stream"] = self.handoff_stream
            cfg["generation"] = gen
        return cfg

    def _spawn(self, name: Optional[str] = None,
               role: str = "unified") -> Replica:
        import yaml

        with self._lock:
            if name is None:
                prefix = {"prefill": "p", "decode": "d"}.get(role, "r")
                name = f"{prefix}{self._next_idx}"
                self._next_idx += 1
            elif name in self._replicas:
                # respawn under an existing consumer name: the pool
                # role rides along (the reclaim story depends on the
                # reborn consumer re-attaching to the same stream)
                role = self._replicas[name].role
        config_path = os.path.join(self.work_dir, f"{name}.yaml")
        ready_file = os.path.join(self.work_dir, f"{name}.ready.json")
        log_path = os.path.join(self.work_dir, f"{name}.log")
        with open(config_path, "w") as f:
            yaml.safe_dump(self._replica_config(name, role), f)
        try:
            os.unlink(ready_file)  # a stale address must never route
        except FileNotFoundError:
            pass
        rep = Replica(name, config_path, ready_file, log_path,
                      role=role)
        rep.proc = self.spawn_backend.spawn(
            name,
            [sys.executable, "-m", "analytics_zoo_tpu.serving.launcher",
             "-c", config_path, "--ready-file", ready_file],
            log_path, self._env)
        rep.identity = self.spawn_backend.identity(rep.proc)
        rep.started_at = time.monotonic()
        with self._lock:
            self._replicas[name] = rep
        emit_event("replica_start", "serving", name=name,
                   pid=rep.proc.pid)
        logger.info("spawned replica %s (pid %d)", name, rep.proc.pid)
        self._update_gauges()
        return rep

    # ------------------------------------------------------ supervision --
    def _control_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self._supervise_tick()
                self._reprobe_tick()
                now = time.monotonic()
                if now - self._last_health >= self.health_interval_s:
                    self._last_health = now
                    self._health_tick()
                    if self.autoscaler is not None and self.autoscale:
                        self._autoscale_tick()
            except Exception as e:  # the control loop must survive
                logger.exception("fleet control tick failed: %s", e)

    def _supervise_tick(self) -> None:
        with self._lock:
            reps = list(self._replicas.values())
        now = time.monotonic()
        for rep in reps:
            if rep.state == "backoff":
                # scheduled respawn (never slept inline: one replica's
                # backoff must not stall supervision of the others)
                if now >= rep.respawn_at:
                    new = self._spawn(rep.name)
                    new.restarts = rep.restarts
                    self._update_gauges()
                continue
            if rep.proc is None or rep.state in ("stopping", "stopped"):
                continue
            if rep.address is None and os.path.isfile(rep.ready_file):
                try:
                    with open(rep.ready_file) as f:
                        ready = json.load(f)
                    rep.address = ready.get("address")
                    rep.state = "up"
                    logger.info("replica %s ready at %s", rep.name,
                                rep.address)
                except (OSError, ValueError) as e:
                    logger.debug("ready file for %s unreadable: %s",
                                 rep.name, e)
            rc = rep.proc.poll()
            if rc is None:
                continue
            # unexpected exit (SIGKILL chaos, OOM, crash the in-process
            # supervisor could not absorb): restart in place with a
            # small capped backoff
            reason = rep.kill_reason or "crashed"
            rep.kill_reason = None
            rep.healthy = False
            emit_event("replica_exit", "serving", name=rep.name,
                       pid=rep.pid, returncode=rc, reason=reason)
            _M_RESTARTS.labels(reason=reason).inc()
            rep.restarts += 1
            backoff = min(2.0, 0.05 * (2 ** min(rep.restarts - 1, 6)))
            backoff *= 0.5 + self._rng.random() * 0.5
            rep.state = "backoff"
            rep.respawn_at = now + backoff
            logger.warning(
                "replica %s exited (rc=%s, %s); restarting in %.2fs "
                "(restart #%d)", rep.name, rc, reason, backoff,
                rep.restarts)
            self._update_gauges()

    def _health_tick(self) -> None:
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            if rep.state != "up" or rep.address is None:
                continue
            was = rep.healthy
            healthy, status = self._probe(rep)
            rep.healthy = healthy
            if healthy and not was:
                rep.probe_failures = 0
                rep.reprobe_at = 0.0
                emit_event("replica_healthy", "serving", name=rep.name,
                           address=rep.address)
            elif was and not healthy:
                emit_event("replica_unhealthy", "serving",
                           name=rep.name, status=status)
                logger.warning("replica %s unhealthy: %s", rep.name,
                               status)
        self._update_gauges()
        if self._rolling:
            n = self.healthy_count()
            if (self.min_healthy_during_restart is None
                    or n < self.min_healthy_during_restart):
                self.min_healthy_during_restart = n

    def _probe(self, rep: Replica):
        try:
            with urllib.request.urlopen(rep.address + "/healthz",
                                        timeout=2.0) as resp:
                return resp.status == 200, "ok"
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read()).get("status", "")
            except (ValueError, OSError):
                detail = ""
            return False, f"http {e.code} {detail}".strip()
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            return False, f"unreachable: {getattr(e, 'reason', e)}"

    def mark_unhealthy(self, rep: Replica, why: str) -> None:
        """Router feedback: a connection-level failure outranks the
        last health poll (the poll is eventually consistent; the
        router just witnessed the truth). Schedules a targeted
        re-probe on the capped-exponential ladder so a replica that
        comes back is re-admitted without waiting for the next full
        health sweep."""
        if rep.healthy:
            rep.healthy = False
            emit_event("replica_unhealthy", "serving", name=rep.name,
                       status=why[:200])
        self._schedule_reprobe(rep)
        self._update_gauges()

    def _schedule_reprobe(self, rep: Replica) -> None:
        rep.probe_failures += 1
        backoff = min(self.reprobe_max_s, self.reprobe_base_s
                      * (2 ** min(rep.probe_failures - 1, 10)))
        backoff *= 0.5 + self._rng.random() * 0.5  # de-sync jitter
        rep.reprobe_at = time.monotonic() + backoff

    def _reprobe_tick(self) -> None:
        """Targeted recovery probes for unhealthy-but-up replicas,
        between health sweeps: each runs on its own capped-exponential
        schedule (base ``zoo.serving.fleet.reprobe_base_s``, cap
        ``reprobe_max_s``, jittered), so one flapping replica neither
        storms its own /healthz nor waits out a full sweep interval to
        rejoin the rotation."""
        with self._lock:
            reps = [r for r in self._replicas.values()
                    if r.state == "up" and not r.healthy
                    and not r.quiesced and r.address is not None]
        now = time.monotonic()
        for rep in reps:
            if now < rep.reprobe_at:
                continue
            healthy, status = self._probe(rep)
            if healthy:
                failures = rep.probe_failures
                rep.healthy = True
                rep.probe_failures = 0
                rep.reprobe_at = 0.0
                emit_event("replica_reprobe", "serving",
                           name=rep.name, outcome="recovered",
                           failures=failures)
                emit_event("replica_healthy", "serving",
                           name=rep.name, address=rep.address)
                logger.info("replica %s recovered on re-probe",
                            rep.name)
            else:
                self._schedule_reprobe(rep)
                logger.debug("re-probe of %s still failing: %s",
                             rep.name, status)
        if reps:
            self._update_gauges()

    # --------------------------------------------------------- routing --
    def pick_replica(self, exclude=(),
                     role: Optional[str] = None) -> Optional[Replica]:
        with self._lock:
            candidates = [r for r in self._replicas.values()
                          if r.routable() and r.name not in exclude
                          and (role is None or r.role == role)]
            if not candidates:
                return None
            self._rr += 1
            return candidates[self._rr % len(candidates)]

    def replica_states(self) -> Dict[str, int]:
        with self._lock:
            reps = list(self._replicas.values())
        return {
            "total": len(reps),
            "running": sum(1 for r in reps
                           if r.proc is not None
                           and r.proc.poll() is None),
            "healthy": sum(1 for r in reps if r.healthy),
            "quiesced": sum(1 for r in reps if r.quiesced),
        }

    def healthy_count(self) -> int:
        return self.replica_states()["healthy"]

    def _update_gauges(self) -> None:
        counts = self.replica_states()
        _M_REPLICAS.labels(state="running").set(counts["running"])
        _M_REPLICAS.labels(state="healthy").set(counts["healthy"])

    # ----------------------------------------------------- chaos seam --
    def _result_observed(self, uri: str, tensors) -> None:
        """Broker drain callback: one call per result entry consumed
        into the result table -- the deterministic tick the replica
        chaos seam counts on (``kill:replica:at=N`` = SIGKILL after
        the Nth observed result)."""
        self.results_observed += 1
        if self._on_result is not None:
            self._on_result(uri, tensors)
        if chaos_point("replica"):
            self.chaos_kill()

    def chaos_kill(self) -> Optional[str]:
        """SIGKILL one seeded-random live replica (the chaos drill's
        process-granular fault). Returns the victim's name."""
        with self._lock:
            live = sorted(
                (r for r in self._replicas.values()
                 if r.proc is not None and r.proc.poll() is None
                 and r.state == "up"),
                key=lambda r: r.name)
        if not live:
            return None
        rep = self._rng.choice(live)
        if not self.kill_replica(rep.name, reason="chaos"):
            return None
        self.chaos_kills += 1
        return rep.name

    def kill_one(self, role: str, reason: str = "drill"
                 ) -> Optional[str]:
        """SIGKILL the lowest-named live replica of one pool -- the
        disaggregated soak's deterministic per-pool fault (chaos_kill
        is seeded-random across pools)."""
        with self._lock:
            live = sorted(
                (r for r in self._replicas.values()
                 if r.role == role and r.proc is not None
                 and r.proc.poll() is None and r.state == "up"),
                key=lambda r: r.name)
        for rep in live:
            if self.kill_replica(rep.name, reason=reason):
                return rep.name
        return None

    def _identity_matches(self, rep: Replica) -> bool:
        """Recycled-identity guard, delegated to the spawn backend
        (the local backend runs manager.py's STARTTIME-only /proc
        check; the manifest backend never recycles a handle)."""
        return self.spawn_backend.identity_matches(rep.proc,
                                                   rep.identity)

    def kill_replica(self, name: str, reason: str = "drill") -> bool:
        """Immediate SIGKILL -- no drain, no warning; the supervision
        loop restarts it and the broker's pending-entry reclaim
        re-serves whatever it had claimed."""
        with self._lock:
            rep = self._replicas.get(name)
        if rep is None or rep.proc is None or rep.proc.poll() is not None:
            return False
        if not self._identity_matches(rep):
            logger.warning("replica %s pid %s identity changed; not "
                           "signaling", name, rep.proc.pid)
            return False
        rep.kill_reason = reason
        rep.healthy = False
        emit_event("replica_killed", "serving", name=name,
                   pid=rep.proc.pid, reason=reason)
        logger.warning("SIGKILL replica %s (pid %d, %s)", name,
                       rep.proc.pid, reason)
        try:
            self.spawn_backend.signal(rep.proc, signal.SIGKILL)
        except (ProcessLookupError, PermissionError) as e:
            logger.info("kill of %s failed: %s", name, e)
            return False
        return True

    # ------------------------------------------------- drain / restart --
    def _terminate(self, rep: Replica, reason: str,
                   drain: bool = True,
                   timeout_s: Optional[float] = None) -> None:
        """Graceful stop of one replica: quiesce at the router,
        SIGTERM (the launcher drains in-process under
        ``zoo.serving.drain.deadline_ms``), escalate to SIGKILL only
        past the deadline + grace."""
        if timeout_s is None:
            deadline_ms = float(get_config().get(
                "zoo.serving.drain.deadline_ms", 10000.0))
            timeout_s = deadline_ms / 1000.0 + 10.0
        rep.quiesced = True
        rep.state = "stopping"
        proc = rep.proc
        if proc is None or proc.poll() is not None:
            rep.state = "stopped"
            return
        if not self._identity_matches(rep):
            rep.state = "stopped"
            return  # recycled pid: never signal a stranger
        try:
            self.spawn_backend.signal(
                proc, signal.SIGTERM if drain else signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            rep.state = "stopped"
            return
        try:
            proc.wait(timeout=timeout_s if drain else 10.0)
        except subprocess.TimeoutExpired:
            logger.warning("replica %s ignored SIGTERM for %.1fs; "
                           "SIGKILL", rep.name, timeout_s)
            emit_event("replica_killed", "serving", name=rep.name,
                       pid=proc.pid, reason="drain_timeout")
            self.spawn_backend.signal(proc, signal.SIGKILL)
            proc.wait(timeout=10.0)
        rep.healthy = False
        rep.state = "stopped"
        emit_event("replica_exit", "serving", name=rep.name,
                   pid=proc.pid, returncode=proc.returncode,
                   reason=reason)

    def wait_healthy(self, n: Optional[int] = None,
                     timeout_s: float = 120.0) -> bool:
        """Block until >= n replicas are healthy (default: the full
        target)."""
        n = self.n_target if n is None else n
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.healthy_count() >= n:
                return True
            time.sleep(0.1)
        return False

    def wait_replica_healthy(self, name: str,
                             timeout_s: float = 120.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                rep = self._replicas.get(name)
            if rep is not None and rep.healthy:
                return True
            time.sleep(0.1)
        return False

    def _slo_ok(self) -> bool:
        """The rolling-restart pacing gate: True when the high
        priority class is within SLO (trivially True when SLO mode is
        off). Sampled live from replica metrics -- restarting while
        interactive traffic is already out of SLO would take the N-1
        capacity dip out of traffic that cannot absorb it."""
        a = self.autoscaler
        if a is None or not a.slo_enabled:
            return True
        s = self._sample_replicas()
        return not a.slo_breaches(s["p99_ms"], s["ttft_p99_ms"],
                                  s["inter_token_p99_ms"])

    def rolling_restart(self, timeout_s: float = 180.0,
                        slo_gate: Optional[Callable[[], bool]] = None,
                        slo_wait_s: float = 30.0) -> bool:
        """Restart every replica, one at a time, each behind a drain:
        quiesce at the router -> SIGTERM (in-process drain) -> wait
        exit -> respawn under the same consumer name -> wait healthy.
        At most one replica is ever down, so serving capacity stays
        >= N-1 throughout; ``min_healthy_during_restart`` records the
        health tick's observed floor as evidence. Returns True when
        every replica came back healthy.

        Before taking each replica down the ``slo_gate`` must answer
        True (default: :meth:`_slo_ok` -- the high class is within
        SLO). A gate that stays False for ``slo_wait_s`` ABORTS the
        restart (False return): shrinking capacity under an active
        SLO breach only deepens the breach."""
        if slo_gate is None:
            slo_gate = self._slo_ok
        emit_event("rolling_restart", "serving", phase="begin",
                   name=None)
        self._rolling = True
        self.min_healthy_during_restart = self.healthy_count()
        ok = True
        with self._lock:
            names = sorted(self._replicas)
        try:
            for name in names:
                gate_deadline = time.monotonic() + slo_wait_s
                while not slo_gate():
                    if time.monotonic() >= gate_deadline:
                        emit_event("rolling_restart", "serving",
                                   phase="slo_blocked", name=name)
                        logger.error(
                            "rolling restart aborted before %s: the "
                            "high priority class stayed out of SLO "
                            "for %.1fs", name, slo_wait_s)
                        return False
                    time.sleep(min(0.2, self.poll_interval_s))
                emit_event("rolling_restart", "serving",
                           phase="replica", name=name)
                with self._lock:
                    rep = self._replicas.get(name)
                if rep is None:
                    continue
                self._terminate(rep, reason="rolling_restart",
                                drain=True)
                _M_RESTARTS.labels(reason="rolling").inc()
                restarts = rep.restarts + 1
                new = self._spawn(name)
                new.restarts = restarts
                if not self.wait_replica_healthy(name,
                                                 timeout_s=timeout_s):
                    logger.error("replica %s did not come back "
                                 "healthy after rolling restart", name)
                    ok = False
        finally:
            self._rolling = False
            emit_event("rolling_restart", "serving", phase="end",
                       name=None)
        return ok

    # --------------------------------------------------------- scaling --
    def scale_to(self, n: int, reason: str = "manual") -> int:
        """Grow or shrink the replica set to ``n`` (clamped to the
        autoscaler's bounds when one is attached). Shrinking drains:
        the victims finish in-flight work before exiting, and their
        un-started claims reclaim to survivors."""
        if self.disaggregated:
            raise ValueError(
                "scale_to on a disaggregated fleet would mix pools; "
                "use scale_pool('prefill'|'decode', n)")
        if self.autoscaler is not None:
            n = max(self.autoscaler.min_replicas,
                    min(self.autoscaler.max_replicas, n))
        n = max(1, int(n))
        with self._lock:
            current = {name: rep for name, rep in self._replicas.items()
                       if rep.state != "stopped"}
        delta = n - len(current)
        if delta == 0:
            return 0
        direction = "up" if delta > 0 else "down"
        emit_event("fleet_scale", "serving", direction=direction,
                   n_from=len(current), n_to=n, reason=reason)
        _M_SCALE.labels(direction=direction).inc()
        logger.info("scaling %s: %d -> %d replicas (%s)", direction,
                    len(current), n, reason)
        if delta > 0:
            for _ in range(delta):
                self._spawn()
        else:
            # newest first: the oldest replicas have the warmest
            # caches and the longest uptime record
            victims = sorted(current.values(),
                             key=lambda r: r.started_at)[delta:]
            for rep in victims:
                # quiesce SYNCHRONOUSLY (the router must stop routing
                # here before this call returns), then drain on a
                # side thread: a busy victim's drain can take the
                # whole deadline, and blocking the control loop that
                # long would stall crash restarts and health probes
                # for every OTHER replica
                rep.quiesced = True
                rep.state = "stopping"
                threading.Thread(
                    target=self._drain_victim, args=(rep,),
                    daemon=True,
                    name=f"fleet-drain-{rep.name}").start()
        self.n_target = n
        self._update_gauges()
        return delta

    def _drain_victim(self, rep: Replica) -> None:
        try:
            self._terminate(rep, reason="scale_down", drain=True)
        except Exception as e:
            logger.exception("scale-down drain of %s failed: %s",
                             rep.name, e)
        with self._lock:
            self._replicas.pop(rep.name, None)
        self._update_gauges()

    def scale_pool(self, role: str, n: int,
                   reason: str = "manual") -> int:
        """Grow or shrink ONE pool of a disaggregated fleet to ``n``
        replicas (clamped to that pool's autoscaler bounds when
        attached). Shrinking drains newest-first, like scale_to --
        and a draining decode victim re-hands its live streams to a
        pool survivor before it exits."""
        if role not in ("prefill", "decode"):
            raise ValueError(f"scale_pool role must be prefill | "
                             f"decode, not {role!r}")
        scaler = (self.prefill_autoscaler if role == "prefill"
                  else self.decode_autoscaler)
        if scaler is not None:
            n = max(scaler.min_replicas, min(scaler.max_replicas, n))
        n = max(1, int(n))
        with self._lock:
            current = {name: rep
                       for name, rep in self._replicas.items()
                       if rep.role == role and rep.state != "stopped"}
        delta = n - len(current)
        if delta == 0:
            return 0
        direction = "up" if delta > 0 else "down"
        emit_event("fleet_scale", "serving", direction=direction,
                   n_from=len(current), n_to=n,
                   reason=f"{reason}:{role}")
        _M_SCALE.labels(direction=direction).inc()
        logger.info("scaling %s pool %s: %d -> %d replicas (%s)",
                    role, direction, len(current), n, reason)
        if delta > 0:
            for _ in range(delta):
                self._spawn(role=role)
        else:
            victims = sorted(current.values(),
                             key=lambda r: r.started_at)[delta:]
            for rep in victims:
                rep.quiesced = True
                rep.state = "stopping"
                threading.Thread(
                    target=self._drain_victim, args=(rep,),
                    daemon=True,
                    name=f"fleet-drain-{rep.name}").start()
        if role == "prefill":
            self.prefill_target = n
        else:
            self.decode_target = n
        self.n_target = self.prefill_target + self.decode_target
        self._update_gauges()
        return delta

    def _autoscale_tick(self) -> None:
        if self.disaggregated:
            self._autoscale_pools_tick()
            return
        backlog = self.broker.store.backlog(self.stream, self.group)
        sample = self._sample_replicas()
        shed_rate = max(0.0, sample["shed_total"]
                        - self._last_shed_total)
        high_shed_rate = max(0.0, sample["high_shed_total"]
                             - self._last_high_shed_total)
        self._last_shed_total = sample["shed_total"]
        self._last_high_shed_total = sample["high_shed_total"]
        states = self.replica_states()
        if self.autoscaler.slo_enabled:
            breaches = self.autoscaler.slo_breaches(
                sample["p99_ms"], sample["ttft_p99_ms"],
                sample["inter_token_p99_ms"])
            if breaches and not self._slo_breached:
                # edge-triggered: one event per breach episode
                emit_event("slo_breach", "serving",
                           signals=",".join(breaches),
                           p99_ms=sample["p99_ms"],
                           ttft_p99_ms=sample["ttft_p99_ms"],
                           inter_token_p99_ms=sample[
                               "inter_token_p99_ms"])
            self._slo_breached = bool(breaches)
        decision = self.autoscaler.decide(
            states["total"], backlog, shed_rate=shed_rate,
            p99_ms=sample["p99_ms"],
            ttft_p99_ms=sample["ttft_p99_ms"],
            inter_token_p99_ms=sample["inter_token_p99_ms"],
            high_shed_rate=high_shed_rate)
        if decision:
            self.scale_to(states["total"] + decision,
                          reason="autoscale")

    def _autoscale_pools_tick(self) -> None:
        """Disaggregated scaling: each pool decides off ITS demand
        signal. Prefill eats the generation request stream, so its
        backlog + admission-side latency (predict p99 / ttft where a
        prefill replica observes it) drive that pool; decode eats the
        handoff stream, so ITS backlog + inter-token p99 (the decode
        pool is where token pacing lives) drive the other. SLO
        attainment samples ride the same decide() machinery --
        streaks, cooldown, dead band -- per pool."""
        gen_backlog = self.broker.store.backlog(self.gen_stream,
                                                self.group)
        handoff_backlog = self.broker.store.backlog(
            self.handoff_stream, f"{self.group}_decode")
        for role, scaler, backlog in (
                ("prefill", self.prefill_autoscaler, gen_backlog),
                ("decode", self.decode_autoscaler, handoff_backlog)):
            if scaler is None:
                continue
            sample = self._sample_replicas(role=role)
            key = f"{role}_shed"
            shed_rate = max(0.0, sample["shed_total"]
                            - self._last_pool_shed.get(key, 0.0))
            high_rate = max(0.0, sample["high_shed_total"]
                            - self._last_pool_shed.get(
                                key + "_high", 0.0))
            self._last_pool_shed[key] = sample["shed_total"]
            self._last_pool_shed[key + "_high"] = (
                sample["high_shed_total"])
            with self._lock:
                n = sum(1 for r in self._replicas.values()
                        if r.role == role and r.state != "stopped")
            decision = scaler.decide(
                n, backlog, shed_rate=shed_rate,
                p99_ms=sample["p99_ms"],
                ttft_p99_ms=sample["ttft_p99_ms"],
                inter_token_p99_ms=sample["inter_token_p99_ms"],
                high_shed_rate=high_rate)
            if decision:
                self.scale_pool(role, n + decision,
                                reason="autoscale")

    def _sample_replicas(self,
                         role: Optional[str] = None) -> Dict[str, Any]:
        """Fleet-wide load/SLO sample scraped from replica
        /metrics.json endpoints -- best-effort: an unreachable replica
        contributes nothing (its health probe is the loud signal).
        Returns shed totals (all classes + the highest class alone)
        and the worst-replica p99 / TTFT-p99 / inter-token-p99 in
        milliseconds (None = no such traffic anywhere)."""
        out: Dict[str, Any] = {
            "shed_total": 0.0, "high_shed_total": 0.0,
            "p99_ms": None, "ttft_p99_ms": None,
            "inter_token_p99_ms": None}
        high_label = f"class={PRIORITY_CLASSES[0]}"
        with self._lock:
            reps = [r for r in self._replicas.values()
                    if r.address and r.state == "up"
                    and (role is None or r.role == role)]
        for rep in reps:
            try:
                with urllib.request.urlopen(
                        rep.address + "/metrics.json",
                        timeout=2.0) as resp:
                    snap = json.load(resp)
            except (urllib.error.URLError, ConnectionError, OSError,
                    ValueError) as e:
                logger.debug("metrics scrape of %s failed: %s",
                             rep.name, e)
                continue
            reg = snap.get("registry", {})
            shed = reg.get("zoo_serving_shed_total")
            if isinstance(shed, dict):
                # snapshot family shape: {"type", "help",
                # "values": {"<label>=<value>": count}}
                for key, v in (shed.get("values") or {}).items():
                    out["shed_total"] += float(v or 0.0)
                    if key == high_label or key == "":
                        # unlabeled = pre-ladder replica: conservative
                        # reading says the high class was refused
                        out["high_shed_total"] += float(v or 0.0)
            service = (snap.get("worker", {}).get("stages", {})
                       .get("service", {}))
            p99 = service.get("p99_s")  # Timer.summary's "_s" suffix
            if p99 is not None:
                ms = float(p99) * 1000.0
                out["p99_ms"] = (ms if out["p99_ms"] is None
                                 else max(out["p99_ms"], ms))
            gen_lat = snap.get("generation", {}).get("latency", {})
            for stage, key in (("ttft", "ttft_p99_ms"),
                               ("inter_token", "inter_token_p99_ms")):
                p = (gen_lat.get(stage) or {}).get("p99_s")
                if p is not None:
                    ms = float(p) * 1000.0
                    out[key] = (ms if out[key] is None
                                else max(out[key], ms))
        return out

    # ----------------------------------------------------------- stats --
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            reps = {name: {"state": r.state, "healthy": r.healthy,
                           "quiesced": r.quiesced, "pid": r.pid,
                           "address": r.address, "role": r.role,
                           "restarts": r.restarts}
                    for name, r in sorted(self._replicas.items())}
        out = {
            "target": self.n_target,
            "replicas": reps,
            "results_observed": self.results_observed,
            "chaos_kills": self.chaos_kills,
            "backlog": (self.broker.store.backlog(self.stream,
                                                  self.group)
                        if self.broker is not None else 0),
        }
        if self.disaggregated:
            pools: Dict[str, Any] = {}
            for pool_role, target, scaler in (
                    ("prefill", self.prefill_target,
                     self.prefill_autoscaler),
                    ("decode", self.decode_target,
                     self.decode_autoscaler)):
                info = {
                    "target": target,
                    "healthy": sum(
                        1 for r in reps.values()
                        if r["role"] == pool_role and r["healthy"]),
                }
                if scaler is not None:
                    info["autoscaler"] = scaler.stats()
                pools[pool_role] = info
            out["pools"] = pools
            if self.broker is not None:
                out["handoff_backlog"] = self.broker.store.backlog(
                    self.handoff_stream, f"{self.group}_decode")
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.stats()
        if self.min_healthy_during_restart is not None:
            out["min_healthy_during_restart"] = (
                self.min_healthy_during_restart)
        return out
