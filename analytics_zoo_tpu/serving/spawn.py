"""Pluggable replica spawn backends for the serving fleet (ISSUE-15).

The :class:`~analytics_zoo_tpu.serving.fleet.FleetController` used to
``subprocess.Popen`` its replicas inline, which welded the fleet's
control plane (supervision, health, scaling, rolling restarts) to one
deployment substrate: local OS processes. This module extracts that
seam behind :class:`SpawnBackend` so the SAME control plane drives:

- :class:`LocalSpawnBackend` -- the historical behavior, byte for
  byte: one launcher process per replica, ``start_new_session``, log
  file capture, /proc-identity guarded signaling. The default; every
  existing fleet test passes against it unchanged.
- :class:`ManifestSpawnBackend` -- spawns nothing. It records each
  replica the controller asked for and renders the equivalent
  **docker-compose** and **Kubernetes** manifests
  (:meth:`~ManifestSpawnBackend.compose_yaml` /
  :meth:`~ManifestSpawnBackend.k8s_yaml`), with host paths rewritten
  to stable container paths so the output is machine-independent and
  golden-testable. ``kill`` / ``signal`` flip the synthetic handle's
  state the way a real exit would, so controller logic (supervision,
  rolling restarts, chaos kills) can be exercised against it without
  processes.

A backend hands back a *handle* with the ``subprocess.Popen`` surface
the controller relies on (``pid`` / ``poll`` / ``returncode`` /
``wait``); all signaling goes through the backend (never bare
``os.kill``), which is what lets the manifest backend intercept it.

``zoo.serving.fleet.spawn_backend`` selects the backend by name
(:func:`make_spawn_backend`); tests and tools may also inject an
instance directly into the controller.
"""

from __future__ import annotations

import os
import signal as _signal
import subprocess
import threading
from typing import Any, Dict, List, Optional, Sequence

from analytics_zoo_tpu.common.log import get_logger
from analytics_zoo_tpu.serving.manager import _proc_identity

logger = get_logger(__name__)


class SpawnBackend:
    """What the fleet needs from a deployment substrate.

    Subclasses implement how a replica comes to exist and how it is
    signaled; the controller owns everything else (naming, config
    files, readiness, health, backoff)."""

    name = "abstract"

    def spawn(self, name: str, argv: Sequence[str], log_path: str,
              env: Dict[str, str]):
        """Bring one replica up; returns a Popen-like handle."""
        raise NotImplementedError

    def identity(self, handle) -> Optional[tuple]:
        """Spawn-time identity fingerprint, or None when the
        substrate cannot provide one."""
        raise NotImplementedError

    def identity_matches(self, handle, identity) -> bool:
        """True unless the handle provably now names a DIFFERENT
        process than ``identity`` fingerprinted at spawn (the
        recycled-pid guard). Unknowable must answer True: the local
        rule is "cannot disprove, may signal"."""
        raise NotImplementedError

    def signal(self, handle, sig: int) -> None:
        """Deliver ``sig`` to the replica behind ``handle``. May
        raise ProcessLookupError/PermissionError like ``os.kill``."""
        raise NotImplementedError


class LocalSpawnBackend(SpawnBackend):
    """OS processes on this host -- the historical inline behavior."""

    name = "local"

    def spawn(self, name: str, argv: Sequence[str], log_path: str,
              env: Dict[str, str]) -> subprocess.Popen:
        log_f = open(log_path, "ab")
        try:
            proc = subprocess.Popen(
                list(argv), stdout=log_f, stderr=subprocess.STDOUT,
                start_new_session=True, env=env)
        finally:
            log_f.close()
        return proc

    def identity(self, handle) -> Optional[tuple]:
        return _proc_identity(handle.pid)

    def identity_matches(self, handle, identity) -> bool:
        # STARTTIME-only /proc check (the manager.py rule): two
        # processes can share a recycled pid, never a (pid,
        # starttime) pair; cmdline legitimately changes across exec
        if identity is None or handle is None:
            return True  # no /proc at spawn: cannot disprove
        now = _proc_identity(handle.pid)
        return now is None or now[0] == identity[0]

    def signal(self, handle, sig: int) -> None:
        os.kill(handle.pid, sig)


class _ManifestHandle:
    """Synthetic Popen-surface handle for a replica that exists only
    in a rendered manifest. Signals flip it to exited, so controller
    state machines run against it exactly as against a process."""

    def __init__(self, name: str, pid: int):
        self.name = name
        self.pid = pid
        self.returncode: Optional[int] = None
        self._cond = threading.Condition()

    def poll(self) -> Optional[int]:
        return self.returncode

    def wait(self, timeout: Optional[float] = None) -> int:
        with self._cond:
            if self.returncode is None:
                self._cond.wait(timeout)
            if self.returncode is None:
                raise subprocess.TimeoutExpired(
                    cmd=f"manifest:{self.name}", timeout=timeout or 0)
            return self.returncode

    def send_signal(self, sig: int) -> None:
        with self._cond:
            if self.returncode is None:
                # a manifest replica "exits" the instant it is
                # signaled -- Popen's negative-signal convention
                self.returncode = -int(sig)
                self._cond.notify_all()

    def kill(self) -> None:
        self.send_signal(int(_signal.SIGKILL))


class ManifestSpawnBackend(SpawnBackend):
    """Records the fleet as deployment manifests instead of running
    it. Pseudo-pids start at 100000 -- far above real pid ranges, so
    a bug that ever routed one into ``os.kill`` would fail loudly.

    Host paths (per-replica YAML, logs) are rewritten to fixed
    container paths (``/etc/zoo``, ``/var/log/zoo``) so the rendered
    YAML is independent of the controller's work_dir and python --
    the property the golden tests pin."""

    name = "manifest"
    CONFIG_DIR = "/etc/zoo"
    LOG_DIR = "/var/log/zoo"

    def __init__(self, image: str = "analytics-zoo-tpu:latest",
                 namespace: str = "zoo-serving"):
        self.image = image
        self.namespace = namespace
        self._next_pid = 100000
        self._lock = threading.Lock()
        self.records: List[Dict[str, Any]] = []

    # ------------------------------------------------------ backend --
    def spawn(self, name: str, argv: Sequence[str], log_path: str,
              env: Dict[str, str]) -> _ManifestHandle:
        argv = list(argv)
        # replica argv shape: [python, -m, module, *flags] -- inside
        # the container the interpreter is just "python" and file
        # flags point at the mounted config dir
        command = ["python"] + [
            a if i == 0 or not os.path.isabs(a)
            else f"{self.CONFIG_DIR}/{os.path.basename(a)}"
            for i, a in enumerate(argv[1:])]
        with self._lock:
            pid = self._next_pid
            self._next_pid += 1
            self.records.append({"name": name, "command": command})
        logger.info("manifest backend recorded replica %s "
                    "(pseudo-pid %d)", name, pid)
        return _ManifestHandle(name, pid)

    def identity(self, handle) -> Optional[tuple]:
        return ("manifest", handle.pid)

    def identity_matches(self, handle, identity) -> bool:
        return True  # nothing to recycle: handles are never reused

    def signal(self, handle, sig: int) -> None:
        handle.send_signal(sig)

    # ------------------------------------------------------- render --
    def compose_yaml(self) -> str:
        """docker-compose v3 manifest: one service per replica, the
        shared config volume, and the exact launcher command line."""
        import yaml

        services: Dict[str, Any] = {}
        for rec in sorted(self.records, key=lambda r: r["name"]):
            services[rec["name"]] = {
                "image": self.image,
                "command": rec["command"],
                "restart": "unless-stopped",
                "volumes": [
                    f"./config:{self.CONFIG_DIR}:ro",
                    f"./logs/{rec['name']}:{self.LOG_DIR}",
                ],
            }
        doc = {"version": "3.8", "services": services}
        return yaml.safe_dump(doc, sort_keys=True,
                              default_flow_style=False)

    def k8s_yaml(self) -> str:
        """Kubernetes manifest: one Pod per replica (the controller
        IS the replica supervisor -- a Deployment's replica count
        would fight the fleet's own autoscaler) plus the shared
        ConfigMap reference."""
        import yaml

        docs: List[Dict[str, Any]] = []
        for rec in sorted(self.records, key=lambda r: r["name"]):
            docs.append({
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": rec["name"],
                    "namespace": self.namespace,
                    "labels": {"app": "zoo-serving",
                               "replica": rec["name"]},
                },
                "spec": {
                    "restartPolicy": "Always",
                    "containers": [{
                        "name": "serving",
                        "image": self.image,
                        "command": rec["command"],
                        "volumeMounts": [{
                            "name": "zoo-config",
                            "mountPath": self.CONFIG_DIR,
                            "readOnly": True,
                        }],
                    }],
                    "volumes": [{
                        "name": "zoo-config",
                        "configMap": {"name": "zoo-serving-config"},
                    }],
                },
            })
        return yaml.safe_dump_all(docs, sort_keys=True,
                                  default_flow_style=False)


class RemoteSpawnBackend(SpawnBackend):
    """Drives replicas as separate containers/hosts through a
    command-runner prefix (ISSUE-20) -- the runnable counterpart of
    the manifests :class:`ManifestSpawnBackend` renders.

    ``runner`` is an argv prefix that executes its arguments on the
    target substrate: ``["ssh", "worker-3"]``, ``["docker", "exec",
    "zoo-fleet"]``, or empty = run the argv directly on this host (the
    degenerate remote target; byte-equivalent to
    :class:`LocalSpawnBackend` modulo process-group signaling). The
    *driver* process -- the local ``ssh``/``exec`` -- is the handle:
    its lifetime tracks the replica's for exec-style runners, and all
    signaling lands on its process group (``start_new_session`` makes
    the driver the group leader), so SIGTERM drains and SIGKILL
    hard-kills reach the replica through the same channel that
    launched it.

    Environment: with an empty runner the env dict passes straight to
    ``Popen``. With a non-empty runner the replica runs on a DIFFERENT
    host, so the config-bearing keys (``AZT_*`` overrides,
    ``PYTHONPATH``, ``JAX_*``) are serialized into an ``env K=V ...``
    command prefix instead -- the one channel guaranteed to cross any
    exec-style runner.

    Readiness stays the controller's business: the ready-file channel
    now carries the replica's ADVERTISED ``host:port``
    (``zoo.serving.fleet.advertise_host``), and the broker liveness
    probe (``redis_adapter.wait_broker``) gates a remote replica's
    launch on the broker actually being reachable across hosts."""

    name = "remote"

    # env keys worth shipping across an exec-style runner: config
    # overrides + interpreter/search-path + accelerator selection
    _ENV_FORWARD_PREFIXES = ("AZT_", "JAX_", "XLA_")
    _ENV_FORWARD_KEYS = ("PYTHONPATH",)

    def __init__(self, runner: Optional[Sequence[str]] = None):
        if runner is None:
            from analytics_zoo_tpu.common.config import get_config

            runner = str(get_config().get(
                "zoo.serving.fleet.remote_runner", "")).split()
        self.runner: List[str] = list(runner)

    def _forwarded_env(self, env: Dict[str, str]) -> List[str]:
        out = []
        for k in sorted(env):
            if (k in self._ENV_FORWARD_KEYS
                    or k.startswith(self._ENV_FORWARD_PREFIXES)):
                out.append(f"{k}={env[k]}")
        return out

    def spawn(self, name: str, argv: Sequence[str], log_path: str,
              env: Dict[str, str]) -> subprocess.Popen:
        if self.runner:
            command = (self.runner + ["env"]
                       + self._forwarded_env(env) + list(argv))
        else:
            command = list(argv)
        log_f = open(log_path, "ab")
        try:
            proc = subprocess.Popen(
                command, stdout=log_f, stderr=subprocess.STDOUT,
                start_new_session=True, env=env)
        finally:
            log_f.close()
        logger.info("remote backend launched replica %s via %s "
                    "(driver pid %d)", name,
                    self.runner or "direct exec", proc.pid)
        return proc

    def identity(self, handle) -> Optional[tuple]:
        # the DRIVER's /proc identity: the recycled-pid guard protects
        # the local process we signal, which is the only process this
        # host can name
        return _proc_identity(handle.pid)

    def identity_matches(self, handle, identity) -> bool:
        if identity is None or handle is None:
            return True  # no /proc at spawn: cannot disprove
        now = _proc_identity(handle.pid)
        return now is None or now[0] == identity[0]

    def signal(self, handle, sig: int) -> None:
        # whole driver process group: an exec-style runner may have
        # interposed an ``env``/shell hop between the driver and the
        # replica -- group delivery reaches every link of that chain
        try:
            os.killpg(handle.pid, sig)
        except ProcessLookupError:
            os.kill(handle.pid, sig)


def make_spawn_backend(name: Optional[str] = None) -> SpawnBackend:
    """Backend by name; None reads ``zoo.serving.fleet.spawn_backend``
    (enum-validated by the config layer: local | manifest |
    remote)."""
    if name is None:
        from analytics_zoo_tpu.common.config import get_config

        name = str(get_config().get("zoo.serving.fleet.spawn_backend",
                                    "local"))
    if name == "local":
        return LocalSpawnBackend()
    if name == "manifest":
        return ManifestSpawnBackend()
    if name == "remote":
        return RemoteSpawnBackend()
    raise ValueError(
        f"unknown spawn backend {name!r}: expected local | manifest "
        "| remote")
