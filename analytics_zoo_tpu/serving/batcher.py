"""Micro-batchers with bounded backpressure.

The analog of ``ClusterServingInference`` batching
(ref: zoo/.../serving/engine/ClusterServingInference.scala:33-160 --
groups up to ``batchSize`` requests per inference call; Flink supplied
backpressure upstream, here the bounded input queue does, SURVEY.md
section 7 "hard parts: serving ... our batcher must implement it").

Two policies:

- :class:`MicroBatcher` -- the fixed size/timeout policy: close a batch
  on ``batch_size`` reached or ``timeout_ms`` after the first item.
- :class:`AdaptiveBatcher` -- size OR deadline close with both knobs
  adapted to observed queue depth (the batch-assembly policy result of
  arXiv:2605.25645: size *and* deadline dominate serving efficiency):

  * **deadline tightens when the queue is shallow** -- waiting the full
    linger for stragglers that are not coming only adds latency, so the
    linger shrinks toward ``min_timeout_ms`` as depth drops;
  * **the cap grows when backlog builds** -- enough waiting requests to
    fill a larger device bucket means a bigger batch amortizes
    per-dispatch overhead and drains the backlog; grown caps are
    snapped to the power-of-two bucket ladder of
    ``InferenceModel.predict`` so no new XLA shapes are introduced.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from analytics_zoo_tpu.obs.events import emit as emit_event
from analytics_zoo_tpu.obs.metrics import get_registry
from analytics_zoo_tpu.serving.chaos import chaos_point

# why a batch closed, process-wide (obs registry): "size" = cap
# reached, "deadline" = linger expired -- the ratio is the first thing
# to read when tuning batch_size/timeout_ms against live traffic
_M_CLOSES = get_registry().counter(
    "zoo_serving_batch_close_total",
    "Micro-batches closed, by close reason", labelnames=("reason",))


def _bucket(n: int) -> int:
    """Power-of-two bucket ladder (mirrors inference_model._bucket; kept
    local so the batcher never imports jax)."""
    b = 1
    while b < n:
        b *= 2
    return b


class MicroBatcher:
    """Pulls items from a queue-like (``get(timeout)``), groups up to
    ``batch_size`` within ``timeout_ms`` of the first item."""

    def __init__(self, queue, batch_size: int = 8,
                 timeout_ms: float = 5.0):
        self.queue = queue
        self.batch_size = batch_size
        self.timeout_ms = timeout_ms

    def next_batch(self, wait_timeout: Optional[float] = 1.0
                   ) -> List[Any]:
        chaos_point("pull")  # queue-stall / crash injection seam
        first = self.queue.get(timeout=wait_timeout)
        if first is None:
            return []
        batch = [first]
        deadline = time.time() + self.timeout_ms / 1000.0
        while len(batch) < self.batch_size:
            remaining = deadline - time.time()
            if remaining <= 0:
                break
            item = self.queue.get(timeout=remaining)
            if item is None:
                break
            batch.append(item)
        _M_CLOSES.labels(reason="size" if len(batch) >= self.batch_size
                         else "deadline").inc()
        return batch

    def stats(self) -> Dict[str, Any]:
        return {}


class AdaptiveBatcher(MicroBatcher):
    """Deadline/size micro-batcher whose cap and linger track queue
    depth (policy described in the module docstring).

    Args:
      queue: queue-like with ``get(timeout)``; ``__len__`` (depth) and
        ``get_many(n)`` are used when available.
      batch_size: base cap -- the micro-batch size under normal load.
      timeout_ms: maximum linger after the first item of a batch.
      min_timeout_ms: linger floor the deadline tightens toward when
        the queue is empty behind the first item.
      max_batch_size: ceiling the cap may grow to under backlog
        (bucket-snapped); <= ``batch_size`` disables growth.
    """

    def __init__(self, queue, batch_size: int = 8,
                 timeout_ms: float = 5.0,
                 min_timeout_ms: Optional[float] = None,
                 max_batch_size: Optional[int] = None):
        super().__init__(queue, batch_size=batch_size,
                         timeout_ms=timeout_ms)
        self.min_timeout_ms = (timeout_ms * 0.2
                               if min_timeout_ms is None
                               else min(min_timeout_ms, timeout_ms))
        if max_batch_size is None:
            max_batch_size = _bucket(4 * batch_size)
        self.max_batch_size = max(batch_size, int(max_batch_size))
        self._lock = threading.Lock()
        self._closes: Dict[str, int] = {"size": 0, "deadline": 0}
        self._occupancy_sum = 0
        self._batches = 0
        self._depth_sum = 0
        self._last_cap = batch_size
        self._last_linger_ms = timeout_ms
        # depth observed behind the latest batch's first item; the
        # worker's queue_depth gauge reads this instead of issuing a
        # second len() (one broker RPC per pull on TcpQueue backends)
        self.last_depth = -1

    # ---------------------------------------------------------- policy --
    def _queue_depth(self) -> int:
        try:
            return len(self.queue)
        except Exception:  # depth-less backends: fixed policy
            return -1

    def _policy(self, depth: int):
        """(cap, linger_seconds) for the batch being assembled, given
        the queue depth observed behind its first item."""
        base = self.batch_size
        if depth < 0:
            return base, self.timeout_ms / 1000.0
        cap = base
        if depth + 1 > base and self.max_batch_size > base:
            # backlog covers a bigger bucket: grow, snapped to the
            # ladder so padded batch shapes stay on already-compiled
            # buckets (never a new XLA shape from growth). Grow to the
            # largest bucket the KNOWN backlog fills -- the covering
            # bucket would leave the batch short and linger the full
            # deadline for stragglers that may never come
            full = _bucket(depth + 1)
            if full > depth + 1:
                full //= 2
            cap = max(base, min(self.max_batch_size, full))
        # shallow queue: tighten the linger -- with depth d items
        # already waiting, only (base - 1 - d) stragglers could improve
        # occupancy, so scale the linger by how full the batch can get
        frac = min(1.0, depth / max(1, base - 1))
        linger_ms = (self.min_timeout_ms
                     + (self.timeout_ms - self.min_timeout_ms) * frac)
        return cap, linger_ms / 1000.0

    # ------------------------------------------------------------ pull --
    def next_batch(self, wait_timeout: Optional[float] = 1.0
                   ) -> List[Any]:
        chaos_point("pull")  # queue-stall / crash injection seam
        first = self.queue.get(timeout=wait_timeout)
        if first is None:
            return []
        depth = self._queue_depth()
        cap, linger = self._policy(depth)
        batch = [first]
        if len(batch) < cap and hasattr(self.queue, "get_many"):
            batch.extend(self.queue.get_many(cap - 1))
        deadline = time.monotonic() + linger
        while len(batch) < cap:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            item = self.queue.get(timeout=remaining)
            if item is None:
                break
            batch.append(item)
        reason = "size" if len(batch) >= cap else "deadline"
        _M_CLOSES.labels(reason=reason).inc()
        with self._lock:
            self._closes[reason] += 1
            self._occupancy_sum += len(batch)
            self._batches += 1
            self._depth_sum += max(0, depth)
            prev_cap = self._last_cap
            self._last_cap = cap
            self._last_linger_ms = linger * 1000.0
            self.last_depth = depth
        if cap != prev_cap:
            # policy transitions only (a handful per load swing, never
            # per batch): the event log shows WHEN the batcher grew
            # into a bigger bucket -- the context for occupancy and
            # close-reason shifts on the dashboard
            emit_event("batch_cap_change", "serving", cap=cap,
                       prev=prev_cap, depth=depth)
        return batch

    def stats(self) -> Dict[str, Any]:
        """Close-reason counts + occupancy/depth means, for
        ``ServingWorker.metrics()``."""
        with self._lock:
            n = max(1, self._batches)
            return {
                "batches": self._batches,
                "close_size": self._closes["size"],
                "close_deadline": self._closes["deadline"],
                "mean_occupancy": self._occupancy_sum / n,
                "mean_queue_depth": self._depth_sum / n,
                "last_cap": self._last_cap,
                "last_linger_ms": self._last_linger_ms,
                "max_batch_size": self.max_batch_size,
            }
