"""Micro-batcher with bounded backpressure.

The analog of ``ClusterServingInference`` batching
(ref: zoo/.../serving/engine/ClusterServingInference.scala:33-160 --
groups up to ``batchSize`` requests per inference call; Flink supplied
backpressure upstream, here the bounded input queue does, SURVEY.md
section 7 "hard parts: serving ... our batcher must implement it").
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional, Tuple


class MicroBatcher:
    """Pulls items from a queue-like (``get(timeout)``), groups up to
    ``batch_size`` within ``timeout_ms`` of the first item."""

    def __init__(self, queue, batch_size: int = 8,
                 timeout_ms: float = 5.0):
        self.queue = queue
        self.batch_size = batch_size
        self.timeout_ms = timeout_ms

    def next_batch(self, wait_timeout: Optional[float] = 1.0
                   ) -> List[Any]:
        first = self.queue.get(timeout=wait_timeout)
        if first is None:
            return []
        batch = [first]
        deadline = time.time() + self.timeout_ms / 1000.0
        while len(batch) < self.batch_size:
            remaining = deadline - time.time()
            if remaining <= 0:
                break
            item = self.queue.get(timeout=remaining)
            if item is None:
                break
            batch.append(item)
        return batch
