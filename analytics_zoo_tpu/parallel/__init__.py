"""Unified SPMD parallelism layer.

The reference implements five coexisting data-parallel communication
backends (Spark BlockManager allreduce, TF collectives, Gloo, Horovod,
MXNet PS-Lite -- SURVEY.md section 2.3). On TPU there is exactly one:
XLA collectives over ICI/DCN, driven by ``jax.sharding.Mesh`` +
``jax.jit``/``jax.shard_map``. This package provides:

- ``mesh``        -- device-mesh construction (single host, multi-host hybrid
                     ICI x DCN meshes)
- ``sharding``    -- NamedSharding helpers, batch/param placement
- ``collectives`` -- psum/all_gather/reduce_scatter/ppermute wrappers
- ``ring_attention`` -- sequence-parallel blockwise attention over a ring
                     (new capability; the reference has no long-context
                     support, SURVEY.md section 5)
- ``pipeline``    -- pipeline-parallel stage execution via collective permute
"""

from analytics_zoo_tpu.parallel.mesh import (  # noqa: F401
    config_axis,
    create_mesh,
    default_mesh,
    mesh_axis_size,
    shard_map,
)
from analytics_zoo_tpu.parallel.sharding import (  # noqa: F401
    named_sharding,
    replicated,
    shard_batch,
    shard_pytree,
    data_parallel_spec,
)
from analytics_zoo_tpu.parallel import collectives  # noqa: F401
from analytics_zoo_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    zigzag_ring_attention,
    ring_self_attention,
)
from analytics_zoo_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_apply,
    pipeline_train_step,
)
from analytics_zoo_tpu.parallel.recipes import (  # noqa: F401
    embedding_tp_spec,
    pipeline_stage_spec,
    transformer_tp_spec,
)
from analytics_zoo_tpu.parallel.staged import (  # noqa: F401
    PipelinedTransformerLM,
)
