"""Ring attention: exact sequence-parallel attention over a device ring.

New capability relative to the reference, which has *no* long-context or
sequence-parallel support (SURVEY.md section 5: "Long-context / sequence
parallelism: Absent"; its TransformerLayer/BERT use full O(L^2) attention
on one device, ref: zoo/.../keras/layers/TransformerLayer.scala).

Design (blockwise online-softmax, Liu et al. ring attention):
- Q, K, V are sharded along the sequence axis of the mesh; each device
  holds one block of queries and one block of keys/values.
- N ring steps: each device computes flash-style partial attention of its
  Q block against the resident K/V block while ``ppermute``-ing K/V to the
  next device -- comm overlaps compute on TPU (ICI is bidirectional).
- Running (max, sum, acc) accumulators give the exact softmax; causal
  masking uses global position offsets derived from the ring step.

The inner block kernel is plain jnp (XLA fuses it well on TPU); swap-in of
the Pallas flash kernel for the intra-block computation happens in
``analytics_zoo_tpu.ops`` when block sizes warrant it.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.parallel.collectives import axis_size
from analytics_zoo_tpu.parallel.mesh import config_axis, shard_map

NEG_INF = -1e30


def _block_attn(q, k, v, bias, q_offset, kv_offset, causal, scale,
                m_prev, l_prev, o_prev, dropout_rate=0.0,
                dropout_key=None):
    """One flash-attention block update with online softmax.

    q: [B, Lq, H, D]; k, v: [B, Lkv, H, D]; accumulators carry the running
    max ``m``, normalizer ``l`` and unnormalized output ``o``.

    Attention-probability dropout composes exactly with the streaming
    softmax: standard attention computes ``dropout(softmax(s)) @ v``,
    whose denominator is dropout-free -- so the Bernoulli mask applies
    only to the NUMERATOR accumulation (``p @ v``) while ``l`` keeps
    every exp term. Each (q-block, kv-block) tile draws from its own
    key, so every global prob element is dropped independently exactly
    once.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])[:, None]
        k_pos = kv_offset + jnp.arange(k.shape[1])[None, :]
        mask = q_pos >= k_pos  # [Lq, Lkv]
        s = jnp.where(mask[None, None], s, NEG_INF)

    m_cur = jnp.max(s, axis=-1)                      # [B, H, Lq]
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked rows (all NEG_INF): exp underflows to 0 safely
    p = jnp.exp(s - m_new[..., None])                # [B, H, Lq, Lkv]
    l_corr = jnp.exp(m_prev - m_new)
    l_new = l_corr * l_prev + jnp.sum(p, axis=-1)
    p_num = p
    if dropout_key is not None and dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_rate,
                                    p.shape)
        p_num = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p_num.astype(v.dtype), v)
    o_new = o_prev * l_corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _ring_attn_local(q, k, v, rng, axis_name: str, causal: bool,
                     scale: Optional[float], dropout_rate: float = 0.0,
                     batch_axis=None):  # str | tuple[str, ...] | None
    """Per-device body, runs under shard_map with seq-sharded q/k/v."""
    n_dev = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if rng is not None and batch_axis is not None:
        # each batch shard draws its own masks: without this fold the
        # replicated rng would repeat one mask across data-parallel
        # shards (correlated dropout that changes with dp degree)
        rng = jax.random.fold_in(rng, lax.axis_index(batch_axis))
    b, lq, h, d = q.shape
    lkv = k.shape[1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)

    q32 = q.astype(jnp.float32)
    m = jnp.full((b, h, lq), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((b, h, lq), dtype=jnp.float32)
    o = jnp.zeros((b, lq, h, d), dtype=jnp.float32)
    q_offset = idx * lq

    def step(carry, i):
        m, l, o, k_blk, v_blk = carry
        # K/V block currently resident came from device (idx - i) mod n
        kv_owner = (idx - i) % n_dev
        kv_offset = kv_owner * lkv
        # key per (q-block, kv-block) tile: deterministic in the GLOBAL
        # tile coordinates, so the mask pattern is independent of how
        # the ring schedule visits tiles
        key = (jax.random.fold_in(rng, idx * n_dev + kv_owner)
               if rng is not None else None)
        m, l, o = _block_attn(q32, k_blk.astype(jnp.float32),
                              v_blk.astype(jnp.float32), None,
                              q_offset, kv_offset, causal, scale, m, l, o,
                              dropout_rate=dropout_rate, dropout_key=key)
        perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (m, l, o, k_nxt, v_nxt), None

    (m, l, o, _, _), _ = lax.scan(step, (m, l, o, k, v),
                                  jnp.arange(n_dev))
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _ring_shard_call(local_fn, q, k, v, mesh, axis_name, qkv_spec,
                     dropout_rate, dropout_rng, **fn_kwargs):
    """Shared wrapper for the ring bodies: derives the default spec,
    detects the batch-sharding axis (per-shard dropout keys), builds
    the shard_map and threads the optional rng operand."""
    if qkv_spec is None:
        # batch dim shards over the configured data axis when the mesh
        # carries it (zoo.mesh.axis.data; reconciled, not hard-coded)
        data_ax = config_axis("data")
        data = data_ax if data_ax in mesh.axis_names else None
        qkv_spec = P(data, axis_name, None, None)
    dropping = dropout_rng is not None and dropout_rate > 0.0
    batch_axis = qkv_spec[0] if len(qkv_spec) > 0 else None
    if isinstance(batch_axis, (tuple, list)):
        # tuple-sharded batch dim, e.g. P(('data','model'), ...):
        # lax.axis_index accepts the tuple and yields the linearized
        # shard index, so every batch shard still folds a distinct
        # dropout key (a bare-string-only check would silently repeat
        # one mask across shards -- correlated dropout)
        batch_axis = tuple(batch_axis) if batch_axis and all(
            isinstance(a, str) for a in batch_axis) else None
    elif not isinstance(batch_axis, str):
        batch_axis = None
    extra = (dropout_rng,) if dropping else ()
    fn = shard_map(
        partial(local_fn, axis_name=axis_name,
                dropout_rate=dropout_rate if dropping else 0.0,
                batch_axis=batch_axis if dropping else None,
                **({} if dropping else {"rng": None}), **fn_kwargs),
        mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec) + (P(),) * len(extra),
        out_specs=qkv_spec)
    return fn(q, k, v, *extra)


def ring_attention(q, k, v, mesh: Mesh,
                   axis_name: Optional[str] = None,
                   causal: bool = False, scale: Optional[float] = None,
                   qkv_spec: Optional[P] = None,
                   dropout_rate: float = 0.0, dropout_rng=None):
    """Exact attention with sequence dim sharded over ``axis_name``
    (default: the ``zoo.mesh.axis.sequence`` config key -> ``"seq"``).

    Args:
      q, k, v: [batch, seq, heads, head_dim] (global arrays or to-be-sharded
        host arrays; seq must divide by the axis size).
      mesh: mesh containing ``axis_name``.
      causal: apply causal masking using global positions.
      qkv_spec: PartitionSpec for q/k/v; default shards batch over 'data'
        (if present in the mesh) and seq over ``axis_name``.
      dropout_rate / dropout_rng: attention-probability dropout; each
        (q-block, kv-block) tile folds its own key from ``dropout_rng``
        so the ring schedule applies exact elementwise prob dropout
        (see ``_block_attn``). Pass a key only when training.
    """
    if axis_name is None:
        axis_name = config_axis("sequence", fallback="seq")
    return _ring_shard_call(_ring_attn_local, q, k, v, mesh,
                            axis_name, qkv_spec, dropout_rate,
                            dropout_rng, causal=causal, scale=scale)


def ring_self_attention(x, wq, wk, wv, wo, num_heads: int, mesh: Mesh,
                        axis_name: Optional[str] = None,
                        causal: bool = False):
    """Convenience: project -> ring attention -> output projection.

    x: [batch, seq, dim]; w*: [dim, dim]. Projections are local (sequence
    dim untouched), so only the attention itself communicates.
    """
    b, s, dim = x.shape
    head_dim = dim // num_heads

    def proj(w):
        return jnp.einsum("bsd,de->bse", x, w).reshape(b, s, num_heads,
                                                       head_dim)

    out = ring_attention(proj(wq), proj(wk), proj(wv), mesh,
                         axis_name=axis_name, causal=causal)
    out = out.reshape(b, s, dim)
    return jnp.einsum("bsd,de->bse", out, wo)


# ===================================================== zigzag ring ====
# Load-balanced causal schedule. The contiguous ring wastes ~half its
# FLOPs under causal masking: device 0's queries attend almost nothing
# (its tile is fully masked on n-1 of n ring steps, computed then
# discarded) while device n-1 computes every step. The zigzag layout
# (Llama-3-style "zig-zag" / striped ring attention) splits the
# sequence into 2n chunks and gives device i the PAIR (i, 2n-1-i) --
# one early (light) and one late (heavy) chunk -- so every device does
# the same ~2 chunk-tiles of unmasked work per step, and fully-masked
# tiles are skipped with a per-core `lax.cond` instead of computed.
# Net: ~2x less attention compute than the contiguous causal ring at
# the same exactness (online softmax over the same global tiles).


def _zigzag_chunk_perm(seq_len: int, n_dev: int):
    """Row permutation mapping the natural sequence layout to the
    zigzag layout (device i holds chunks i and 2n-1-i, concatenated).
    Returns (perm, inverse_perm)."""
    if seq_len % (2 * n_dev):
        raise ValueError(f"zigzag needs seq_len divisible by 2*n_dev "
                         f"({2 * n_dev}), got {seq_len}")
    c = seq_len // (2 * n_dev)
    order = []
    for i in range(n_dev):
        order.extend(range(i * c, (i + 1) * c))
        order.extend(range((2 * n_dev - 1 - i) * c,
                           (2 * n_dev - i) * c))
    perm = np.asarray(order)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(perm.size)
    return perm, inv


def _zigzag_local(q, k, v, rng, axis_name: str, scale: Optional[float],
                  dropout_rate: float = 0.0,
                  batch_axis=None):  # str | tuple[str, ...] | None
    """Per-device zigzag body. Local q/k/v rows are the chunk pair
    (idx, 2n-1-idx); each ring step computes only the causally-needed
    chunk products:

      A: q_early x kv_early   -- needed iff kv owner <= idx
      B: q_late  x kv_early   -- always needed (late attends all early)
      C: q_late  x kv_late    -- needed iff kv owner >= idx

    (q_early x kv_late is never needed: every late chunk sits after
    every early chunk.) A and C toggle via per-core ``lax.cond``, so
    masked tiles cost a branch, not a matmul."""
    n_dev = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if rng is not None and batch_axis is not None:
        rng = jax.random.fold_in(rng, lax.axis_index(batch_axis))
    b, l2, h, d = q.shape
    c = l2 // 2
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    q32 = q.astype(jnp.float32)
    q_e, q_l = q32[:, :c], q32[:, c:]
    off_qe = idx * c
    off_ql = (2 * n_dev - 1 - idx) * c

    def empty_state():
        return (jnp.full((b, h, c), NEG_INF, jnp.float32),
                jnp.zeros((b, h, c), jnp.float32),
                jnp.zeros((b, c, h, d), jnp.float32))

    def tile(qc, kc, vc, q_off, kv_off, q_chunk, kv_chunk, state):
        m, lsum, acc = state
        key = None
        if rng is not None and dropout_rate > 0.0:
            # tile key in GLOBAL chunk coordinates (schedule-invariant)
            key = jax.random.fold_in(
                rng, q_chunk * 2 * n_dev + kv_chunk)
        return _block_attn(qc, kc.astype(jnp.float32),
                           vc.astype(jnp.float32), None, q_off, kv_off,
                           True, scale, m, lsum, acc,
                           dropout_rate=dropout_rate, dropout_key=key)

    def step(carry, s):
        st_e, st_l, k_blk, v_blk = carry
        owner = (idx - s) % n_dev
        kv_e, kv_l = k_blk[:, :c], k_blk[:, c:]
        v_e, v_l = v_blk[:, :c], v_blk[:, c:]
        off_ke = owner * c
        off_kl = (2 * n_dev - 1 - owner) * c

        st_e = lax.cond(
            owner <= idx,
            lambda st: tile(q_e, kv_e, v_e, off_qe, off_ke,
                            idx, owner, st),
            lambda st: st, st_e)
        st_l = tile(q_l, kv_e, v_e, off_ql, off_ke,
                    2 * n_dev - 1 - idx, owner, st_l)
        st_l = lax.cond(
            owner >= idx,
            lambda st: tile(q_l, kv_l, v_l, off_ql, off_kl,
                            2 * n_dev - 1 - idx,
                            2 * n_dev - 1 - owner, st),
            lambda st: st, st_l)
        perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (st_e, st_l, k_nxt, v_nxt), None

    init = (empty_state(), empty_state(), k, v)
    (st_e, st_l, _, _), _ = lax.scan(step, init, jnp.arange(n_dev))

    def finish(state):
        m, lsum, acc = state
        lsum = jnp.maximum(lsum, 1e-30)
        return acc / lsum.transpose(0, 2, 1)[..., None]

    out = jnp.concatenate([finish(st_e), finish(st_l)], axis=1)
    return out.astype(q.dtype)


def zigzag_ring_attention(q, k, v, mesh: Mesh,
                          axis_name: Optional[str] = None,
                          scale: Optional[float] = None,
                          qkv_spec: Optional[P] = None,
                          dropout_rate: float = 0.0, dropout_rng=None,
                          pre_permuted: bool = False):
    """Exact CAUSAL attention over a zigzag-balanced ring -- ~2x less
    compute than :func:`ring_attention` with ``causal=True`` on long
    sequences (see the schedule note above). Same contract: q/k/v are
    [batch, seq, heads, head_dim] in natural sequence order; the
    zigzag permutation is applied (and inverted) internally.

    Layout cost: on a seq-sharded mesh the entry/exit permutations are
    cross-device reshards (3 in, 1 out per call). For a deep stack,
    hoist the layout once instead: every non-attention layer (FFN, LN,
    residual) is permutation-equivariant along the sequence, so a
    model may permute its hidden states with ``_zigzag_chunk_perm``
    once after the position embedding, run every attention call with
    ``pre_permuted=True`` (inputs/outputs stay in zigzag layout), and
    invert once at the top.

    Non-causal attention has no masked tiles to skip; use
    :func:`ring_attention` there.
    """
    if axis_name is None:
        axis_name = config_axis("sequence", fallback="seq")
    n_dev = mesh.shape[axis_name]
    seq_len = q.shape[1]
    perm, inv = _zigzag_chunk_perm(seq_len, n_dev)
    if pre_permuted:
        return _ring_shard_call(_zigzag_local, q, k, v, mesh,
                                axis_name, qkv_spec, dropout_rate,
                                dropout_rng, scale=scale)
    out = _ring_shard_call(_zigzag_local, q[:, perm], k[:, perm],
                           v[:, perm], mesh, axis_name, qkv_spec,
                           dropout_rate, dropout_rng, scale=scale)
    return out[:, inv]
