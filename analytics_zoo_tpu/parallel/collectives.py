"""Collective primitives for use inside ``shard_map``-ed functions.

One set of XLA collectives replaces the reference's five transport stacks
(Spark BlockManager shuffle+broadcast, TF RING collectives, Gloo, Horovod,
MXNet PS-Lite -- SURVEY.md section 2.3). The semantics of BigDL's
``AllReduceParameter`` (reduce-scatter then re-fetch == allreduce,
ref: docs/docs/wp-bigdl.md:138-160) are exactly ``psum``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax


def all_reduce_sum(x: Any, axis_name: str) -> Any:
    return jax.tree_util.tree_map(lambda t: lax.psum(t, axis_name), x)


def all_reduce_mean(x: Any, axis_name: str) -> Any:
    return jax.tree_util.tree_map(lambda t: lax.pmean(t, axis_name), x)


def all_gather(x: Any, axis_name: str, axis: int = 0, tiled: bool = True) -> Any:
    return jax.tree_util.tree_map(
        lambda t: lax.all_gather(t, axis_name, axis=axis, tiled=tiled), x)


def reduce_scatter(x: Any, axis_name: str, axis: int = 0) -> Any:
    return jax.tree_util.tree_map(
        lambda t: lax.psum_scatter(t, axis_name, scatter_dimension=axis,
                                   tiled=True), x)


def ring_permute(x: Any, axis_name: str, shift: int = 1) -> Any:
    """Send to the next device on the ring (rank -> rank+shift mod N)."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.tree_util.tree_map(
        lambda t: lax.ppermute(t, axis_name, perm), x)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str) -> int:
    """Static size of a named mesh axis from inside a mapped body.
    jax 0.4.x has no ``lax.axis_size``; ``psum(1, axis)`` is the
    classic spelling and constant-folds to a python int either way."""
    size = getattr(lax, "axis_size", None)
    if size is not None:
        return size(axis_name)
    return lax.psum(1, axis_name)


def _q8(t: jnp.ndarray):
    """Symmetric per-shard int8 quantization: (int8 payload, f32 scale).
    The scale floor keeps all-zero shards finite (0/eps = 0, exact)."""
    scale = jnp.maximum(jnp.max(jnp.abs(t)), 1e-30) / 127.0
    q = jnp.clip(jnp.round(t / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def quantized_psum(x: Any, axis_name: str) -> Any:
    """Approximate ``psum`` that moves int8 instead of f32/bf16 across
    the interconnect (the EQuARX idiom, arXiv:2506.17615: quantized
    AllReduce built for exactly the TPU tensor-parallel serving regime).

    Each shard quantizes its operand symmetrically to int8 with one
    per-shard scale, all-gathers the int8 payloads (+ the tiny scale
    vector), then dequantizes and reduces locally in the operand dtype
    -- so the cross-chip bytes are ~1/4 of an f32 ring allreduce (1/2
    of bf16) at the cost of a bounded relative error (~1/127 per
    shard's contribution). Exact ``all_reduce_sum`` stays the default
    everywhere; this is the opt-in wire-compression path
    (``zoo.serving.shard.quantized_collectives``)."""
    def one(t):
        q, scale = _q8(t)
        qs = lax.all_gather(q, axis_name, axis=0, tiled=False)
        ss = lax.all_gather(scale, axis_name, axis=0, tiled=False)
        deq = qs.astype(t.dtype) * ss.reshape(
            (-1,) + (1,) * t.ndim).astype(t.dtype)
        return jnp.sum(deq, axis=0)

    return jax.tree_util.tree_map(one, x)


def quantized_all_gather(x: Any, axis_name: str, axis: int = 0) -> Any:
    """Approximate tiled ``all_gather`` moving int8 payloads + per-shard
    scales instead of full-precision shards (the same EQuARX wire
    compression applied to a gather: ~1/4 the cross-chip bytes of f32).
    Shards concatenate along ``axis`` in shard order, exactly like
    ``lax.all_gather(..., tiled=True)``; each shard's slice carries its
    own rescale. The sharded serving layer uses this to re-assemble
    tensor-parallel parameter shards per dispatch
    (:mod:`analytics_zoo_tpu.inference.sharded`)."""
    def one(t):
        q, scale = _q8(t)
        qs = lax.all_gather(q, axis_name, axis=0, tiled=False)
        ss = lax.all_gather(scale, axis_name, axis=0, tiled=False)
        deq = qs.astype(t.dtype) * ss.reshape(
            (-1,) + (1,) * t.ndim).astype(t.dtype)
        # [N, ...local...] -> concatenation along `axis`, shard-major
        # (the NamedSharding slice order)
        out = jnp.moveaxis(deq, 0, axis)
        shape = (t.shape[:axis] + (t.shape[axis] * deq.shape[0],)
                 + t.shape[axis + 1:])
        return out.reshape(shape)

    return jax.tree_util.tree_map(one, x)


def global_norm(tree: Any, axis_name: str = None) -> jnp.ndarray:
    """L2 norm over an entire pytree (used for global gradient clipping,
    matching the reference's global-gradient L2 clipping semantics,
    ref: pyzoo/zoo/tfpark/tf_optimizer.py:392-396).

    When the tree's leaves are *sharded* across a mesh axis inside a
    ``shard_map`` body (e.g. FSDP), pass ``axis_name`` so the squared sum
    is psum-reduced to the true global norm instead of a per-shard norm.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    sq = sum(jnp.sum(jnp.square(l)) for l in leaves)
    if axis_name is not None:
        sq = lax.psum(sq, axis_name)
    return jnp.sqrt(sq)
