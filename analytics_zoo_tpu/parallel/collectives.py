"""Collective primitives for use inside ``shard_map``-ed functions.

One set of XLA collectives replaces the reference's five transport stacks
(Spark BlockManager shuffle+broadcast, TF RING collectives, Gloo, Horovod,
MXNet PS-Lite -- SURVEY.md section 2.3). The semantics of BigDL's
``AllReduceParameter`` (reduce-scatter then re-fetch == allreduce,
ref: docs/docs/wp-bigdl.md:138-160) are exactly ``psum``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax


def all_reduce_sum(x: Any, axis_name: str) -> Any:
    return jax.tree_util.tree_map(lambda t: lax.psum(t, axis_name), x)


def all_reduce_mean(x: Any, axis_name: str) -> Any:
    return jax.tree_util.tree_map(lambda t: lax.pmean(t, axis_name), x)


def all_gather(x: Any, axis_name: str, axis: int = 0, tiled: bool = True) -> Any:
    return jax.tree_util.tree_map(
        lambda t: lax.all_gather(t, axis_name, axis=axis, tiled=tiled), x)


def reduce_scatter(x: Any, axis_name: str, axis: int = 0) -> Any:
    return jax.tree_util.tree_map(
        lambda t: lax.psum_scatter(t, axis_name, scatter_dimension=axis,
                                   tiled=True), x)


def ring_permute(x: Any, axis_name: str, shift: int = 1) -> Any:
    """Send to the next device on the ring (rank -> rank+shift mod N)."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.tree_util.tree_map(
        lambda t: lax.ppermute(t, axis_name, perm), x)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str) -> int:
    return lax.axis_size(axis_name)


def global_norm(tree: Any, axis_name: str = None) -> jnp.ndarray:
    """L2 norm over an entire pytree (used for global gradient clipping,
    matching the reference's global-gradient L2 clipping semantics,
    ref: pyzoo/zoo/tfpark/tf_optimizer.py:392-396).

    When the tree's leaves are *sharded* across a mesh axis inside a
    ``shard_map`` body (e.g. FSDP), pass ``axis_name`` so the squared sum
    is psum-reduced to the true global norm instead of a per-shard norm.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    sq = sum(jnp.sum(jnp.square(l)) for l in leaves)
    if axis_name is not None:
        sq = lax.psum(sq, axis_name)
    return jnp.sqrt(sq)
