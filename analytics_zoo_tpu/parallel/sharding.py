"""Sharding placement helpers.

The analog of the reference's parameter/batch distribution machinery:
BigDL's ``AllReduceParameter`` partitions the flat parameter vector across
N sync tasks and Spark ships batch partitions to executors
(ref: zoo/.../keras/models/Topology.scala:1204, docs/docs/wp-bigdl.md:138-160).
Here placement is declarative: a ``NamedSharding`` per array, and XLA
inserts the collectives.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.parallel.mesh import DATA_AXIS


def named_sharding(mesh: Mesh, *axes) -> NamedSharding:
    """``named_sharding(mesh, 'data', None)`` -> NamedSharding(mesh, P('data', None))."""
    return NamedSharding(mesh, P(*axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_parallel_spec(x: Any, axis: str = DATA_AXIS) -> P:
    """PartitionSpec sharding the leading (batch) dim, for one array."""
    ndim = np.ndim(x)
    if ndim == 0:
        return P()
    return P(axis, *([None] * (ndim - 1)))


def shard_batch(batch: Any, mesh: Optional[Mesh] = None,
                axis: str = DATA_AXIS) -> Any:
    """Place a host batch pytree onto the mesh, sharded along ``axis``.

    Single-process: a plain ``device_put`` with a batch-sharded
    NamedSharding. Multi-process: each host holds its local slice of the
    global batch and we assemble a global array via
    ``jax.make_array_from_process_local_data`` (the analog of Spark
    shipping RDD partitions to executors -- except zero-copy per host).
    """
    from analytics_zoo_tpu.parallel.mesh import default_mesh

    mesh = mesh or default_mesh()

    def place(x):
        x = np.asarray(x)
        sharding = NamedSharding(mesh, data_parallel_spec(x, axis))
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(sharding, x)
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(place, batch)


def shard_pytree(tree: Any, mesh: Optional[Mesh] = None,
                 spec_fn=None) -> Any:
    """Place a pytree (e.g. params) onto the mesh.

    ``spec_fn(path, leaf) -> PartitionSpec`` chooses per-leaf placement;
    default is full replication (the reference replicates the model on every
    executor, ref: Topology.scala:1145-1548 cached model replicas).
    """
    from analytics_zoo_tpu.parallel.mesh import default_mesh

    mesh = mesh or default_mesh()

    def place(path, x):
        spec = spec_fn(path, x) if spec_fn is not None else P()
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, tree)


def batch_spec_tree(batch: Any, axis: str = DATA_AXIS) -> Any:
    """Pytree of PartitionSpecs sharding every leaf's leading dim."""
    return jax.tree_util.tree_map(
        lambda x: data_parallel_spec(x, axis), batch)


def gather_to_host(tree: Any) -> Any:
    """Materialize a pytree on every host as numpy.

    Leaves sharded across hosts (not fully addressable) are assembled
    into their global value with a collective ``process_allgather``;
    fully-addressable leaves (host-local or replicated) are fetched
    directly -- allgathering those would wrongly stack/concatenate the
    per-process copies. Collective: every process must call this with
    the same tree structure.
    """
    if jax.process_count() <= 1:
        return jax.device_get(tree)
    from jax.experimental import multihost_utils

    def leaf(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return multihost_utils.process_allgather(x, tiled=True)
        return jax.device_get(x)

    return jax.tree_util.tree_map(leaf, tree)
