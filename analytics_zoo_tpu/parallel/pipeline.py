"""Pipeline parallelism via collective permute.

New capability relative to the reference (data-parallel only, SURVEY.md
section 2.3). GPipe-style schedule expressed SPMD: every device holds one
stage's parameters; microbatches flow around the ring with ``ppermute``
inside a ``lax.scan``. With M microbatches and S stages the schedule runs
M + S - 1 ticks (the classic bubble).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.parallel.collectives import axis_size
from analytics_zoo_tpu.parallel.mesh import config_axis, shard_map


def _pipeline_local(stage_params, microbatches, rng, stage_fn,
                    axis_name: str, n_microbatches: int):
    """Runs on one device holding one stage (shard_map body).

    stage_params: this stage's params (leading stage dim stripped by
      shard_map).
    microbatches: [M, ...] -- replicated on every stage (in_specs=P());
      only stage 0 reads it to inject inputs. This costs S copies of the
      microbatch buffer; acceptable because microbatches are inputs, not
      the (large) inter-stage activations, which stay per-device.
    rng: optional base dropout key (replicated). When set, ``stage_fn``
      receives ``(params, x, mb_idx, stage_id, rng)`` so it can fold a
      deterministic per-(microbatch, layer) key -- the plumbing that
      makes dropout exact-reproducible between the pipeline schedule
      and a sequential run of the same blocks.
    """
    n_stages = axis_size(axis_name)
    stage_id = lax.axis_index(axis_name)
    # shard_map keeps the sharded leading stage dim as size 1; strip it
    stage_params = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    total_ticks = n_microbatches + n_stages - 1
    mb_shape = microbatches.shape[1:]

    state = jnp.zeros(mb_shape, microbatches.dtype)  # current activation
    outputs = jnp.zeros((n_microbatches,) + mb_shape, microbatches.dtype)

    def tick(carry, t):
        state, outputs = carry
        # stage 0 injects microbatch t (if any remain); others use incoming
        inject = microbatches[jnp.minimum(t, n_microbatches - 1)]
        x = jnp.where(stage_id == 0,
                      jnp.where(t < n_microbatches, inject,
                                jnp.zeros_like(inject)),
                      state)
        if rng is None:
            y = stage_fn(stage_params, x)
        else:
            # at tick t this stage processes microbatch t - stage_id
            # (bubble ticks compute on zeros and are never recorded)
            mb_idx = jnp.clip(t - stage_id, 0, n_microbatches - 1)
            y = stage_fn(stage_params, x, mb_idx, stage_id, rng)
        # last stage records its finished microbatch (t - (S-1))
        out_idx = t - (n_stages - 1)
        record = jnp.logical_and(stage_id == n_stages - 1, out_idx >= 0)
        outputs = lax.cond(
            record,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(out_idx, 0), 0),
            lambda o: o, outputs)
        # pass activation to the next stage (ring; wraparound ignored)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        state = lax.ppermute(y, axis_name, perm)
        return (state, outputs), None

    (_, outputs), _ = lax.scan(tick, (state, outputs),
                               jnp.arange(total_ticks))
    # only the last stage recorded anything; psum replicates its buffer
    # (other stages contribute zeros) so out_specs=P() is truthful.
    return lax.psum(outputs, axis_name)


def pipeline_apply(stage_fn: Callable[..., jnp.ndarray],
                   stacked_params: Any, microbatches: jnp.ndarray,
                   mesh: Mesh, axis_name: Optional[str] = None,
                   data_axis: str = None, rng=None) -> jnp.ndarray:
    """Run ``stage_fn`` as an S-stage pipeline over the ``axis_name``
    axis (default: the ``zoo.mesh.axis.pipeline`` config key ->
    ``"pipe"``, so a deployment that renames its pipeline axis sets
    one key instead of threading the name through every call).

    Args:
      stage_fn: (stage_params, activation [*mb_shape]) -> activation; must
        preserve the activation shape/dtype between stages. With ``rng``
        set the signature is (stage_params, activation, mb_idx, stage_id,
        rng) -> activation (fold your per-layer dropout keys from those).
      stacked_params: pytree whose leaves have leading dim S (one slice per
        stage) -- sharded so each device gets its stage.
      microbatches: [M, *mb_shape] microbatch activations.
      mesh: mesh with a pipeline axis of size S.
      data_axis: optional mesh axis to shard the microbatch batch dim
        (``mb_shape[0]``) over -- a combined dp x pp mesh: each data
        shard runs its own pipeline over the same stage parameters.
      rng: optional base dropout key, replicated to every stage.

    Returns [M, *mb_shape]: outputs of the final stage per microbatch.
    """
    if axis_name is None:
        axis_name = config_axis("pipeline", fallback="pipe")
    n_microbatches = microbatches.shape[0]
    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params)
    mb_spec = (P(None, data_axis) if data_axis is not None
               and data_axis in mesh.axis_names else P())
    extra = () if rng is None else (rng,)
    body = partial(_pipeline_local, stage_fn=stage_fn,
                   axis_name=axis_name, n_microbatches=n_microbatches,
                   **({"rng": None} if rng is None else {}))
    fn = shard_map(
        body, mesh,
        in_specs=(param_specs, mb_spec) + (P(),) * len(extra),
        out_specs=mb_spec)
    return fn(stacked_params, microbatches, *extra)


def pipeline_train_step(stage_fn: Callable[[Any, jnp.ndarray],
                                           jnp.ndarray],
                        loss_fn: Callable[[jnp.ndarray, jnp.ndarray],
                                          jnp.ndarray],
                        tx, mesh: Mesh,
                        axis_name: Optional[str] = None,
                        data_axis: str = None):
    """Build a jitted pipeline-parallel TRAINING step.

    The whole GPipe schedule is differentiable (``ppermute``/``scan``/
    ``cond`` all have transposes), so the backward pass is simply the
    reverse pipeline XLA derives -- activations recorded by ``scan`` play
    the role of GPipe's stashed microbatch activations.

    Args:
      stage_fn: (stage_params, activation) -> activation.
      loss_fn: (outputs [M, *mb], targets [M, *mb']) -> scalar.
      tx: optax GradientTransformation applied to the stacked params.
      mesh: mesh with the pipeline axis.

    Returns ``step(stacked_params, opt_state, microbatches, targets) ->
    (params, opt_state, loss)``.
    """
    import optax

    def step(stacked_params, opt_state, microbatches, targets, rng=None):
        def loss(params):
            out = pipeline_apply(stage_fn, params, microbatches, mesh,
                                 axis_name, data_axis=data_axis, rng=rng)
            return loss_fn(out, targets)

        l, grads = jax.value_and_grad(loss)(stacked_params)
        updates, opt_state = tx.update(grads, opt_state, stacked_params)
        params = optax.apply_updates(stacked_params, updates)
        return params, opt_state, l

    return jax.jit(step)
