"""Shipped sharding recipes: megatron-style tensor parallelism for the
transformer family.

New capability relative to the reference (data-parallel only, SURVEY.md
section 2.3). A recipe is a ``param_spec_fn`` for the Estimator: it maps
each parameter (and, transitively, its optimizer moments -- the Estimator
applies the same specs to ``opt_state``) to a ``PartitionSpec`` over the
mesh's model axis. GSPMD then partitions the matmuls and inserts the
collectives; the result is numerically exact (loss parity with the
replicated layout), so the recipe is purely a memory/throughput knob.

Layout (Megatron-LM convention):

- ``qkv`` and ``ffn_in`` kernels: column-parallel (output dim sharded)
  -- each model shard computes its slice of heads / FFN hidden;
- ``proj`` and ``ffn_out`` kernels: row-parallel (input dim sharded)
  -- consumes the sharded activation, XLA inserts the psum;
- embedding tables: vocab-dim sharded;
- LayerNorm / biases of row-parallel layers: replicated.

Works for any model built on ``keras.layers.transformer`` blocks
(TransformerModule, BERTModule and the BERT estimators), whose
parameter names this matches by suffix.
"""

from __future__ import annotations

from typing import Callable, Optional

from jax.sharding import PartitionSpec as P

from analytics_zoo_tpu.parallel.mesh import config_axis


def _path_name(path) -> str:
    return "/".join(str(getattr(k, "key", k)) for k in path).lower()


def transformer_tp_spec(axis: Optional[str] = None,
                        shard_embeddings: bool = True) -> Callable:
    """``param_spec_fn`` sharding transformer blocks over ``axis``
    (default: the ``zoo.mesh.axis.model`` config key -> ``"model"``).

    Pass to ``Estimator(param_spec_fn=transformer_tp_spec())`` together
    with a mesh carrying a model axis, e.g.
    ``create_mesh({"data": 2, "model": 4})``. Composes with data
    parallelism (the batch shards over the data axis independently).
    """
    axis = axis if axis is not None else config_axis("model")

    def spec(path, leaf) -> P:
        name = _path_name(path)
        ndim = getattr(leaf, "ndim", 0)
        # fused qkv kernel is [H, 3, H] (DenseGeneral): shard the
        # per-section output dim so tp slices stay head-aligned
        if name.endswith("qkv/kernel") and ndim == 3:
            return P(None, None, axis)
        if name.endswith("qkv/bias") and ndim == 2:
            return P(None, axis)
        if ndim == 2:
            # column-parallel: output dim sharded
            if name.endswith("ffn_in/kernel"):
                return P(None, axis)
            # row-parallel: input dim sharded
            if name.endswith("proj/kernel") or name.endswith(
                    "ffn_out/kernel"):
                return P(axis, None)
            if shard_embeddings and "embed" in name:
                # vocab/position-dim sharded tables (gathers become
                # sharded lookups + psum)
                return P(axis, None)
        if ndim == 1 and name.endswith("ffn_in/bias"):
            # biases of column-parallel layers follow the sharded dim
            return P(axis)
        return P()

    return spec


def embedding_tp_spec(axis: Optional[str] = None) -> Callable:
    """``param_spec_fn`` sharding only embedding tables (the recommender
    recipe: MLP stays replicated, the big tables split over ``axis``,
    default ``zoo.mesh.axis.model``)."""
    axis = axis if axis is not None else config_axis("model")

    def spec(path, leaf) -> P:
        name = _path_name(path)
        if "embed" in name and getattr(leaf, "ndim", 0) == 2:
            return P(axis, None)
        return P()

    return spec


def pipeline_stage_spec(axis: Optional[str] = None) -> Callable:
    """``param_spec_fn`` for stacked-stage parameters (leading dim =
    pipeline stage, as produced by ``parallel.staged`` models; default
    axis name from ``zoo.mesh.axis.pipeline`` -> ``"pipe"``)."""
    axis = axis if axis is not None else config_axis("pipeline",
                                                     fallback="pipe")

    def spec(path, leaf) -> P:
        name = _path_name(path)
        if "blocks/" in name or name.startswith("blocks"):
            ndim = getattr(leaf, "ndim", 0)
            return P(axis, *([None] * max(0, ndim - 1)))
        return P()

    return spec
