"""Device-mesh construction.

Replaces the reference's cluster-topology discovery (BigDL ``Engine.init``
node/core discovery, ref: zoo/.../common/NNContext.scala:134-150, and the
five runtimes of SURVEY.md section 2.3) with a single concept: an N-d
``jax.sharding.Mesh`` whose axes are the parallelism dimensions
(data / fsdp / tensor / sequence / pipeline / expert).

On multi-host TPU pods, ``create_mesh`` builds a *hybrid* mesh so that the
fastest-varying axes ride ICI within a slice and only the outermost axis
crosses DCN -- the layout recommended by the scaling playbook.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names used across the framework.
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
TENSOR_AXIS = "model"
SEQUENCE_AXIS = "seq"
PIPELINE_AXIS = "pipe"
EXPERT_AXIS = "expert"


def create_mesh(
    axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh from an ordered ``{axis_name: size}`` mapping.

    An axis size of ``-1`` (at most one) is inferred from the device count.
    With no ``axes``, returns a 1-d data-parallel mesh over all devices.

    On multi-process (multi-host) runs, uses
    ``mesh_utils.create_hybrid_device_mesh`` so the innermost axes map to
    ICI and the outer product to DCN.
    """
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if not axes:
        axes = {DATA_AXIS: n}
    names = tuple(axes.keys())
    sizes = [int(s) for s in axes.values()]
    n_infer = sum(1 for s in sizes if s == -1)
    if n_infer > 1:
        raise ValueError(f"at most one axis may be -1, got {axes}")
    if n_infer == 1:
        known = int(np.prod([s for s in sizes if s != -1]))
        if known == 0 or n % known != 0:
            raise ValueError(
                f"cannot infer axis: {n} devices not divisible by {known}")
        sizes = [n // known if s == -1 else s for s in sizes]
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} needs {total} devices, have {n}")

    if jax.process_count() > 1 and devices == jax.devices():
        # hybrid ICI x DCN layout: split each axis into a DCN (across hosts)
        # and ICI (within host) factor.
        from jax.experimental import mesh_utils

        n_hosts = jax.process_count()
        dcn = _factor_over_hosts(sizes, n_hosts)
        ici = [s // d for s, d in zip(sizes, dcn)]
        try:
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici, dcn, devices=devices)
        except ValueError:
            if devices[0].platform == "tpu":
                # on real pods a factoring error is a misconfiguration;
                # a topology-ignorant fallback would silently route
                # ICI-heavy axes over DCN
                raise
            # no slice topology (multi-process CPU testing): a
            # process-major reshape keeps host boundaries on the
            # outermost axis factors, good enough off-TPU
            ordered = sorted(devices,
                             key=lambda d: (d.process_index, d.id))
            dev_array = np.asarray(ordered).reshape(sizes)
        return Mesh(dev_array, names)

    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, names)


def _factor_over_hosts(sizes: Sequence[int], n_hosts: int) -> list:
    """Greedily assign the host (DCN) factor to the outermost axes."""
    remaining = n_hosts
    dcn = []
    for s in sizes:
        g = int(np.gcd(s, remaining))
        dcn.append(g)
        remaining //= g
    if remaining != 1:
        raise ValueError(
            f"cannot factor {n_hosts} hosts over mesh sizes {list(sizes)}")
    return dcn


def default_mesh() -> Mesh:
    """The context mesh if a ZooContext is live, else a fresh DP mesh."""
    from analytics_zoo_tpu.common.context import ZooContext

    ctx = ZooContext.get()
    if ctx is not None:
        return ctx.mesh
    return create_mesh()


def mesh_axis_size(mesh: Mesh, name: str) -> int:
    if name not in mesh.axis_names:
        return 1
    return mesh.devices.shape[mesh.axis_names.index(name)]


def shard_map(f, mesh: Mesh, in_specs, out_specs):
    """Version-compat shard_map: jax >= 0.5 exposes ``jax.shard_map``
    (``check_vma``), 0.4.x ships ``jax.experimental.shard_map``
    (``check_rep``). Replication checking is off either way -- bodies
    with per-shard divergent values (dropout keys, quantization
    scales) are the norm in this package. Every ``parallel/`` and
    serving shard_map routes through here so the whole tree runs on
    both jax lines."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
        except TypeError:
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as esm

    try:
        return esm(f, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=False)
    except TypeError:
        return esm(f, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs)


def config_axis(role: str, fallback: Optional[str] = None) -> str:
    """Canonical mesh-axis name for a parallelism *role* -- the
    ``zoo.mesh.axis.<role>`` config family (roles: data, model,
    sequence, pipeline, expert). Call sites take an ``axis`` argument
    and default it through here, so a deployment that renames an axis
    (e.g. a hybrid mesh calling its tensor axis ``"tp"``) sets one
    config key instead of threading the name through every recipe.
    Unknown roles fall back to ``fallback`` (default: the role
    itself)."""
    from analytics_zoo_tpu.common.config import get_config

    return str(get_config().get("zoo.mesh.axis." + role,
                                fallback if fallback is not None
                                else role))
