"""Stage-split transformer models: real models through the pipeline.

New capability relative to the reference (data-parallel only, SURVEY.md
section 2.3). ``PipelinedTransformerLM`` is an Estimator-compatible
model (init/apply adapter contract) whose encoder blocks are the SAME
``keras.layers.transformer.TransformerBlock`` used by TransformerModule
and BERT -- stored stacked (leading dim = block index) so they can be
split into pipeline stages and run through ``parallel.pipeline`` over a
mesh ``pipe`` axis, composing with data parallelism over the ``data``
axis (dp x pp mesh).

When the active mesh has no pipe axis (or shapes don't divide), apply
falls back to a sequential ``lax.scan`` over the stacked blocks --
numerically identical (the pipeline only reorders the microbatch
schedule), which is what the parity tests assert.

Dropout is fully supported through the pipeline: every (microbatch,
block) pair folds its own key from the step rng -- ``fold_in(rng,
mb_idx * n_block + global_block)`` -- a formula independent of the
pipeline degree, so on a pipe-only mesh the GPipe schedule and the
sequential fallback draw IDENTICAL masks (asserted by the parity
test). On a dp x pp mesh each data shard additionally folds its shard
index, keeping masks i.i.d. across the batch -- per-shard draws, like
any shard_map dropout, so bitwise parity with a differently-sharded
run is not defined there.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from analytics_zoo_tpu.keras.layers.transformer import TransformerBlock
from analytics_zoo_tpu.parallel.mesh import (
    config_axis, default_mesh, mesh_axis_size)
from analytics_zoo_tpu.parallel.pipeline import pipeline_apply


class _Embedder(nn.Module):
    """Token + position embedding (kept outside the pipeline)."""

    vocab: int
    seq_len: int
    hidden_size: int

    @nn.compact
    def __call__(self, ids):
        ids = ids.astype(jnp.int32)
        tok = nn.Embed(self.vocab, self.hidden_size,
                       name="token_embed")(ids)
        pos = self.param("position_embed", nn.initializers.normal(0.01),
                         (self.seq_len, self.hidden_size))
        return tok + pos[None, :ids.shape[1]]


class PipelinedTransformerLM:
    """GPT-style stack with pipeline-splittable blocks.

    Estimator-compatible adapter: ``init(rng, x) -> variables`` and
    ``apply(variables, x, training, rng) -> (hidden_states, extra)``.
    Returns the final hidden states [B, L, H] (same contract as
    ``TransformerModule``); attach a head via the loss or wrap it.

    Args:
      n_microbatches: microbatches per step on the pipeline path; must
        divide the (per-data-shard) batch.
      mesh: defaults to the context mesh at call time. Pipeline engages
        when the mesh has a ``pipe`` axis of size > 1 that divides
        ``n_block``.

    Use ``parallel.recipes.pipeline_stage_spec()`` as the Estimator's
    ``param_spec_fn`` so each stage's block slice (and its optimizer
    moments) lives on its pipeline rank.
    """

    def __init__(self, vocab: int, seq_len: int, hidden_size: int = 768,
                 n_head: int = 12, n_block: int = 12,
                 intermediate_size: Optional[int] = None,
                 causal: bool = True, n_microbatches: int = 2,
                 hidden_dropout: float = 0.0, attn_dropout: float = 0.0,
                 dtype: Any = jnp.float32, mesh=None):
        self.vocab = vocab
        self.seq_len = seq_len
        self.hidden_size = hidden_size
        self.n_block = n_block
        self.n_microbatches = n_microbatches
        self.dropout_on = hidden_dropout > 0 or attn_dropout > 0
        self.dtype = dtype
        self.mesh = mesh
        self._embedder = _Embedder(vocab, seq_len, hidden_size)
        self._block = TransformerBlock(
            hidden_size, n_head,
            intermediate_size or 4 * hidden_size,
            hidden_dropout=hidden_dropout, attn_dropout=attn_dropout,
            causal=causal, dtype=dtype)

    # ------------------------------------------------- adapter contract --
    def init(self, rng, x) -> Dict[str, Any]:
        ids = jnp.asarray(np.asarray(x), jnp.int32)
        embed_vars = self._embedder.init(rng, ids)
        h = self._embedder.apply(embed_vars, ids).astype(self.dtype)
        block_rngs = jax.random.split(jax.random.fold_in(rng, 7),
                                      self.n_block)

        def init_block(r):
            return self._block.init(r, h)["params"]

        stacked = jax.vmap(init_block)(block_rngs)
        return {"params": {"embed": embed_vars["params"],
                           "blocks": stacked}}

    def _mesh(self):
        return self.mesh or default_mesh()

    def apply(self, variables, x, training: bool = False, rng=None):
        p = variables["params"]
        ids = jnp.asarray(x)
        h = self._embedder.apply({"params": p["embed"]}, ids)
        h = h.astype(self.dtype)
        blocks = p["blocks"]
        b = h.shape[0]
        mesh = self._mesh()
        # axis names reconciled against zoo.mesh.axis.* (a deployment
        # renaming its pipe/data axes sets the config, not this file)
        pipe_axis = config_axis("pipeline", fallback="pipe")
        dp_axis = config_axis("data")
        pipe = (mesh_axis_size(mesh, pipe_axis)
                if pipe_axis in mesh.axis_names else 1)
        data = (mesh_axis_size(mesh, dp_axis)
                if dp_axis in mesh.axis_names else 1)
        m = self.n_microbatches
        use_pipe = (pipe > 1 and self.n_block % pipe == 0
                    and b % m == 0 and (b // m) % data == 0)
        dropout = self.dropout_on and training and rng is not None
        n_block = self.n_block
        if use_pipe:
            bps = self.n_block // pipe
            stage_params = jax.tree_util.tree_map(
                lambda a: a.reshape((pipe, bps) + a.shape[1:]), blocks)
            mb = h.reshape((m, b // m) + h.shape[1:])
            data_axis = dp_axis if data > 1 else None

            def stage_fn(sp, a, *ctx):
                # ctx = (mb_idx, stage_id, key) when pipeline_apply got
                # an rng; empty otherwise (see pipeline_apply contract)
                key = None
                if ctx:
                    mb_idx, stage_id, key = ctx
                    if data_axis is not None:
                        # per-data-shard masks: a replicated key would
                        # repeat one mask across dp shards
                        key = jax.random.fold_in(
                            key, lax.axis_index(data_axis))

                def body(carry, layer_j):
                    layer, j = layer_j
                    k = (None if key is None else jax.random.fold_in(
                        key, mb_idx * n_block + stage_id * bps + j))
                    return self._apply_block(layer, carry, k), None

                out, _ = lax.scan(body, a, (sp, jnp.arange(bps)))
                return out

            out = pipeline_apply(
                stage_fn, stage_params, mb, mesh, axis_name=pipe_axis,
                data_axis=data_axis, rng=rng if dropout else None)
            h = out.reshape((b,) + h.shape[1:])
        elif dropout:
            # sequential fallback with the SAME per-(microbatch, block)
            # key formula, so pipe-only pp and sequential draw identical
            # masks. A batch the microbatch count doesn't divide
            # degrades to one microbatch (the pipeline wouldn't engage
            # there either).
            if b % m != 0:
                m = 1
            hm = h.reshape((m, b // m) + h.shape[1:])

            def body(carry, layer_j):
                layer, j = layer_j

                def per_mb(mb_h, mb_idx):
                    k = jax.random.fold_in(rng, mb_idx * n_block + j)
                    return self._apply_block(layer, mb_h, k)

                return jax.vmap(per_mb)(carry, jnp.arange(m)), None

            hm, _ = lax.scan(body, hm, (blocks, jnp.arange(n_block)))
            h = hm.reshape((b,) + h.shape[1:])
        else:
            def body(carry, layer):
                return self._apply_block(layer, carry), None

            h, _ = lax.scan(body, h, blocks)
        return h, {}

    def _apply_block(self, layer_params, h, dropout_key=None):
        """One TransformerBlock application, optionally with a dropout
        key -- the single site every path above funnels through."""
        if dropout_key is None:
            return self._block.apply({"params": layer_params}, h)
        return self._block.apply({"params": layer_params}, h,
                                 train=True,
                                 rngs={"dropout": dropout_key})

    def __call__(self, variables, x):
        return self.apply(variables, x)[0]
