#!/usr/bin/env python
"""Chaos soak driver: the serving pipeline under a seeded fault
schedule (ISSUE-5).

Drives the REAL data plane (InputQueue -> supervised ServingWorker ->
OutputQueue, fast wire codec, InferenceModel bucketed predict) while
the chaos harness (serving/chaos.py) injects crashes, stalls, errors
and dropped replies at the engine's stage seams -- randomized but
SEEDED, so a failing soak replays exactly with the same --seed/--spec.

What "pass" looks like: every request the chaos schedule did not
explicitly destroy (dropped replies) is answered exactly once -- as a
result or a structured error -- without operator action, across
however many supervisor restarts the schedule forces.

Prints one JSON line (the perf_serving_pipeline.py convention):
  {"requests", "answered", "ok", "errors", "deadline_exceeded",
   "duplicates", "unanswered", "restarts", "injected", "elapsed_s",
   "rps", "seed", "spec", "recovered"}
"""

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

FEATURES = 16
DEFAULT_SPEC = ("crash:dispatch:at=25;crash:decode:at=70;"
                "sleep:finalize:p=0.01:dur=0.05;"
                "error:dispatch:p=0.01;drop:push:p=0.005")


def build_model():
    import flax.linen as nn
    import jax

    from analytics_zoo_tpu.inference.inference_model import (
        InferenceModel, bucket_ladder)

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(8)(nn.relu(nn.Dense(32)(x)))

    net = Net()
    variables = net.init(jax.random.PRNGKey(0),
                         np.zeros((1, FEATURES), np.float32))
    model = InferenceModel().load_flax(net, variables=variables)
    model.warm_up(np.zeros((1, FEATURES), np.float32),
                  batch_sizes=tuple(bucket_ladder(32)))
    return model


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=2000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--spec", default=DEFAULT_SPEC,
                    help="chaos schedule (kind:seam[:k=v]*;...); "
                         "with --replicas, kill:replica:at=K entries "
                         "SIGKILL whole replica processes")
    ap.add_argument("--deadline-ms", type=float, default=30000.0)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--drain-timeout", type=float, default=60.0)
    ap.add_argument("--replicas", type=int, default=0,
                    help="> 1 runs the soak against a real replica "
                         "FLEET (delegates to fleet_soak.py: consumer"
                         "-group sharding, SIGKILL of whole replicas, "
                         "rolling restart)")
    args = ap.parse_args()

    if args.replicas and args.replicas > 1:
        # replica-level chaos lives in the fleet driver -- one soak
        # convention, two granularities. The fleet driver honors
        # requests/seed/drain-timeout and kill:replica spec entries
        # ONLY; say so instead of silently eating the others.
        import fleet_soak

        dropped = []
        if args.deadline_ms != 30000.0:
            dropped.append("--deadline-ms")
        if args.batch_size != 8:
            dropped.append("--batch-size")
        in_process = [e for e in args.spec.split(";")
                      if e.strip() and "replica" not in e]
        if in_process:
            dropped.append(f"spec entries {';'.join(in_process)!r} "
                           "(in-process seams only arm in single-"
                           "worker mode)")
        if dropped:
            print("chaos_serving --replicas: ignoring "
                  + ", ".join(dropped), file=sys.stderr)
        sys.argv = [sys.argv[0],
                    "--requests", str(args.requests),
                    "--replicas", str(args.replicas),
                    "--seed", str(args.seed),
                    "--drain-timeout", str(args.drain_timeout)]
        replica_entries = ";".join(
            e for e in args.spec.split(";")
            if e.strip() and "replica" in e)
        if replica_entries:
            sys.argv += ["--spec", replica_entries]
        fleet_soak.main()
        return

    from analytics_zoo_tpu.serving import chaos
    from analytics_zoo_tpu.serving.queues import (
        InputQueue, OutputQueue)
    from analytics_zoo_tpu.serving.resilience import Supervisor
    from analytics_zoo_tpu.serving.worker import (
        DEADLINE_PREFIX, ERROR_KEY, ServingWorker)

    model = build_model()
    rng = np.random.RandomState(args.seed)
    xs = rng.randn(256, FEATURES).astype(np.float32)

    in_q = InputQueue(maxlen=args.requests + 10,
                      deadline_ms=args.deadline_ms)
    out_q = OutputQueue()
    for i in range(args.requests):
        assert in_q.enqueue(f"c{i:06d}", x=xs[i % len(xs)])

    injector = chaos.install(chaos.ChaosInjector(
        chaos.parse_spec(args.spec), seed=args.seed))
    worker = ServingWorker(model, in_q, out_q,
                           batch_size=args.batch_size, timeout_ms=2.0,
                           max_batch_size=32, pipelined=True)
    sup = Supervisor(worker, poll_interval_s=0.05,
                     heartbeat_timeout_s=2.0, backoff_base_s=0.02,
                     backoff_max_s=0.5, seed=args.seed)
    t0 = time.perf_counter()
    worker.start()
    sup.start()
    replies = []
    seen = set()
    deadline = time.time() + args.drain_timeout
    try:
        while len(seen) < args.requests and time.time() < deadline:
            item = out_q.dequeue(timeout=0.1)
            if item is not None:
                replies.append(item)
                seen.add(item[0])
    finally:
        elapsed = time.perf_counter() - t0
        sup.stop()
        worker.stop()
        chaos.uninstall()

    ok = errors = deadlines = 0
    for _, tensors in replies:
        if ERROR_KEY not in tensors:
            ok += 1
        elif str(tensors[ERROR_KEY]).startswith(DEADLINE_PREFIX):
            deadlines += 1
        else:
            errors += 1
    injected = injector.counts()
    dropped = injected.get("push:drop", 0)
    unanswered = args.requests - len(seen)
    line = {
        "requests": args.requests,
        "answered": len(seen),
        "ok": ok,
        "errors": errors,
        "deadline_exceeded": deadlines,
        "duplicates": len(replies) - len(seen),
        "unanswered": unanswered,
        "dropped_by_chaos": dropped,
        "restarts": sup.restarts,
        "injected": injected,
        "elapsed_s": round(elapsed, 3),
        "rps": round(len(seen) / max(elapsed, 1e-9), 1),
        "seed": args.seed,
        "spec": args.spec,
        # recovery verdict: everything the schedule didn't destroy
        # (dropped replies, or replies racing the final drain cutoff)
        # was answered; restarts happened if the spec forced any
        "recovered": unanswered <= dropped,
    }
    print(json.dumps(line))
    sys.exit(0 if line["recovered"] else 1)


if __name__ == "__main__":
    main()
