#!/usr/bin/env python
"""Attention implementation shootout at BERT-base shapes on real TPU.
Chained inside lax.fori_loop so tunnel dispatch overhead amortizes."""
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp

PEAK = 197e12


def sync(r):
    leaf = jax.tree_util.tree_leaves(r)[0]
    val = leaf if getattr(leaf, "ndim", 0) == 0 else jnp.sum(leaf)
    float(jax.device_get(val))


def chain_bench(name, attn_fn, b, h, l, d, iters=20):
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, l, d),
                          jnp.bfloat16)

    @jax.jit
    def run(q):
        def body(i, q):
            def loss(q):
                return jnp.sum(attn_fn(q, q, q).astype(jnp.float32))

            g = jax.grad(loss)(q)
            return q + 0.0001 * g.astype(q.dtype)

        return jax.lax.fori_loop(0, iters, body, q)

    t0 = time.perf_counter()
    sync(run(q))
    comp = time.perf_counter() - t0
    t0 = time.perf_counter()
    sync(run(q))
    dt = (time.perf_counter() - t0) / iters
    # fwd 4*b*h*l*l*d MACs*2? use flops = 2 matmuls: 2*2*b*h*l*l*d fwd,
    # bwd ~2.5x -> 3.5x total
    fl = 3.5 * 4 * b * h * l * l * d
    print(f"{name} b{b} l{l} d{d}: {dt*1e3:.2f} ms fwd+bwd, "
          f"{fl/dt/1e12:.1f} TF/s (compile {comp:.0f}s)", flush=True)
    return dt


def stock_flash(q, k, v):
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention)

    return flash_attention(q, k, v, causal=False,
                           sm_scale=1.0 / np.sqrt(q.shape[-1]))


def jnp_ref(q, k, v):
    from analytics_zoo_tpu.ops.attention import reference_attention

    return reference_attention(q, k, v)


def xla_dpa(q, k, v):
    # jax.nn.dot_product_attention expects [B, L, H, D]
    qt = q.transpose(0, 2, 1, 3)
    out = jax.nn.dot_product_attention(qt, k.transpose(0, 2, 1, 3),
                                       v.transpose(0, 2, 1, 3))
    return out.transpose(0, 2, 1, 3)


def own_padded(q, k, v):
    from analytics_zoo_tpu.ops.pallas_attention import (
        pallas_flash_attention_fwd)

    d = q.shape[-1]
    pad = [(0, 0)] * 3 + [(0, 128 - d)]
    qp, kp, vp = (jnp.pad(t, pad) for t in (q, k, v))
    out = pallas_flash_attention_fwd(qp, kp, vp, False,
                                     1.0 / np.sqrt(d))
    return out[..., :d]


def stock_flash_bq(bq, bk):
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, flash_attention)

    def fn(q, k, v):
        l = q.shape[2]
        bs = BlockSizes(
            block_q=min(bq, l), block_k_major=min(bk, l),
            block_k=min(bk, l), block_b=1,
            block_q_major_dkv=min(bq, l), block_k_major_dkv=min(bk, l),
            block_k_dkv=min(bk, l), block_q_dkv=min(bq, l),
            block_k_major_dq=min(bk, l), block_k_dq=min(bk, l),
            block_q_dq=min(bq, l))
        return flash_attention(q, k, v, causal=False,
                               sm_scale=1.0 / np.sqrt(q.shape[-1]),
                               block_sizes=bs)

    return fn


if __name__ == "__main__":
    print(jax.devices(), flush=True)
    shapes = [(32, 12, 384, 64)]
    for b, h, l, d in shapes:
        chain_bench("jnp_einsum", jnp_ref, b, h, l, d)
        chain_bench("xla_dpa", xla_dpa, b, h, l, d)
        chain_bench("stock_flash_default", stock_flash, b, h, l, d)
        chain_bench("stock_flash_128/128", stock_flash_bq(128, 128),
                    b, h, l, d)
        chain_bench("own_kernel_padded128", own_padded, b, h, l, d)
    chain_bench("jnp_einsum", jnp_ref, 64, 12, 384, 64)
    chain_bench("xla_dpa", xla_dpa, 64, 12, 384, 64)
