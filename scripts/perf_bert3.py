#!/usr/bin/env python
"""Isolate RNG/dropout cost in the BERT step; try rbg PRNG."""
import functools
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")
import jax

if "rbg" in sys.argv:
    jax.config.update("jax_default_prng_impl", "rbg")
import jax.numpy as jnp
import optax

PEAK = 197e12


def sync(r):
    leaf = jax.tree_util.tree_leaves(r)[0]
    val = leaf if getattr(leaf, "ndim", 0) == 0 else jnp.sum(leaf)
    float(jax.device_get(val))


def bert_step(batch, dropout, label, seq=384):
    from analytics_zoo_tpu.common.config import get_config
    from analytics_zoo_tpu.models.text.bert_squad import (
        BERTForSQuAD, squad_span_loss)

    get_config().set("zoo.ops.attention_impl", "einsum")
    mod = BERTForSQuAD(vocab=30522, dtype=jnp.bfloat16,
                       hidden_dropout=dropout)
    x = {"input_ids": np.random.RandomState(0).randint(
        0, 30522, (batch, seq)).astype(np.int32)}
    y = np.stack([np.random.randint(0, seq, batch),
                  np.random.randint(0, seq, batch)], 1).astype(np.int32)
    variables = mod.init(jax.random.PRNGKey(0),
                         {"input_ids": x["input_ids"][:1]}, train=False)
    tx = optax.adam(1e-4)
    params = variables["params"]
    opt_state = tx.init(params)

    def loss_fn(p, x, y, rng):
        preds = mod.apply({"params": p}, x, train=True,
                          rngs={"dropout": rng})
        return squad_span_loss(preds, y)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, x, y, rng):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y, rng)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = jax.random.PRNGKey(1)
    t0 = time.perf_counter()
    params, opt_state, loss = step(params, opt_state, x, y, rng)
    sync(loss)
    compile_s = time.perf_counter() - t0
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, x, y, rng)
    sync(loss)
    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, x, y, rng)
    sync(loss)
    dt = (time.perf_counter() - t0) / iters
    p_dense = sum(int(l.size) for p, l in
                  jax.tree_util.tree_flatten_with_path(params)[0]
                  if "embed" not in "/".join(str(s) for s in p).lower())
    fpt = 6 * p_dense + 12 * 12 * 768 * seq
    mfu = batch * seq * fpt / dt / PEAK
    print(f"BERT {label} b{batch}: {dt*1e3:.1f} ms/step, "
          f"{1/dt:.2f} steps/s, MFU {mfu:.3f} (compile {compile_s:.0f}s)",
          flush=True)


if __name__ == "__main__":
    print(jax.devices(), jax.config.jax_default_prng_impl, flush=True)
    if "rbg" in sys.argv:
        bert_step(32, 0.1, "einsum+rbg+drop0.1")
    else:
        bert_step(32, 0.0, "einsum+drop0")
