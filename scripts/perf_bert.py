#!/usr/bin/env python
"""BERT perf exploration on the real chip: step time vs batch, attention
share, matmul roofline. Prints JSON lines; run on TPU."""
import functools
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp
import optax

PEAK = 197e12


def sync(r):
    # on the remote-dispatch axon platform block_until_ready returns
    # before execution completes; a real host fetch is the only sync.
    # Reduce to a scalar ON DEVICE first -- fetching the full array
    # would drag megabytes through the tunnel and dominate the timing.
    leaf = jax.tree_util.tree_leaves(r)[0]
    val = leaf if getattr(leaf, "ndim", 0) == 0 else jnp.sum(leaf)
    float(jax.device_get(val))


def timeit(fn, *args, iters=10, warmup=2):
    for _ in range(warmup):
        r = fn(*args)
    sync(r)
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn(*args)
    sync(r)
    return (time.perf_counter() - t0) / iters


def roofline():
    # big matmul chain to sanity-check achievable peak
    a = jnp.ones((8192, 8192), jnp.bfloat16)
    b = jnp.ones((8192, 8192), jnp.bfloat16)

    @jax.jit
    def mm(a, b):
        return a @ b

    dt = timeit(mm, a, b, iters=20)
    fl = 2 * 8192**3
    print(f"ROOFLINE matmul 8192^3: {dt*1e3:.2f} ms, "
          f"{fl/dt/1e12:.1f} TF/s ({fl/dt/PEAK:.2f} of peak)", flush=True)


def attention_share(batch=32, seq=384):
    from analytics_zoo_tpu.ops.attention import dot_product_attention
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (batch, 12, seq, 64), jnp.bfloat16)

    def attn_loss(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v).astype(jnp.float32))

    g = jax.jit(jax.grad(attn_loss, argnums=(0, 1, 2)))
    dt = timeit(lambda: g(q, q, q), iters=20)
    # fwd+bwd attention flops: ~ 4*2*B*H*L^2*D*... fwd=4*B*H*L*L*D ; bwd ~2.5x
    fl = 3.5 * 4 * batch * 12 * seq * seq * 64
    print(f"ATTN b{batch} l{seq}: {dt*1e3:.3f} ms/step x12layers="
          f"{dt*12*1e3:.1f} ms, {fl/dt/1e12:.1f} TF/s", flush=True)


def bert_step(batch, seq=384, dtype=jnp.bfloat16, remat=None, label=""):
    from analytics_zoo_tpu.models.text.bert_squad import (
        BERTForSQuAD, squad_span_loss)
    mod = BERTForSQuAD(vocab=30522, dtype=dtype)
    x = {"input_ids": np.random.RandomState(0).randint(
        0, 30522, (batch, seq)).astype(np.int32)}
    y = np.stack([np.random.randint(0, seq, batch),
                  np.random.randint(0, seq, batch)], 1).astype(np.int32)
    variables = mod.init(jax.random.PRNGKey(0),
                         {"input_ids": x["input_ids"][:1]}, train=False)
    tx = optax.adam(1e-4)
    params = variables["params"]
    opt_state = tx.init(params)

    def loss_fn(p, x, y, rng):
        preds = mod.apply({"params": p}, x, train=True,
                          rngs={"dropout": rng})
        return squad_span_loss(preds, y)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, x, y, rng):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y, rng)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = jax.random.PRNGKey(1)
    # donated buffers: re-feed outputs
    t0 = time.perf_counter()
    params, opt_state, loss = step(params, opt_state, x, y, rng)
    sync(loss)
    compile_s = time.perf_counter() - t0
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, x, y, rng)
    sync(loss)
    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, x, y, rng)
    sync(loss)
    dt = (time.perf_counter() - t0) / iters
    p_dense = sum(int(l.size) for p, l in
                  jax.tree_util.tree_flatten_with_path(params)[0]
                  if "embed" not in "/".join(str(s) for s in p).lower())
    fpt = 6 * p_dense + 12 * 12 * 768 * seq
    mfu = batch * seq * fpt / dt / PEAK
    print(f"BERT{label} b{batch}: {dt*1e3:.1f} ms/step, "
          f"{1/dt:.2f} steps/s, MFU {mfu:.3f} (compile {compile_s:.0f}s)",
          flush=True)
    return dt, mfu


if __name__ == "__main__":
    print(jax.devices(), flush=True)
    roofline()
    attention_share(32)
    attention_share(64)
    for b in (32, 64, 128):
        try:
            bert_step(b)
        except Exception as e:
            print(f"BERT b{b} FAILED: {type(e).__name__}: {e}",
                  flush=True)
