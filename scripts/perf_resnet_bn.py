#!/usr/bin/env python
"""ResNet-50 BN-statistics attack A/B (VERDICT r4 item 6): exact
full-batch BN vs sampled stats (zoo.models.bn_stat_rows), interleaved
fit-loop windows in one process. The r4 trace put the BN stat reduce
at 30 ms of a 99 ms step (31%, pure HBM bandwidth); rows=64 of 256
should cut that pass ~4x.

Usage: python scripts/perf_resnet_bn.py [rounds] [rows...]
"""
import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

BATCH, STEPS = 256, 8
TRAIN_FLOPS_PER_IMG = 3 * 4.1e9
PEAK = 197e12


def run_config(rows, epochs):
    """ONE fit call per config: per-epoch seconds come from the fit
    history (epoch 1 = compile, excluded). A fit call re-uploads the
    dataset over the ~10 MB/s tunnel, so windows-per-fit-call would
    measure the tunnel, not the chip."""
    from analytics_zoo_tpu.common.config import get_config
    from analytics_zoo_tpu.models.image.classifier import ImageClassifier

    cfg = get_config()
    cfg.set("zoo.train.log_every_n_steps", 100000)
    # read at TRACE time (like zoo.ops.attention_impl) -- set through
    # this model's compile
    cfg.set("zoo.models.bn_stat_rows", rows)
    rng = np.random.RandomState(0)
    n = BATCH * STEPS
    x = rng.rand(n, 224, 224, 3).astype(np.float32)  # match bench.py
    y = rng.randint(0, 1000, n).astype(np.int32)
    model = ImageClassifier(class_num=1000, backbone="resnet50",
                            dtype="bfloat16")
    hist = model.fit((x, y), batch_size=BATCH, epochs=epochs,
                     device_cache=True)
    secs = sorted(h["seconds"] for h in hist[1:])
    mfus = [(n / s) * TRAIN_FLOPS_PER_IMG / PEAK for s in secs]
    return {"best": round(max(mfus), 4),
            "median": round(mfus[len(mfus) // 2], 4),
            "epoch_s": [round(s, 3) for s in secs]}


def main():
    epochs = (int(sys.argv[1]) if len(sys.argv) > 1 else 5) + 1
    rows_list = [int(a) for a in sys.argv[2:]] or [0, 64]
    out = {}
    for rows in rows_list:
        print(f"running rows={rows} ...", flush=True)
        out[str(rows)] = run_config(rows, epochs)
        print(f"rows={rows}: {out[str(rows)]}", flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
