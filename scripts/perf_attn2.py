#!/usr/bin/env python
"""Round 2: splash kernel, fwd/bwd split, clean in-jit matmul roofline,
score-dtype variants. BERT-base shapes."""
import functools
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp

PEAK = 197e12


def sync(r):
    leaf = jax.tree_util.tree_leaves(r)[0]
    val = leaf if getattr(leaf, "ndim", 0) == 0 else jnp.sum(leaf)
    float(jax.device_get(val))


def chain(name, step_fn, x, iters, flops):
    @jax.jit
    def run(x):
        return jax.lax.fori_loop(0, iters, step_fn, x)

    t0 = time.perf_counter()
    sync(run(x))
    comp = time.perf_counter() - t0
    t0 = time.perf_counter()
    sync(run(x))
    dt = (time.perf_counter() - t0) / iters
    print(f"{name}: {dt*1e3:.2f} ms, {flops/dt/1e12:.1f} TF/s "
          f"({flops/dt/PEAK*100:.0f}% peak, compile {comp:.0f}s)",
          flush=True)
    return dt


def matmul_roofline():
    a = jax.random.normal(jax.random.PRNGKey(0), (4096, 4096),
                          jnp.bfloat16)

    def body(i, a):
        return (a @ a) * 0.0001 + a

    chain("matmul4096_chain", body, a, 30, 2 * 4096**3)
    # K=64 contraction matmul (the attention shape problem)
    b = jax.random.normal(jax.random.PRNGKey(1), (4096, 64),
                          jnp.bfloat16)

    def body2(i, b):
        s = b @ (b.T @ b) * 1e-6  # [4096,64]@[64,64]? no: b.T@b=[64,64]
        return b + s

    chain("matmulK64_chain", body2, b, 30,
          2 * 4096 * 64 * 64 * 2)


def attn_fwd_only(b, h, l, d):
    from analytics_zoo_tpu.ops.attention import reference_attention
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, l, d),
                          jnp.bfloat16)

    def body(i, q):
        o = reference_attention(q, q, q)
        return q + 0.0001 * o.astype(q.dtype)

    chain(f"einsum_fwd b{b}", body, q, 20, 4 * b * h * l * l * d)


def attn_fwd_f32_scores(b, h, l, d):
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, l, d),
                          jnp.bfloat16)
    scale = 1.0 / np.sqrt(d)

    def attn(q, k, v):
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                            preferred_element_type=jnp.float32) * scale
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, v)

    def body(i, q):
        def loss(q):
            return jnp.sum(attn(q, q, q).astype(jnp.float32))

        return q + 0.0001 * jax.grad(loss)(q).astype(q.dtype)

    chain(f"einsum_f32sm b{b}", body, q, 20,
          3.5 * 4 * b * h * l * l * d)


def splash(b, h, l, d):
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk, splash_attention_mask as sm)

    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, l, d),
                          jnp.bfloat16)
    mask = sm.MultiHeadMask(
        [sm.FullMask((l, l)) for _ in range(h)])
    kernel = sk.make_splash_mha(mask=mask, head_shards=1, q_seq_shards=1)
    kernel = jax.vmap(kernel)
    scale = 1.0 / np.sqrt(d)

    def body(i, q):
        def loss(q):
            return jnp.sum(kernel(q * scale, q, q).astype(jnp.float32))

        return q + 0.0001 * jax.grad(loss)(q).astype(q.dtype)

    chain(f"splash b{b}", body, q, 20, 3.5 * 4 * b * h * l * l * d)


def bert_fwd_vs_step(batch):
    from analytics_zoo_tpu.models.text.bert_squad import (
        BERTForSQuAD, squad_span_loss)
    mod = BERTForSQuAD(vocab=30522, dtype=jnp.bfloat16)
    seq = 384
    x = {"input_ids": np.random.RandomState(0).randint(
        0, 30522, (batch, seq)).astype(np.int32)}
    variables = mod.init(jax.random.PRNGKey(0),
                         {"input_ids": x["input_ids"][:1]}, train=False)

    @jax.jit
    def fwd(v, x):
        s, e = mod.apply(v, x, train=False)
        return jnp.sum(s.astype(jnp.float32))

    t0 = time.perf_counter()
    sync(fwd(variables, x))
    comp = time.perf_counter() - t0
    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fwd(variables, x)
    sync(r)
    dt = (time.perf_counter() - t0) / iters
    print(f"BERT fwd-only b{batch}: {dt*1e3:.1f} ms "
          f"(compile {comp:.0f}s)", flush=True)


if __name__ == "__main__":
    print(jax.devices(), flush=True)
    matmul_roofline()
    attn_fwd_only(32, 12, 384, 64)
    attn_fwd_f32_scores(32, 12, 384, 64)
    try:
        splash(32, 12, 384, 64)
    except Exception as e:
        print(f"splash failed: {type(e).__name__}: {e}", flush=True)
    bert_fwd_vs_step(32)
