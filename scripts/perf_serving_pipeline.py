#!/usr/bin/env python
"""A/B harness: synchronous vs pipelined serving engine (ISSUE-1).

Two phases, both driving the REAL data plane (InputQueue -> worker ->
OutputQueue, fast wire codec, InferenceModel bucketed predict):

1. **Saturation throughput**: pre-fill the input queue with N requests
   (the reference's offline-benchmark pattern: docker/cluster-serving/
   perf/offline-benchmark) and time until every result lands. Windows
   interleave sync/pipelined so a machine-speed shift hits both
   engines, and the best window per engine is the comparator (the
   repo's chip-variance convention, BENCH_NOTES.md).
2. **Matched-load latency**: offer BOTH engines the same paced request
   rate (well under the sync engine's saturation point) in closed loop
   and compare client-observed p50/p99. The pipelined engine must be
   no worse -- its adaptive deadline should actually *win* here, since
   a shallow queue tightens the linger instead of burning the fixed
   timeout.

Both engines run the same configured ``batch_size``/``timeout_ms``;
the pipelined engine additionally gets what the new data plane always
gives it: staged decode/assembly/finalize threads, a bounded in-flight
dispatch window, and the adaptive batcher (backlog growth snapped to
the warmed bucket ladder). On this 1-core CPU-backend rig the win is
dominated by adaptive batch growth amortizing per-batch dispatch
overhead (stage overlap cannot add cores); on multi-core or TPU hosts
the decode/compute/finalize overlap stacks on top.

Prints one JSON line:
  {"sync_rps", "pipe_rps", "speedup", "sync_p50_ms", "sync_p99_ms",
   "pipe_p50_ms", "pipe_p99_ms", "matched_rps", ...}
"""

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

BATCH_SIZE = 8          # the stock serving default (zoo.serving.batch_size)
TIMEOUT_MS = 5.0        # stock linger (zoo.serving.batch_timeout_ms)
MAX_BATCH = 256         # adaptive growth ceiling (bucket ladder value)
PIPE_DEPTH = 3
FEATURES = 64
HIDDEN = 256


def build_model():
    import flax.linen as nn
    import jax

    from analytics_zoo_tpu.inference.inference_model import (
        InferenceModel, bucket_ladder)

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(HIDDEN)(x))
            x = nn.relu(nn.Dense(HIDDEN)(x))
            return nn.Dense(16)(x)

    net = Net()
    variables = net.init(jax.random.PRNGKey(0),
                         np.zeros((1, FEATURES), np.float32))
    model = InferenceModel().load_flax(net, variables=variables)
    # warm every ladder bucket up to the adaptive ceiling: the A/B
    # times serving, not XLA compiles
    model.warm_up(np.zeros((1, FEATURES), np.float32),
                  batch_sizes=tuple(bucket_ladder(MAX_BATCH)))
    return model


def _worker(model, in_q, out_q, pipelined):
    from analytics_zoo_tpu.serving.worker import ServingWorker

    return ServingWorker(model, in_q, out_q, batch_size=BATCH_SIZE,
                         timeout_ms=TIMEOUT_MS, pipelined=pipelined,
                         max_batch_size=MAX_BATCH,
                         pipeline_depth=PIPE_DEPTH)


def _registry_snapshot():
    from analytics_zoo_tpu.obs.metrics import get_registry

    return {name: fam for name, fam in
            get_registry().snapshot(with_buckets=False).items()
            if name.startswith(("zoo_serving_", "zoo_inference_"))}


def _registry_delta(before, after):
    """This window's own registry activity (the registry is process-
    global and cumulative, so without the delta a window's numbers
    would blend in every preceding window's -- including the other
    engine's). The diff itself is obs.metrics.snapshot_delta, shared
    with the rollup reporter."""
    from analytics_zoo_tpu.obs.metrics import snapshot_delta

    return snapshot_delta(before, after)


def saturation_window(model, pipelined, n, xs):
    """Pre-filled queue -> time to drain everything; returns (rps,
    worker_metrics, registry_delta). Counter/histogram deltas cover
    exactly this window; queue-depth/in-flight gauges are sampled at
    the HALFWAY point of the drain (end-of-window gauges would show
    the drained state, not the load). The
    client side counts raw result blobs (one get_many per poll)
    instead of tensor-decoding all of them: on this 1-core rig a full
    client decode costs ~10 us/request of the same CPU the engine
    under test needs, which would understate BOTH engines and dilute
    their ratio. A 64-result sample is still decoded and validated
    per window."""
    from analytics_zoo_tpu.serving.queues import (
        InputQueue, OutputQueue, _decode)

    from analytics_zoo_tpu.obs.metrics import get_registry

    in_q, out_q = InputQueue(maxlen=n + 10), OutputQueue()
    for i in range(n):
        assert in_q.enqueue(f"r{i}", x=xs[i % len(xs)])
    worker = _worker(model, in_q, out_q, pipelined)
    backend = out_q.queue
    sample = []
    reg_before = _registry_snapshot()
    t0 = time.perf_counter()
    worker.start()
    done = 0
    mid_gauges = None
    while done < n:
        got = backend.get_many(512)
        done += len(got)
        if not sample and got:
            sample = got[:64]
        if mid_gauges is None and done >= n // 2:
            # gauges sampled MID-drain: the end-of-window values are
            # post-backlog (~0) and carry no signal about the load the
            # window actually ran under
            reg = get_registry()
            mid_gauges = {
                name: reg.get(name).value
                for name in ("zoo_serving_queue_depth_items",
                             "zoo_serving_inflight_batches_items")
                if reg.get(name) is not None}
        if not got:
            time.sleep(0.002)
    dt = time.perf_counter() - t0
    obs = _registry_delta(reg_before, _registry_snapshot())
    for name, v in (mid_gauges or {}).items():
        obs[name] = {"type": "gauge", "values": {"": v}}
    worker.stop()
    for blob in sample:  # spot-check real responses came back
        uri, tensors = _decode(blob)
        assert uri.startswith("r") and "output" in tensors, uri
    return n / dt, worker.metrics(), obs


def matched_load_window(model, pipelined, rps, seconds, xs):
    """Paced open-loop offered load; returns (p50_s, p99_s,
    achieved_rps). Latency is client-observed enqueue->dequeue."""
    from analytics_zoo_tpu.serving.queues import InputQueue, OutputQueue

    in_q, out_q = InputQueue(maxlen=100000), OutputQueue()
    worker = _worker(model, in_q, out_q, pipelined).start()
    try:
        # pre-burst: let the engine's threads/buckets reach steady
        # state so the window measures serving, not spin-up
        for i in range(200):
            in_q.enqueue(f"warm{i}", x=xs[i % len(xs)])
        drained = 0
        deadline = time.perf_counter() + 10.0
        while drained < 200 and time.perf_counter() < deadline:
            drained += len(out_q.dequeue_all())
            time.sleep(0.001)
        sent = {}
        done = {}
        t_start = time.perf_counter()
        t_end = t_start + seconds
        i = 0
        while True:
            now = time.perf_counter()
            if now >= t_end:
                break
            # pace: how many requests the schedule owes by `now`
            owed = int((now - t_start) * rps) - i
            for _ in range(max(0, owed)):
                uri = f"m{i}"
                in_q.enqueue(uri, x=xs[i % len(xs)])
                sent[uri] = time.perf_counter()
                i += 1
            for uri, _t in out_q.dequeue_all():
                done[uri] = time.perf_counter()
            time.sleep(0.0005)
        deadline = time.perf_counter() + 10.0
        while len(done) < len(sent) and time.perf_counter() < deadline:
            for uri, _t in out_q.dequeue_all():
                done[uri] = time.perf_counter()
            time.sleep(0.001)
    finally:
        worker.stop()
    lats = sorted(done[u] - sent[u] for u in done if u in sent)
    if not lats:
        raise RuntimeError("matched-load window produced no results")
    p50 = lats[len(lats) // 2]
    p99 = lats[min(len(lats) - 1, int(len(lats) * 0.99))]
    return p50, p99, len(done) / seconds


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=6000,
                    help="requests per saturation window")
    ap.add_argument("--windows", type=int, default=3,
                    help="interleaved saturation windows per engine")
    ap.add_argument("--matched-rps", type=float, default=2000.0,
                    help="offered load for the latency phase")
    ap.add_argument("--matched-seconds", type=float, default=5.0)
    args = ap.parse_args()

    model = build_model()
    rng = np.random.RandomState(0)
    xs = rng.randn(1024, FEATURES).astype(np.float32)

    # one throwaway window per engine: first-run thread/alloc warmup
    saturation_window(model, False, 500, xs)
    saturation_window(model, True, 500, xs)

    sync_rps, pipe_rps = [], []
    pipe_metrics = pipe_obs = None
    for _ in range(args.windows):  # interleaved: shifts hit both
        r, _, _ = saturation_window(model, False, args.requests, xs)
        sync_rps.append(r)
        r, pipe_metrics, pipe_obs = saturation_window(
            model, True, args.requests, xs)
        pipe_rps.append(r)

    best_sync, best_pipe = max(sync_rps), max(pipe_rps)
    sync_p50, sync_p99, sync_ach = matched_load_window(
        model, False, args.matched_rps, args.matched_seconds, xs)
    pipe_p50, pipe_p99, pipe_ach = matched_load_window(
        model, True, args.matched_rps, args.matched_seconds, xs)

    batcher = (pipe_metrics or {}).get("pipeline", {}).get("batcher", {})
    line = {
        "sync_rps": round(best_sync, 1),
        "pipe_rps": round(best_pipe, 1),
        "speedup": round(best_pipe / best_sync, 3),
        "sync_rps_all": [round(r, 1) for r in sync_rps],
        "pipe_rps_all": [round(r, 1) for r in pipe_rps],
        "matched_rps": args.matched_rps,
        "sync_p50_ms": round(sync_p50 * 1e3, 2),
        "sync_p99_ms": round(sync_p99 * 1e3, 2),
        "pipe_p50_ms": round(pipe_p50 * 1e3, 2),
        "pipe_p99_ms": round(pipe_p99 * 1e3, 2),
        "sync_achieved_rps": round(sync_ach, 1),
        "pipe_achieved_rps": round(pipe_ach, 1),
        "batch_size": BATCH_SIZE,
        "max_batch_size": MAX_BATCH,
        "pipe_mean_occupancy": round(batcher.get("mean_occupancy", 0),
                                     1),
        "requests_per_window": args.requests,
        "cores": os.cpu_count(),
        # this-window registry delta of the LAST pipelined saturation
        # window (queue depth / occupancy / in-flight / compiles),
        # captured while that engine was live -- the operational
        # context BENCH_*.json records alongside the throughput
        "registry": pipe_obs or {},
    }
    print(json.dumps(line))


if __name__ == "__main__":
    main()
