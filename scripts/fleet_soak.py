#!/usr/bin/env python
"""Fleet chaos soak: replica-level faults under a seeded schedule
(ISSUE-9).

Drives a REAL fleet -- N ``python -m analytics_zoo_tpu.serving.launcher``
replica processes sharding one consumer-group stream behind the
FleetController's broker and router -- while a seeded chaos schedule
SIGKILLs whole replicas mid-run (``kill:replica:at=K`` fires after the
Kth observed result). Then, with HTTP traffic flowing through the
front-tier router, rolls a restart across every replica.

What "pass" looks like:
- every stream request is answered EXACTLY once (the broker's pending
  -entry reclaim re-serves a dead replica's claims; the worker's
  ack-on-reply keeps re-serves from double-answering);
- the rolling restart completes with ZERO 5xx from the router
  (quiesce -> drain -> restart, one replica at a time, capacity
  >= N-1 throughout).

``--zipf`` switches to the overload drill (ISSUE-15): a flat-out
calibration burst measures fleet capacity, then a paced producer
offers 2x that rate through a REAL producer-side ``InputQueue`` --
the brownout AdmissionController makes every admit/shed decision
(spied per-decision for the priority-inversion check) -- with a
seeded 20/30/50 interactive/batch/background class mix and
zipf-skewed tenant ids riding the uri. Pass adds:
- ZERO priority inversions (no lower class admitted at an effective
  depth where a higher class was shed);
- the background class browns out (shed > 0) while interactive e2e
  p99 stays within ``--slo-p99-ms`` despite a mid-run replica SIGKILL.

``--disaggregated`` switches to the split-pool drill (ISSUE-20): the
fleet runs dedicated prefill and decode replica pools (paged-KV
handoff stream between them), the zipf predict overload runs
unchanged through the same replicas, and a generation lane streams
token replies (chunk-``seq`` dedup client-side) while a watcher
SIGKILLs one PREFILL and one DECODE replica mid-run. Pass adds:
- every generation stream terminates EXACTLY once, gapless after
  seq dedup, with the full token budget (a killed decode replica's
  streams resume from the reclaimed KV snapshot on a survivor; a
  killed prefill replica's claims re-prefill from scratch);
- both role-targeted kills fired;
- generation TTFT p99 within ``--gen-ttft-slo-ms``.

Prints one JSON line (the chaos_serving.py convention) and exits 0
only when every armed gate holds.
"""

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

FEATURES = 6
DEFAULT_SPEC = "kill:replica:at=40;kill:replica:at=160"
PRIORITY_NAMES = ("interactive", "batch", "background")
CLASS_MIX = (0.2, 0.3, 0.5)  # interactive / batch / background
# zipf-drill model shape: heavy enough that replica compute (not the
# producer's XADD round-trip or the broker) bounds fleet capacity
ZIPF_FEATURES = 128
ZIPF_VOCAB = 1000
ZIPF_EMBED = 64


def _calib_count(requests: int) -> int:
    """Size of the flat-out calibration burst before the paced phase."""
    return max(200, min(1000, requests // 20))


def _zipf_probs(n: int, s: float) -> np.ndarray:
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), s)
    return w / w.sum()


def build_model_dir(path: str, features: int = FEATURES,
                    vocab: int = 50, embed_dim: int = 8) -> str:
    """Train-and-save the tiny TextClassifier the replicas load (the
    launcher needs a ZooModel directory, not an in-process model).
    The zipf drill uses a heavier shape so the fleet's compute -- not
    the producer's enqueue RPC -- is the capacity bottleneck."""
    if os.path.isdir(path) and os.listdir(path):
        return path
    from analytics_zoo_tpu.models import TextClassifier

    rng = np.random.RandomState(0)
    x = rng.randint(1, vocab, (64, features)).astype(np.int32)
    y = (x[:, 0] > vocab // 2).astype(np.int32)
    m = TextClassifier(class_num=2, vocab=vocab, embed_dim=embed_dim,
                       sequence_length=features)
    m.fit((x, y), batch_size=32, epochs=1)
    m.save_model(path)
    return path


def http_load(router_address: str, stop: threading.Event,
              counts: dict) -> None:
    """Sequential /predict loop through the router until stopped;
    tallies status codes (the rolling restart's zero-5xx evidence)."""
    body = json.dumps(
        {"inputs": {"input": [1, 2, 3, 4, 5, 6]}}).encode()
    while not stop.is_set():
        try:
            req = urllib.request.Request(
                router_address + "/predict", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                code = resp.status
        except urllib.error.HTTPError as e:
            code = e.code
        except (urllib.error.URLError, OSError):
            code = -1  # router itself unreachable (never expected)
        counts[code] = counts.get(code, 0) + 1


class _CachedLenQueue:
    """RedisStreamQueue wrapper that caches XLEN for a few ms so the
    paced producer's admission depth probe isn't RPC-bound below the
    2x offered-rate target (staleness of ~15 requests vs threshold
    gaps of ~200 at the default ladder depth)."""

    def __init__(self, inner, ttl_s: float = 0.005):
        self._inner = inner
        self._ttl = ttl_s
        self._len = 0
        self._at = -1.0

    def put(self, item: bytes) -> bool:
        return self._inner.put(item)

    def __len__(self) -> int:
        now = time.perf_counter()
        if now - self._at > self._ttl:
            self._len = len(self._inner)
            self._at = now
        return self._len


def zipf_phase(args, fc, answered: dict, answer_times: dict,
               xs: np.ndarray) -> dict:
    """Overload drill: calibrate capacity, then offer 2x through a
    producer-side InputQueue so the real brownout ladder sheds."""
    from analytics_zoo_tpu.serving.queues import InputQueue, _encode
    from analytics_zoo_tpu.serving.redis_adapter import RedisStreamQueue

    # ---- calibration: flat-out burst, capacity = answered rate ----
    calib = _calib_count(args.requests)
    prod = RedisStreamQueue(fc.broker_address, stream="serving_stream")
    for i in range(calib):
        while not prod.put(_encode(f"w{i:06d}",
                                   {"input": xs[i % len(xs)]})):
            time.sleep(0.01)
    cal_deadline = time.time() + args.drain_timeout
    while (sum(1 for u in answered if u.startswith("w")) < calib
           and time.time() < cal_deadline):
        time.sleep(0.05)
    w_times = sorted(t for u, t in answer_times.items()
                     if u.startswith("w"))
    if len(w_times) < 2:
        return {"error": "calibration produced no throughput sample",
                "recovered": False}
    capacity_rps = (len(w_times) - 1) / max(
        w_times[-1] - w_times[0], 1e-3)
    rate = args.overload * capacity_rps
    # ladder sized from capacity. Under a concurrent producer the
    # fleet runs ~30% below the calibrated burst number (broker RPC
    # contention), and sustained 2x overload parks the backlog at the
    # BATCH threshold (0.6x), so size the full ladder at ~cap/8:
    # batch-threshold queue wait ~0.1s, interactive worst case ~0.3s
    # even while a kill recovery runs the fleet one replica short --
    # inside the 500ms SLO with margin for the reclaim stragglers
    shed_depth = args.shed_depth or max(
        48, min(512, int(capacity_rps / 8)))

    # ---- paced overload through the REAL admission controller ----
    q = InputQueue(
        queue=_CachedLenQueue(RedisStreamQueue(
            fc.broker_address, stream="serving_stream")),
        shed_depth=shed_depth)
    decisions: list = []  # (effective_depth, class_idx, admitted)
    _admit = q.admission.admit

    def _spy(depth, priority, cost=1):
        ok = _admit(depth, priority, cost=cost)
        decisions.append((depth + cost - 1, priority, ok))
        return ok

    q.admission.admit = _spy

    rng = np.random.RandomState(args.seed + 1)
    classes = rng.choice(3, size=args.requests, p=CLASS_MIX)
    tenants = rng.choice(args.tenants, size=args.requests,
                         p=_zipf_probs(args.tenants, args.zipf_s))
    sent: dict = {}  # uri -> (class_idx, t_sent)
    offered = [0, 0, 0]
    admitted = [0, 0, 0]
    backpressured = 0
    t_start = time.perf_counter()
    for i in range(args.requests):
        target = t_start + i / rate
        now = time.perf_counter()
        if target > now:
            time.sleep(target - now)
        c = int(classes[i])
        offered[c] += 1
        uri = f"t{int(tenants[i]):03d}-c{i:06d}"
        n0 = len(decisions)
        ok = q.enqueue(uri, priority=c, input=xs[i % len(xs)])
        if ok:
            admitted[c] += 1
            sent[uri] = (c, time.perf_counter())
        elif len(decisions) == n0 or decisions[-1][2]:
            backpressured += 1  # stream full, not a ladder shed
    produce_s = max(time.perf_counter() - t_start, 1e-9)

    deadline = time.time() + args.drain_timeout
    while (sum(1 for u in sent if u in answered) < len(sent)
           and time.time() < deadline):
        time.sleep(0.1)

    # ---- per-class latency + shed accounting ----
    lat: dict = {0: [], 1: [], 2: []}
    for uri, (c, ts) in sent.items():
        ta = answer_times.get(uri)
        if ta is not None:
            lat[c].append((ta - ts) * 1000.0)
    shed_counts = q.admission.shed_counts()
    per_class = {}
    for c, name in enumerate(PRIORITY_NAMES):
        arr = lat[c]
        per_class[name] = {
            "offered": int(offered[c]),
            "admitted": int(admitted[c]),
            "shed": int(shed_counts.get(name, 0)),
            "answered": len(arr),
            "p50_ms": (round(float(np.percentile(arr, 50)), 1)
                       if arr else None),
            "p99_ms": (round(float(np.percentile(arr, 99)), 1)
                       if arr else None),
        }

    # ---- zero-inversion check over every admission decision: no
    # lower class admitted at an effective depth at-or-above one
    # where a higher class was shed (the ladder's monotone invariant,
    # verified empirically across the whole run) ----
    inf = float("inf")
    min_shed_eff = [inf, inf, inf]
    max_admit_eff = [-1, -1, -1]
    for eff, pri, ok in decisions:
        if ok:
            max_admit_eff[pri] = max(max_admit_eff[pri], eff)
        else:
            min_shed_eff[pri] = min(min_shed_eff[pri], eff)
    inversions = sum(
        1 for hi in range(3) for lo in range(hi + 1, 3)
        if min_shed_eff[hi] <= max_admit_eff[lo])

    ip99 = per_class["interactive"]["p99_ms"]
    slo_within = ip99 is not None and ip99 <= args.slo_p99_ms
    top_share = float(np.bincount(
        tenants, minlength=args.tenants).max()) / args.requests
    return {
        "mode": "zipf",
        "calibration_requests": calib,
        "produced": calib + len(sent),
        "backpressured": backpressured,
        "shed_depth": shed_depth,
        "capacity_rps": round(capacity_rps, 1),
        "offered_rps": round(args.requests / produce_s, 1),
        "overload_factor": round(
            (args.requests / produce_s) / capacity_rps, 2),
        "classes": per_class,
        "priority_inversions": inversions,
        "admission_decisions": len(decisions),
        "slo": {"interactive_p99_ms": ip99,
                "target_ms": args.slo_p99_ms,
                "within": slo_within},
        "zipf": {"s": args.zipf_s, "tenants": args.tenants,
                 "top_tenant_share": round(top_share, 3)},
        "zipf_pass": (inversions == 0 and slo_within
                      and per_class["background"]["shed"] > 0),
    }


def disagg_phase(args, fc, answered: dict, answer_times: dict,
                 xs: np.ndarray) -> dict:
    """Split-pool drill: the zipf predict overload runs unchanged
    while a generation lane streams token replies through the
    prefill -> handoff -> decode pipeline; a watcher SIGKILLs one
    replica of EACH pool keyed on lane progress."""
    from analytics_zoo_tpu.serving.protocol import ERROR_KEY, STREAM_KEY
    from analytics_zoo_tpu.serving.queues import _decode, _encode
    from analytics_zoo_tpu.serving.redis_adapter import RedisStreamQueue

    n, n_tok = args.gen_streams, args.gen_tokens
    reply_stream = "fleet_soak_gen_replies"
    rng = np.random.RandomState(args.seed + 2)
    classes = rng.choice(3, size=n, p=CLASS_MIX)
    tenants = rng.choice(args.tenants, size=n,
                         p=_zipf_probs(args.tenants, args.zipf_s))
    prompts = [rng.randint(1, 64, size=6).astype(np.int32)
               for _ in range(n)]
    uris = [f"g{int(tenants[i]):03d}-{i:05d}" for i in range(n)]
    recs: dict = {u: {"last": -1, "toks": 0, "terms": 0, "errs": 0,
                      "dups": 0, "t_sent": None, "t_first": None,
                      "t_done": None}
                  for u in uris}
    stop = threading.Event()
    halt = threading.Event()  # predict phase over: send no new streams
    kills: dict = {}
    state = {"sent": 0, "done": 0}
    lock = threading.Lock()

    def consumer():
        sub = RedisStreamQueue(fc.broker_address, stream=reply_stream,
                               group="soak_gen", consumer="c0",
                               autoack=True)
        while not stop.is_set():
            blob = sub.get(timeout=0.2)
            if blob is None:
                continue
            uri, tens = _decode(blob)
            rec = recs.get(uri)
            if rec is None:
                continue
            now = time.perf_counter()
            if ERROR_KEY in tens:
                # structured terminal (seq -1): fails the gate below
                rec["errs"] += 1
                with lock:
                    state["done"] += 1
                continue
            seq = int(np.asarray(tens[STREAM_KEY]).reshape(()))
            if seq <= rec["last"]:
                rec["dups"] += 1  # replayed chunk: deduped by seq
                continue
            if seq != rec["last"] + 1:
                rec["gap"] = (rec["last"], seq)
            rec["last"] = seq
            if rec["t_first"] is None:
                rec["t_first"] = now
            if "token" in tens:
                rec["toks"] += int(
                    np.asarray(tens["token"]).reshape(-1).size)
            if "finish_reason" in tens:
                rec["terms"] += 1
                rec["t_done"] = now
                with lock:
                    state["done"] += 1

    def producer():
        # bounded in-flight window: the decode pool's slot tables cap
        # concurrency anyway (capacity-gated handoff claims), the
        # window just keeps queue wait out of the TTFT measurement
        prod = RedisStreamQueue(fc.broker_address,
                                stream=fc.gen_stream)
        for i, uri in enumerate(uris):
            while not stop.is_set() and not halt.is_set():
                with lock:
                    if state["sent"] - state["done"] < args.gen_window:
                        break
                time.sleep(0.02)
            if stop.is_set() or halt.is_set():
                return
            recs[uri]["t_sent"] = time.perf_counter()
            while not prod.put(_encode(
                    uri, {"tokens": prompts[i]},
                    reply_to=reply_stream, max_tokens=n_tok,
                    priority=int(classes[i]))):
                time.sleep(0.01)
            with lock:
                state["sent"] += 1

    def watcher():
        # role-targeted faults keyed on lane progress so both land
        # with streams in flight; absolute caps keep the thresholds
        # early even when the lane is sized to span a long run
        fired = set()
        at_decode = max(2, min(n // 4, 8 * args.gen_window))
        at_prefill = max(4, min(n // 2, 16 * args.gen_window))
        while not stop.is_set() and len(fired) < 2:
            with lock:
                done = state["done"]
            if "decode" not in fired and done >= at_decode:
                kills["decode"] = fc.kill_one("decode", reason="soak")
                fired.add("decode")
            if "prefill" not in fired and done >= at_prefill:
                kills["prefill"] = fc.kill_one("prefill",
                                               reason="soak")
                fired.add("prefill")
            time.sleep(0.05)

    # warm the generation plane first (prefill bucket + decode step
    # compiles on both pools): the predict calibration burst must
    # measure the mixed steady state, not a compile-contended window
    # -- an undershot capacity makes the "2x" paced phase sub-capacity
    # and the brownout ladder never sheds
    warm = RedisStreamQueue(fc.broker_address, stream=fc.gen_stream)
    # warmup replies ride their OWN stream: a consumer group that goes
    # quiet pins every later entry as outstanding (the store's
    # all-groups ack-to-trim rule), so parking soak_gen_warm on the
    # lane's reply stream would backpressure decode publishes once the
    # lane outgrows maxlen -- wedging the final in-flight window
    warm_reply = reply_stream + "_warm"
    wsub = RedisStreamQueue(fc.broker_address, stream=warm_reply,
                            group="soak_gen_warm", consumer="w0",
                            autoack=True)
    n_warm = 2 * args.gen_window
    for j in range(n_warm):
        while not warm.put(_encode(f"warm-{j:03d}",
                                   {"tokens": prompts[j % n]},
                                   reply_to=warm_reply,
                                   max_tokens=2)):
            time.sleep(0.01)
    wterms = 0
    wdeadline = time.time() + 180
    while wterms < n_warm and time.time() < wdeadline:
        blob = wsub.get(timeout=0.2)
        if blob is None:
            continue
        uri, tens = _decode(blob)
        if uri.startswith("warm-") and ("finish_reason" in tens
                                        or ERROR_KEY in tens):
            wterms += 1

    threads = [threading.Thread(target=f, daemon=True)
               for f in (consumer, producer, watcher)]
    for t in threads:
        t.start()

    # the predict overload drill runs concurrently through the same
    # split fleet (every replica serves predict regardless of role)
    extra = zipf_phase(args, fc, answered, answer_times, xs)

    # predict phase over: halt new streams, let in-flight ones finish
    halt.set()
    deadline = time.time() + args.drain_timeout
    while time.time() < deadline:
        with lock:
            if state["done"] >= state["sent"]:
                break
        time.sleep(0.1)
    stop.set()
    for t in threads:
        t.join(5.0)
    sent = state["sent"]
    sent_uris = [u for u in uris if recs[u]["t_sent"] is not None]

    ttft = [(r["t_first"] - r["t_sent"]) * 1000.0
            for r in recs.values()
            if r["t_first"] is not None and r["t_sent"] is not None]
    e2e = [(r["t_done"] - r["t_sent"]) * 1000.0
           for r in recs.values()
           if r["t_done"] is not None and r["t_sent"] is not None]
    complete = sum(1 for u in sent_uris
                   if recs[u]["terms"] == 1
                   and recs[u]["toks"] == n_tok)
    gaps = sum(1 for r in recs.values() if "gap" in r)
    errs = sum(r["errs"] for r in recs.values())
    multi = sum(1 for r in recs.values() if r["terms"] > 1)
    replays = sum(r["dups"] for r in recs.values())
    ttft_p99 = (round(float(np.percentile(ttft, 99)), 1)
                if ttft else None)
    ttft_within = (ttft_p99 is not None
                   and ttft_p99 <= args.gen_ttft_slo_ms)
    gen_exactly_once = (sent > 0 and complete == sent and gaps == 0
                        and errs == 0 and multi == 0)
    extra["mode"] = "disaggregated"
    extra["offered_total"] = args.requests + sent
    extra["generation"] = {
        "streams": sent, "lane_size": n,
        "tokens_per_stream": n_tok,
        "complete": complete, "terminals_gt1": multi,
        "seq_gaps": gaps, "errors": errs,
        "replayed_chunks_deduped": replays,
        "ttft_p99_ms": ttft_p99,
        "e2e_p99_ms": (round(float(np.percentile(e2e, 99)), 1)
                       if e2e else None),
        "ttft_slo": {"target_ms": args.gen_ttft_slo_ms,
                     "within": ttft_within},
        "exactly_once": gen_exactly_once,
    }
    extra["kills"] = kills
    extra["pools"] = fc.stats().get("pools", {})
    # per-pool interactive-SLO attainment: every replica serves the
    # predict plane regardless of role, so each pool's worst-replica
    # service p99 is scored against the same interactive target (the
    # gen-plane TTFT/inter-token sample rides along for the decode
    # pool's SLO picture)
    for pool_role in ("prefill", "decode"):
        samp = fc._sample_replicas(role=pool_role)
        p99 = samp.get("p99_ms")
        extra["pools"].setdefault(pool_role, {})["slo"] = {
            "interactive_p99_ms": (round(p99, 1)
                                   if p99 is not None else None),
            "target_ms": args.slo_p99_ms,
            "within": p99 is not None and p99 <= args.slo_p99_ms,
            "ttft_p99_ms": (round(samp["ttft_p99_ms"], 1)
                            if samp.get("ttft_p99_ms") is not None
                            else None),
            "inter_token_p99_ms": (
                round(samp["inter_token_p99_ms"], 1)
                if samp.get("inter_token_p99_ms") is not None
                else None),
        }
    extra["disagg_pass"] = (
        extra.get("zipf_pass", False) and gen_exactly_once
        and kills.get("prefill") is not None
        and kills.get("decode") is not None and ttft_within)
    return extra


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=None,
                    help="offered predict requests (default 2000; "
                         "200000 with --disaggregated -- 10x the "
                         "FLEET_SOAK_r02 scale)")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--spec", default=DEFAULT_SPEC,
                    help="chaos schedule (kill:replica:at=K;...)")
    ap.add_argument("--reclaim-idle-ms", type=float, default=1000.0)
    ap.add_argument("--drain-timeout", type=float, default=180.0,
                    help="seconds to wait for every request's answer")
    ap.add_argument("--rolling", action="store_true", default=True)
    ap.add_argument("--no-rolling", dest="rolling",
                    action="store_false")
    ap.add_argument("--model-dir", default=None)
    ap.add_argument("--work-dir", default=None)
    ap.add_argument("--zipf", action="store_true",
                    help="overload drill: 2x-capacity paced load, "
                         "priority class mix, zipf tenants, brownout "
                         "shed + zero-inversion + SLO gates")
    ap.add_argument("--zipf-s", type=float, default=1.1,
                    help="zipf skew of the tenant distribution")
    ap.add_argument("--tenants", type=int, default=100)
    ap.add_argument("--shed-depth", type=int, default=None,
                    help="producer-side brownout ladder queue_depth; "
                         "default sizes it from calibrated capacity "
                         "so the backlog behind the background "
                         "threshold stays ~0.1s of queue wait")
    ap.add_argument("--overload", type=float, default=2.0,
                    help="offered load as a multiple of calibrated "
                         "fleet capacity")
    ap.add_argument("--slo-p99-ms", type=float, default=500.0,
                    help="interactive end-to-end p99 gate (zipf mode)")
    ap.add_argument("--disaggregated", action="store_true",
                    help="split-pool drill: prefill/decode pools, "
                         "zipf predict overload + generation lane, "
                         "one SIGKILL per pool, KV-handoff "
                         "exactly-once + TTFT SLO gates")
    ap.add_argument("--prefill-replicas", type=int, default=2)
    ap.add_argument("--decode-replicas", type=int, default=2)
    ap.add_argument("--gen-streams", type=int, default=256,
                    help="generation lane size (streams)")
    ap.add_argument("--gen-tokens", type=int, default=8,
                    help="new-token budget per generation stream")
    ap.add_argument("--gen-window", type=int, default=16,
                    help="generation lane in-flight window")
    ap.add_argument("--gen-ttft-slo-ms", type=float, default=5000.0,
                    help="generation time-to-first-chunk p99 gate")
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized run: 2 replicas, 120 requests, "
                         "one kill (600 requests with --zipf / "
                         "--disaggregated)")
    args = ap.parse_args()
    if args.requests is None:
        args.requests = 200000 if args.disaggregated else 2000
    if args.smoke and args.disaggregated:
        args.requests = min(args.requests, 600)
        args.gen_streams = min(args.gen_streams, 24)
        args.gen_tokens = min(args.gen_tokens, 6)
        args.gen_window = min(args.gen_window, 8)
        # same reasoning as the zipf smoke: the run is shorter than a
        # kill-recovery window, so its p99 IS the recovery spike --
        # the smoke asserts mechanics, the full run gates the SLOs
        args.slo_p99_ms = max(args.slo_p99_ms, 15000.0)
        args.gen_ttft_slo_ms = max(args.gen_ttft_slo_ms, 60000.0)
        # smoke capacity calibration is noisy on a loaded box (the gen
        # lane's intensity varies across a ~10 s window): shed_depth 32
        # + 3x pacing keep the paced phase over true capacity -- and
        # the brownout ladder exercised -- even when calibration
        # undershoots by ~2x
        args.shed_depth = min(args.shed_depth or 32, 32)
        args.overload = max(args.overload, 3.0)
    elif args.smoke:
        args.replicas = min(args.replicas, 2)
        if args.zipf:
            args.requests = min(args.requests, 600)
            # the CI smoke is shorter than a kill-recovery window
            # (restart + pending-entry reclaim), so its p99 IS the
            # recovery spike; it asserts the mechanics (shed, zero
            # inversions, exactly-once), the full run gates the SLO.
            # The ladder scales down with the run so the background
            # threshold is reachable within 600 requests
            args.slo_p99_ms = max(args.slo_p99_ms, 15000.0)
            args.shed_depth = min(args.shed_depth or 64, 64)
        else:
            args.requests = min(args.requests, 120)
            args.spec = "kill:replica:at=25"
    if args.zipf:
        args.rolling = False  # r01 is the rolling-restart evidence
        if args.reclaim_idle_ms == 1000.0:
            # faster pending-entry reclaim: a SIGKILLed replica's
            # claimed interactive requests re-serve in ~0.3s instead
            # of riding the default idle threshold into the p99
            args.reclaim_idle_ms = 250.0
        if args.spec == DEFAULT_SPEC:
            # one SIGKILL about a third of the way into the paced
            # phase: the at=K counter observes RESULTS (calibration
            # included), and at 2x overload only ~half the offered
            # requests are admitted, so K = calib + requests/6
            # (earlier in the smoke, whose shed rate runs higher)
            args.spec = "kill:replica:at=%d" % (
                _calib_count(args.requests)
                + args.requests // (12 if args.smoke else 6))
    if args.disaggregated:
        args.rolling = False  # r01 is the rolling-restart evidence
        args.spec = ""  # kills are role-targeted (kill_one), not chaos
        if args.reclaim_idle_ms == 1000.0:
            args.reclaim_idle_ms = 250.0

    import tempfile

    work_dir = args.work_dir or tempfile.mkdtemp(prefix="fleet-soak-")
    features, vocab, embed = (
        (ZIPF_FEATURES, ZIPF_VOCAB, ZIPF_EMBED)
        if args.zipf or args.disaggregated
        else (FEATURES, 50, 8))
    model_dir = build_model_dir(
        args.model_dir or os.path.join(work_dir, "model"),
        features=features, vocab=vocab, embed_dim=embed)

    from analytics_zoo_tpu.serving import chaos
    from analytics_zoo_tpu.serving.fleet import FleetController
    from analytics_zoo_tpu.serving.queues import _encode
    from analytics_zoo_tpu.serving.redis_adapter import RedisStreamQueue

    injector = chaos.install(chaos.ChaosInjector(
        chaos.parse_spec(args.spec), seed=args.seed))

    answered: dict = {}
    answer_times: dict = {}

    def on_result(uri, tensors):
        answered[uri] = answered.get(uri, 0) + 1
        answer_times[uri] = time.perf_counter()

    cfg = {"model": {"path": model_dir},
           "params": {"batch_size": 4, "timeout_ms": 2,
                      "warm_batch_sizes": [1, 4]}}
    env = {
        "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
        "AZT_ZOO_SERVING_FLEET_RECLAIM_IDLE_MS":
            str(args.reclaim_idle_ms),
    }
    fleet_kw: dict = {"replicas": args.replicas}
    total_replicas = args.replicas
    if args.disaggregated:
        # every replica still serves predict (the model block rides
        # along); the role split applies to the generation plane
        cfg["generation"] = {
            "model": {"vocab": 64, "dim": 32, "heads": 2,
                      "head_dim": 16, "layers": 2, "seed": 0},
            "max_tokens": args.gen_tokens,
            "stream_chunk_tokens": 1}
        env["AZT_ZOO_GENERATION_STEP_IDLE_MS"] = "5"
        fleet_kw = {"prefill_replicas": args.prefill_replicas,
                    "decode_replicas": args.decode_replicas}
        total_replicas = args.prefill_replicas + args.decode_replicas
        args.replicas = total_replicas
    fc = FleetController(cfg,
                         work_dir=os.path.join(work_dir, "fleet"),
                         env=env, seed=args.seed,
                         poll_interval_s=0.2, health_interval_s=0.4,
                         on_result=on_result, **fleet_kw)
    t0 = time.perf_counter()
    fc.start()
    rolling = {}
    extra: dict = {}
    try:
        if not fc.wait_healthy(total_replicas, timeout_s=300):
            print(json.dumps({"error": "fleet never became healthy",
                              "states": fc.replica_states(),
                              "recovered": False}))
            sys.exit(1)

        rng = np.random.RandomState(args.seed)
        xs = rng.randint(1, vocab, (64, features)).astype(np.int32)
        if args.disaggregated:
            # ---- split-pool drill: predict overload + generation
            # lane, one SIGKILL per pool ----
            extra = disagg_phase(args, fc, answered, answer_times, xs)
        elif args.zipf:
            # ---- overload drill: paced 2x load through the real
            # brownout admission ladder, SIGKILL mid-run ----
            extra = zipf_phase(args, fc, answered, answer_times, xs)
        else:
            # ---- phase 1: stream soak, replica SIGKILLs mid-run ----
            prod = RedisStreamQueue(fc.broker_address,
                                    stream="serving_stream")
            for i in range(args.requests):
                while not prod.put(_encode(
                        f"c{i:06d}", {"input": xs[i % len(xs)]})):
                    time.sleep(0.01)  # backpressured: fleet is busy
            deadline = time.time() + args.drain_timeout
            while (len(answered) < args.requests
                   and time.time() < deadline):
                time.sleep(0.1)

        # ---- phase 2: rolling restart under live HTTP traffic ----
        if args.rolling:
            fc.wait_healthy(args.replicas, timeout_s=120)
            codes: dict = {}
            stop_load = threading.Event()
            loader = threading.Thread(
                target=http_load,
                args=(fc.router.address, stop_load, codes),
                daemon=True)
            loader.start()
            ok = fc.rolling_restart(timeout_s=180)
            stop_load.set()
            loader.join(35.0)
            rolling = {
                "ok": ok,
                "min_healthy": fc.min_healthy_during_restart,
                "http_codes": {str(k): v for k, v in
                               sorted(codes.items())},
                "http_requests": sum(codes.values()),
                "http_5xx": sum(v for k, v in codes.items()
                                if k >= 500 or k < 0),
            }
    finally:
        elapsed = time.perf_counter() - t0
        fc.stop()
        chaos.uninstall()

    if os.environ.get("SOAK_DEBUG_ANSWERED"):
        with open(os.environ["SOAK_DEBUG_ANSWERED"], "w") as fh:
            json.dump(sorted(answered), fh)
    dups = sum(c - 1 for c in answered.values() if c > 1)
    # zipf mode: shed requests were never produced, so exactly-once
    # covers what the admission ladder let through (+ calibration)
    produced = extra.get("produced", args.requests)
    unanswered = produced - len(answered)
    # the broker's delivery ledger absorbs reclaim-race re-serves
    # (at-least-once redelivery under SIGKILL) -- suppressed re-serves
    # are reported as evidence, delivered duplicates fail the gate
    suppressed = (fc.broker.duplicates_suppressed
                  if fc.broker is not None else 0)
    exactly_once = unanswered == 0 and dups == 0
    rolling_clean = (not args.rolling
                     or (rolling.get("ok", False)
                         and rolling.get("http_5xx", 1) == 0))
    zipf_clean = (not args.zipf
                  or (extra.get("zipf_pass", False)
                      and fc.chaos_kills >= 1))
    disagg_clean = (not args.disaggregated
                    or extra.get("disagg_pass", False))
    line = {
        "requests": args.requests,
        "replicas": args.replicas,
        "answered": len(answered),
        "duplicates": dups,
        "reserves_suppressed": suppressed,
        "unanswered": unanswered,
        "replica_kills": fc.chaos_kills,
        "injected": injector.counts(),
        "restarts": {name: r["restarts"] for name, r in
                     fc.stats()["replicas"].items()},
        "rolling_restart": rolling,
        "elapsed_s": round(elapsed, 3),
        "rps": round(len(answered) / max(elapsed, 1e-9), 1),
        "seed": args.seed,
        "spec": args.spec,
        "exactly_once": exactly_once,
        "recovered": (exactly_once and rolling_clean and zipf_clean
                      and disagg_clean),
    }
    line.update(extra)
    print(json.dumps(line))
    sys.exit(0 if line["recovered"] else 1)


if __name__ == "__main__":
    main()
