#!/usr/bin/env python
"""Fleet chaos soak: replica-level faults under a seeded schedule
(ISSUE-9).

Drives a REAL fleet -- N ``python -m analytics_zoo_tpu.serving.launcher``
replica processes sharding one consumer-group stream behind the
FleetController's broker and router -- while a seeded chaos schedule
SIGKILLs whole replicas mid-run (``kill:replica:at=K`` fires after the
Kth observed result). Then, with HTTP traffic flowing through the
front-tier router, rolls a restart across every replica.

What "pass" looks like:
- every stream request is answered EXACTLY once (the broker's pending
  -entry reclaim re-serves a dead replica's claims; the worker's
  ack-on-reply keeps re-serves from double-answering);
- the rolling restart completes with ZERO 5xx from the router
  (quiesce -> drain -> restart, one replica at a time, capacity
  >= N-1 throughout).

Prints one JSON line (the chaos_serving.py convention) and exits 0
only when both hold.
"""

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

FEATURES = 6
DEFAULT_SPEC = "kill:replica:at=40;kill:replica:at=160"


def build_model_dir(path: str) -> str:
    """Train-and-save the tiny TextClassifier the replicas load (the
    launcher needs a ZooModel directory, not an in-process model)."""
    if os.path.isdir(path) and os.listdir(path):
        return path
    from analytics_zoo_tpu.models import TextClassifier

    rng = np.random.RandomState(0)
    x = rng.randint(1, 50, (64, FEATURES)).astype(np.int32)
    y = (x[:, 0] > 25).astype(np.int32)
    m = TextClassifier(class_num=2, vocab=50, embed_dim=8,
                       sequence_length=FEATURES)
    m.fit((x, y), batch_size=32, epochs=1)
    m.save_model(path)
    return path


def http_load(router_address: str, stop: threading.Event,
              counts: dict) -> None:
    """Sequential /predict loop through the router until stopped;
    tallies status codes (the rolling restart's zero-5xx evidence)."""
    body = json.dumps(
        {"inputs": {"input": [1, 2, 3, 4, 5, 6]}}).encode()
    while not stop.is_set():
        try:
            req = urllib.request.Request(
                router_address + "/predict", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                code = resp.status
        except urllib.error.HTTPError as e:
            code = e.code
        except (urllib.error.URLError, OSError):
            code = -1  # router itself unreachable (never expected)
        counts[code] = counts.get(code, 0) + 1


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=2000)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--spec", default=DEFAULT_SPEC,
                    help="chaos schedule (kill:replica:at=K;...)")
    ap.add_argument("--reclaim-idle-ms", type=float, default=1000.0)
    ap.add_argument("--drain-timeout", type=float, default=180.0,
                    help="seconds to wait for every request's answer")
    ap.add_argument("--rolling", action="store_true", default=True)
    ap.add_argument("--no-rolling", dest="rolling",
                    action="store_false")
    ap.add_argument("--model-dir", default=None)
    ap.add_argument("--work-dir", default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized run: 2 replicas, 120 requests, "
                         "one kill")
    args = ap.parse_args()
    if args.smoke:
        args.replicas = min(args.replicas, 2)
        args.requests = min(args.requests, 120)
        args.spec = "kill:replica:at=25"

    import tempfile

    work_dir = args.work_dir or tempfile.mkdtemp(prefix="fleet-soak-")
    model_dir = build_model_dir(
        args.model_dir or os.path.join(work_dir, "model"))

    from analytics_zoo_tpu.serving import chaos
    from analytics_zoo_tpu.serving.fleet import FleetController
    from analytics_zoo_tpu.serving.queues import _encode
    from analytics_zoo_tpu.serving.redis_adapter import RedisStreamQueue

    injector = chaos.install(chaos.ChaosInjector(
        chaos.parse_spec(args.spec), seed=args.seed))

    answered: dict = {}

    def on_result(uri, tensors):
        answered[uri] = answered.get(uri, 0) + 1

    cfg = {"model": {"path": model_dir},
           "params": {"batch_size": 4, "timeout_ms": 2,
                      "warm_batch_sizes": [1, 4]}}
    env = {
        "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
        "AZT_ZOO_SERVING_FLEET_RECLAIM_IDLE_MS":
            str(args.reclaim_idle_ms),
    }
    fc = FleetController(cfg, replicas=args.replicas,
                         work_dir=os.path.join(work_dir, "fleet"),
                         env=env, seed=args.seed,
                         poll_interval_s=0.2, health_interval_s=0.4,
                         on_result=on_result)
    t0 = time.perf_counter()
    fc.start()
    rolling = {}
    try:
        if not fc.wait_healthy(args.replicas, timeout_s=300):
            print(json.dumps({"error": "fleet never became healthy",
                              "states": fc.replica_states(),
                              "recovered": False}))
            sys.exit(1)

        # ---- phase 1: stream soak with replica SIGKILLs mid-run ----
        prod = RedisStreamQueue(fc.broker_address,
                                stream="serving_stream")
        rng = np.random.RandomState(args.seed)
        xs = rng.randint(1, 50, (64, FEATURES)).astype(np.int32)
        for i in range(args.requests):
            while not prod.put(_encode(f"c{i:06d}",
                                       {"input": xs[i % len(xs)]})):
                time.sleep(0.01)  # backpressured: the fleet is busy
        deadline = time.time() + args.drain_timeout
        while len(answered) < args.requests and time.time() < deadline:
            time.sleep(0.1)

        # ---- phase 2: rolling restart under live HTTP traffic ----
        if args.rolling:
            fc.wait_healthy(args.replicas, timeout_s=120)
            codes: dict = {}
            stop_load = threading.Event()
            loader = threading.Thread(
                target=http_load,
                args=(fc.router.address, stop_load, codes),
                daemon=True)
            loader.start()
            ok = fc.rolling_restart(timeout_s=180)
            stop_load.set()
            loader.join(35.0)
            rolling = {
                "ok": ok,
                "min_healthy": fc.min_healthy_during_restart,
                "http_codes": {str(k): v for k, v in
                               sorted(codes.items())},
                "http_requests": sum(codes.values()),
                "http_5xx": sum(v for k, v in codes.items()
                                if k >= 500 or k < 0),
            }
    finally:
        elapsed = time.perf_counter() - t0
        fc.stop()
        chaos.uninstall()

    dups = sum(c - 1 for c in answered.values() if c > 1)
    unanswered = args.requests - len(answered)
    # the broker's delivery ledger absorbs reclaim-race re-serves
    # (at-least-once redelivery under SIGKILL) -- suppressed re-serves
    # are reported as evidence, delivered duplicates fail the gate
    suppressed = (fc.broker.duplicates_suppressed
                  if fc.broker is not None else 0)
    exactly_once = unanswered == 0 and dups == 0
    rolling_clean = (not args.rolling
                     or (rolling.get("ok", False)
                         and rolling.get("http_5xx", 1) == 0))
    line = {
        "requests": args.requests,
        "replicas": args.replicas,
        "answered": len(answered),
        "duplicates": dups,
        "reserves_suppressed": suppressed,
        "unanswered": unanswered,
        "replica_kills": fc.chaos_kills,
        "injected": injector.counts(),
        "restarts": {name: r["restarts"] for name, r in
                     fc.stats()["replicas"].items()},
        "rolling_restart": rolling,
        "elapsed_s": round(elapsed, 3),
        "rps": round(len(answered) / max(elapsed, 1e-9), 1),
        "seed": args.seed,
        "spec": args.spec,
        "exactly_once": exactly_once,
        "recovered": exactly_once and rolling_clean,
    }
    print(json.dumps(line))
    sys.exit(0 if line["recovered"] else 1)


if __name__ == "__main__":
    main()
