#!/usr/bin/env bash
# Fast lint gate: zoolint over the package plus the tier-1 test
# modules that enforce its contracts (the zoolint gate itself, the
# CFG/lifecycle engine suite, and the metric/event vocabulary lint).
# Runs in seconds -- wire it before the
# full suite locally (pre-push) and first in CI so lint regressions
# fail fast.
#
# Usage:
#     scripts/check_tree.sh              # full package lint + gate tests
#     scripts/check_tree.sh --changed    # sub-second pre-push loop:
#                                        # lint only files changed vs HEAD
#     scripts/check_tree.sh --soak       # lint + a CI-sized fleet chaos
#                                        # soak (2 replica processes, one
#                                        # SIGKILL, rolling restart; ~2
#                                        # min) -- the exactly-once gate --
#                                        # plus the split-pool smoke (2
#                                        # prefill + 2 decode replicas,
#                                        # one SIGKILL per pool, KV-
#                                        # handoff exactly-once gate)
#                                        # plus the generation soak smoke
#                                        # (60 overlapping token streams,
#                                        # exact + exactly-once + A/B)
#                                        # plus the automl vectorized A/B
#                                        # smoke (8-trial cohort vs pool,
#                                        # per-trial reward parity gate)
#
# Any other arguments are forwarded to scripts/zoolint.py.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

SOAK=0
ARGS=()
for a in "$@"; do
    if [ "$a" = "--soak" ]; then SOAK=1; else ARGS+=("$a"); fi
done

echo "== zoolint =="
python scripts/zoolint.py "${ARGS[@]+"${ARGS[@]}"}"

echo "== gate tests (test_zoolint, test_zoolint_lifecycle, test_metric_names) =="
python -m pytest tests/test_zoolint.py tests/test_zoolint_lifecycle.py \
    tests/test_metric_names.py -q -p no:cacheprovider

if [ "$SOAK" = 1 ]; then
    echo "== slow acceptance drills (process-fleet, -m slow) =="
    python -m pytest tests/ -q -m slow -p no:cacheprovider
    echo "== fleet chaos soak (smoke) =="
    python scripts/fleet_soak.py --smoke
    echo "== fleet overload soak (zipf smoke) =="
    python scripts/fleet_soak.py --zipf --smoke
    echo "== disaggregated fleet soak (split-pool smoke) =="
    python scripts/fleet_soak.py --disaggregated --smoke
    echo "== generation soak (smoke) =="
    python scripts/perf_generation.py --smoke
    echo "== automl vectorized A/B (smoke) =="
    python scripts/perf_automl.py --smoke
fi
