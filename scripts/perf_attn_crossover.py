#!/usr/bin/env python
"""Attention-kernel crossover: einsum vs owned Pallas flash vs stock
flash, fwd+bwd at BERT-like geometry (h12 d64 bf16), token count held
constant while L sweeps. Interleaved rounds in one process (chip speed
swings ~±25%/hour). Produces the measured table that drives the
``zoo.ops.attention_flash_min_seq`` default (VERDICT r4 item 4).

Usage: python scripts/perf_attn_crossover.py [rounds]
"""
import functools
import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp

H, D = 12, 64
TOKENS = 48 * 384  # constant work per shape
ITERS = 20


def make_fns(L, causal=False):
    from analytics_zoo_tpu.ops.attention import _einsum_attention
    from analytics_zoo_tpu.ops.pallas_attention import (
        pallas_flash_attention_fwd)
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention)

    b = max(1, TOKENS // L)
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, H, L, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, H, L, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, H, L, D), jnp.bfloat16)

    def bench_fn(attn):
        def loss(q, k, v):
            return jnp.sum(attn(q, k, v).astype(jnp.float32))

        grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        def run():
            return grad(q, k, v)

        def sync(out):
            # block_until_ready returns without waiting on the axon
            # remote runtime; only a device->host VALUE pull actually
            # fences the serial device queue
            return float(jnp.sum(out[0].astype(jnp.float32)))

        return run, sync

    impls = {
        "einsum": bench_fn(functools.partial(_einsum_attention,
                                             causal=causal)),
        "flash_owned": bench_fn(
            lambda a, b_, c: pallas_flash_attention_fwd(a, b_, c,
                                                        causal)),
        "flash_stock": bench_fn(
            lambda a, b_, c: flash_attention(a, b_, c, causal=causal)),
    }
    return impls, b


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 5
    table = {}
    for L in (384, 512, 1024, 2048, 4096):
        impls, b = make_fns(L)
        # warm / compile
        for name, (run, sync) in impls.items():
            sync(run())
        times = {n: [] for n in impls}
        for _ in range(rounds):
            for name, (run, sync) in impls.items():
                t0 = time.perf_counter()
                for _i in range(ITERS):
                    out = run()
                sync(out)
                times[name].append((time.perf_counter() - t0) / ITERS)
        row = {n: round(min(ts) * 1e3, 3) for n, ts in times.items()}
        row["batch"] = b
        table[L] = row
        print(f"L={L} b={b}: " + "  ".join(
            f"{n}={v}ms" for n, v in row.items() if n != "batch"),
            flush=True)
    print(json.dumps(table))


if __name__ == "__main__":
    main()
