#!/usr/bin/env python
"""Generation serving soak + A/B (ISSUE-10).

Drives the REAL token-streaming data plane -- InputQueue ->
GenerationWorker (continuous batcher over the paged-KV DecodeEngine)
-> chunked replies on the OutputQueue -- with overlapping request
lifetimes (a bounded admission window keeps ``--concurrency`` streams
alive at once), then verifies the contract the acceptance criteria
name:

- **exactly-once**: every request's chunk seqs are contiguous from 0
  with exactly one terminal chunk, nothing unanswered, no duplicates;
- **token-exact**: every stream's tokens equal a SOLO decode of the
  same prompt (fresh single-slot engine, same params) -- continuous
  batching changes scheduling, never results;
- **zero recompile storms** (and zero live generation compiles) after
  warm-up -- the prefill ladder + fixed-shape decode step really do
  pin the XLA shape set;
- **A/B**: continuous batching vs the naive one-request-at-a-time
  decode baseline (slots=1 engine, same params) on tokens/sec, plus
  an optional cache-free re-prefill-per-token baseline
  (``--with-reprefill``).

Prints ONE JSON line (the perf_serving_pipeline.py convention) and
exits nonzero when any correctness gate fails. CPU host-device rig:
absolute numbers are hardware-dependent; the correctness gates and the
continuous-vs-naive ratio are the committed signal (GEN_r01.json,
BENCH_NOTES.md).
"""

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def pct(xs, q):
    return float(np.percentile(np.asarray(xs), q)) if xs else 0.0


def build_engine(args, slots):
    from analytics_zoo_tpu.serving.generation.engine import DecodeEngine
    from analytics_zoo_tpu.serving.generation.model import (
        GenModelConfig, TinyGenLM)

    cfg = GenModelConfig(vocab=64, dim=32, heads=2, head_dim=16,
                         layers=2, max_len=args.max_len,
                         seed=args.seed)
    return DecodeEngine(TinyGenLM(cfg), num_slots=slots,
                        page_size=args.page_size,
                        max_len=args.max_len)


def make_prompts(args):
    rng = np.random.RandomState(args.seed)
    return [rng.randint(0, 64, rng.randint(2, args.prompt_max))
            .astype(np.int32) for _ in range(args.prompt_pool)]


def solo_expected(params, prompts, args):
    """Ground truth per pool prompt: solo decode on a fresh 1-slot
    engine sharing the same params (the 'solo decode' of the
    acceptance criteria)."""
    eng = build_engine(args, slots=1)
    eng.params = params
    eng.warm_up()
    out = []
    for p in prompts:
        slot, t0 = eng.admit(p, args.max_tokens)
        toks = [t0]
        while len(toks) < args.max_tokens:
            toks.append(dict(eng.step())[slot])
        eng.release(slot)
        out.append(toks)
    return out


def run_continuous(args, engine, prompts):
    """The soak: ``--requests`` streams with overlapping lifetimes
    through one GenerationWorker; returns (records, elapsed_s)."""
    from analytics_zoo_tpu.serving.protocol import ERROR_KEY, STREAM_KEY
    from analytics_zoo_tpu.serving.queues import InputQueue, OutputQueue
    from analytics_zoo_tpu.serving.generation.worker import (
        GenerationWorker)

    in_q = InputQueue(backend="memory")
    out_q = OutputQueue(backend="memory")
    worker = GenerationWorker(engine, in_q, out_q)
    recs = {}
    done = threading.Event()
    lock = threading.Lock()
    finished = [0]

    def collector():
        while not done.is_set() or finished[0] < args.requests:
            item = out_q.dequeue(timeout=0.2)
            if item is None:
                if done.is_set() and finished[0] >= args.requests:
                    return
                continue
            now = time.perf_counter()
            uri, tensors = item
            rec = recs.get(uri)
            if rec is None:
                continue
            rec["chunk_t"].append(now)
            rec["seqs"].append(int(np.asarray(
                tensors[STREAM_KEY]).reshape(())))
            if ERROR_KEY in tensors:
                rec["error"] = str(np.asarray(
                    tensors[ERROR_KEY]).reshape(()))
                rec["terminal"] = rec.get("terminal", 0) + 1
                with lock:
                    finished[0] += 1
                continue
            if "token" in tensors:
                rec["toks"].extend(int(t) for t in np.asarray(
                    tensors["token"]).reshape(-1))
            if "finish_reason" in tensors:
                rec["terminal"] = rec.get("terminal", 0) + 1
                with lock:
                    finished[0] += 1

    col = threading.Thread(target=collector, daemon=True)
    col.start()
    worker.start()
    t_start = time.perf_counter()
    submitted = 0
    try:
        while finished[0] < args.requests:
            with lock:
                outstanding = submitted - finished[0]
            if submitted < args.requests and \
                    outstanding < args.concurrency:
                pool_i = submitted % len(prompts)
                uri = f"r{submitted}-p{pool_i}"
                recs[uri] = {"pool": pool_i, "toks": [], "seqs": [],
                             "chunk_t": [],
                             "enq_t": time.perf_counter()}
                in_q.enqueue_generation(uri, prompts[pool_i],
                                        max_tokens=args.max_tokens)
                submitted += 1
                continue
            time.sleep(0.001)
        elapsed = time.perf_counter() - t_start
    finally:
        done.set()
        col.join(10.0)
        worker.stop()
    return recs, elapsed


def run_naive_sequential(args, params, prompts, n):
    """Baseline: one-request-at-a-time decode (slots=1 engine, KV
    cache but zero batching) over the same workload shape."""
    eng = build_engine(args, slots=1)
    eng.params = params
    eng.warm_up()
    t0 = time.perf_counter()
    toks = 0
    for i in range(n):
        p = prompts[i % len(prompts)]
        slot, _ = eng.admit(p, args.max_tokens)
        produced = 1
        while produced < args.max_tokens:
            eng.step()
            produced += 1
        eng.release(slot)
        toks += produced
    return toks / (time.perf_counter() - t0)


def run_naive_reprefill(args, params, prompts, n):
    """Cache-free baseline: re-run the full prefix forward per token
    (eager; what serving generation through the predict path would
    amount to)."""
    from analytics_zoo_tpu.serving.generation.model import (
        GenModelConfig, TinyGenLM)

    cfg = GenModelConfig(vocab=64, dim=32, heads=2, head_dim=16,
                         layers=2, max_len=args.max_len,
                         seed=args.seed)
    lm = TinyGenLM(cfg)
    t0 = time.perf_counter()
    toks = 0
    for i in range(n):
        out = lm.reference_generate(params, prompts[i % len(prompts)],
                                    args.max_tokens)
        toks += len(out)
    return toks / (time.perf_counter() - t0)


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--requests", type=int, default=500)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--max-tokens", type=int, default=16)
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--prompt-pool", type=int, default=32)
    ap.add_argument("--prompt-max", type=int, default=24)
    ap.add_argument("--naive-requests", type=int, default=40,
                    help="requests for the sequential baseline "
                         "(tokens/sec is per-request stable, so a "
                         "subset suffices)")
    ap.add_argument("--with-reprefill", action="store_true",
                    help="also run the cache-free re-prefill baseline")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized run (60 requests, concurrency 4)")
    args = ap.parse_args()
    if args.smoke:
        args.requests = 60
        args.concurrency = 4
        args.naive_requests = 10
        args.prompt_pool = 12
    assert args.prompt_max + args.max_tokens <= args.max_len

    from analytics_zoo_tpu.obs.events import get_event_log

    prompts = make_prompts(args)
    engine = build_engine(args, slots=args.slots)
    engine.warm_up()
    expected = solo_expected(engine.params, prompts, args)
    log = get_event_log()
    live_before = len([
        e for e in log.tail(100000, type="compile")
        if e["fields"]["fn"].startswith("generation.")
        and not e["fields"]["warm"]])

    recs, elapsed = run_continuous(args, engine, prompts)

    # ---------------------------------------------------- verdicts --
    exact = exactly_once = True
    unanswered = errors = 0
    ttft_ms, intertoken_ms = [], []
    for uri, rec in recs.items():
        if not rec.get("terminal"):
            unanswered += 1
            exactly_once = False
            continue
        if rec.get("terminal", 0) != 1:
            exactly_once = False
        if "error" in rec:
            errors += 1
            exact = False
            continue
        data_seqs = [s for s in rec["seqs"] if s >= 0]
        if data_seqs != list(range(len(data_seqs))):
            exactly_once = False
        if rec["toks"] != expected[rec["pool"]]:
            exact = False
        if rec["chunk_t"]:
            ttft_ms.append((rec["chunk_t"][0] - rec["enq_t"]) * 1e3)
            gaps = np.diff(rec["chunk_t"])
            intertoken_ms.extend(float(g) * 1e3 for g in gaps)
    total_tokens = sum(len(r["toks"]) for r in recs.values())
    cont_tps = total_tokens / elapsed if elapsed else 0.0

    storms = [e for e in log.tail(100000, type="recompile_storm")
              if e["subsystem"] == "generation"]
    live_after = len([
        e for e in log.tail(100000, type="compile")
        if e["fields"]["fn"].startswith("generation.")
        and not e["fields"]["warm"]])

    naive_tps = run_naive_sequential(args, engine.params, prompts,
                                     args.naive_requests)
    reprefill_tps = (run_naive_reprefill(
        args, engine.params, prompts,
        max(4, args.naive_requests // 4))
        if args.with_reprefill else None)

    ok = (exact and exactly_once and unanswered == 0 and errors == 0
          and not storms and live_after == live_before
          and cont_tps > naive_tps)
    line = {
        "mode": "perf_generation",
        "requests": args.requests,
        "concurrency": args.concurrency,
        "max_tokens": args.max_tokens,
        "slots": args.slots,
        "elapsed_s": round(elapsed, 3),
        "tokens_total": total_tokens,
        "tokens_per_s": round(cont_tps, 2),
        "ttft_ms": {"p50": round(pct(ttft_ms, 50), 2),
                    "p99": round(pct(ttft_ms, 99), 2)},
        "intertoken_ms": {"p50": round(pct(intertoken_ms, 50), 3),
                          "p99": round(pct(intertoken_ms, 99), 3)},
        "exact": exact,
        "exactly_once": exactly_once,
        "unanswered": unanswered,
        "errors": errors,
        "storms_after_warmup": len(storms),
        "live_compiles_after_warmup": live_after - live_before,
        "ab": {
            "continuous_tps": round(cont_tps, 2),
            "naive_sequential_tps": round(naive_tps, 2),
            "speedup": round(cont_tps / naive_tps, 2)
            if naive_tps else None,
            "naive_requests": args.naive_requests,
            "reprefill_tps": (round(reprefill_tps, 2)
                              if reprefill_tps is not None else None),
        },
        "seed": args.seed,
        "ok": ok,
    }
    print(json.dumps(line))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
