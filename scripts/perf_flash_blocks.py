#!/usr/bin/env python
"""Block-size autotune for the owned flash kernel at long context.

The fwd caps blocks at 1024 and the bwd at 512 (VMEM budget sized for
d=128). At d=64 the q/k/v/do tiles and scratch halve, so larger bwd
blocks may fit and pipeline better. A/B at L in {1024, 2048, 4096},
fwd+bwd, interleaved rounds, scalar-pull fence.

Usage: python scripts/perf_flash_blocks.py [rounds]
"""
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.ops.pallas_attention import (
    pallas_flash_attention_fwd)

H, D = 12, 64
TOKENS = 48 * 384
ITERS = 10


def runner(L, block_q, block_k):
    b = max(1, TOKENS // L)
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, H, L, D), jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(pallas_flash_attention_fwd(
            q, k, v, False, None, block_q, block_k).astype(jnp.float32))

    grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    def run():
        out = None
        for _ in range(ITERS):
            out = grad(q, q, q)
        return float(jnp.sum(out[0].astype(jnp.float32)))

    run()
    return run


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    for L in (1024, 2048, 4096):
        cfgs = {}
        for bq in (None, 256, 512, 1024):
            if bq is not None and bq > L:
                continue
            name = f"bq{bq or 'auto'}"
            try:
                cfgs[name] = runner(L, bq, bq)
            except Exception as e:
                print(f"L={L} {name}: failed {str(e)[:80]}", flush=True)
        best = {}
        for _ in range(rounds):
            for name, run in cfgs.items():
                t0 = time.perf_counter()
                run()
                dt = (time.perf_counter() - t0) / ITERS
                best[name] = min(best.get(name, dt), dt)
        print(f"L={L}: " + "  ".join(
            f"{n}={v*1e3:.2f}ms" for n, v in sorted(best.items())),
            flush=True)


if __name__ == "__main__":
    main()
