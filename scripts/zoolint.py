#!/usr/bin/env python
"""zoolint CLI: run the analytics_zoo_tpu.analysis checkers.

Usage:
    python scripts/zoolint.py [paths ...]          # default: analytics_zoo_tpu
    python scripts/zoolint.py --json analytics_zoo_tpu
    python scripts/zoolint.py --format sarif > zoolint.sarif
    python scripts/zoolint.py --profile            # per-family timing table
    python scripts/zoolint.py --baseline zoolint_baseline.json pkg/
    python scripts/zoolint.py --update-baseline    # grandfather current findings
    python scripts/zoolint.py --list-rules
    python scripts/zoolint.py --rules silent-except,lock-guard pkg/
    python scripts/zoolint.py --changed            # only files changed vs HEAD
    python scripts/zoolint.py --changed origin/main

Exit status: 0 when every finding is baselined (or there are none);
1 when any NEW finding exists; 2 on usage errors. The tier-1 test
``tests/test_zoolint.py`` enforces the same contract in CI.
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, "zoolint_baseline.json")

# zoolint severity -> SARIF result level
_SARIF_LEVELS = {"error": "error", "warning": "warning", "info": "note"}


def _sarif_log(findings, baseline, rule_catalog):
    """Minimal SARIF 2.1.0 log: one run, the full rule catalog in the
    driver (so viewers resolve ruleIndex even for clean runs), one
    result per finding. ``baselineState`` carries the baseline verdict
    so GitHub code scanning only annotates NEW findings."""
    rule_ids = sorted(rule_catalog)
    rule_index = {r: i for i, r in enumerate(rule_ids)}
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "ruleIndex": rule_index.get(f.rule, -1),
            "level": _SARIF_LEVELS.get(f.severity, "warning"),
            "message": {"text": f.message},
            "baselineState": ("unchanged" if f.key() in baseline
                              else "new"),
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "%SRCROOT%"},
                    # SARIF regions are 1-based; whole-file findings
                    # (line 0) anchor to the first line
                    "region": {"startLine": max(f.line, 1)},
                },
            }],
        })
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "zoolint",
                "informationUri":
                    "https://github.com/analytics-zoo-tpu",
                "rules": [{"id": r,
                           "shortDescription":
                               {"text": rule_catalog[r]}}
                          for r in rule_ids],
            }},
            "columnKind": "utf16CodeUnits",
            "originalUriBaseIds": {"%SRCROOT%": {"uri": "file:///"}},
            "results": results,
        }],
    }


def _print_profile(timings, n_findings):
    """Per-family wall-clock table on stderr (stdout stays parseable
    for --format json/sarif consumers)."""
    total = sum(timings.values())
    print("zoolint profile (wall seconds per checker family):",
          file=sys.stderr)
    for name, secs in sorted(timings.items(),
                             key=lambda kv: -kv[1]):
        pct = 100.0 * secs / total if total else 0.0
        print(f"  {name:14s} {secs:7.3f}s  {pct:5.1f}%",
              file=sys.stderr)
    print(f"  {'total':14s} {total:7.3f}s  ({n_findings} finding(s))",
          file=sys.stderr)


def _changed_files(ref: str):
    """Absolute paths of .py files changed vs ``ref`` (tracked diff +
    untracked), or None when git itself fails (not a repo, bad ref) --
    the caller falls back to a full run rather than linting nothing."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "-z", ref],
            cwd=REPO, capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard", "-z"],
            cwd=REPO, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0 or untracked.returncode != 0:
        return None
    names = [n for out in (diff.stdout, untracked.stdout)
             for n in out.split("\0") if n]
    return sorted({os.path.join(REPO, n) for n in names
                   if n.endswith(".py") and os.path.isfile(
                       os.path.join(REPO, n))})


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="zoolint",
        description="repo-native static analysis: jit/trace hazards, "
                    "serving concurrency, config-key drift, "
                    "metric/event vocabulary, exception hygiene")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: the "
                         "analytics_zoo_tpu package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output (alias for "
                         "--format json)")
    ap.add_argument("--format", default=None, dest="fmt",
                    choices=("text", "json", "sarif"),
                    help="output format; sarif emits a SARIF 2.1.0 "
                         "log for GitHub code-scanning annotations "
                         "(baselined findings are marked unchanged)")
    ap.add_argument("--profile", action="store_true",
                    help="print per-checker-family wall-clock timings "
                         "to stderr after the run")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline json of grandfathered findings "
                         "(default: zoolint_baseline.json at the repo "
                         "root, when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline; every finding is new")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(rationales for surviving entries are kept)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset to run")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--graph", action="store_true",
                    help="dump deepcheck's resolved call graph as "
                         "JSON (contexts, taint, edges, unresolved "
                         "counts) instead of linting -- the debugging "
                         "surface for 'why did/didn't this propagate'")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="lint only .py files changed vs a git ref "
                         "(default HEAD: working-tree edits + "
                         "untracked). Project-wide ground truth is "
                         "still read from the full tree; findings "
                         "outside the changed files are dropped")
    args = ap.parse_args(argv)
    fmt = args.fmt or ("json" if args.as_json else "text")

    def _nothing_changed(detail: str) -> int:
        # the pre-push fast path: nothing to lint (none of the heavy
        # imports below ever run). --json/--format consumers still
        # get the documented object shape, not a prose line; an empty
        # SARIF log carries no rule catalog (uploaders only read
        # results from it).
        if fmt == "sarif":
            print(json.dumps(_sarif_log([], {}, {}), indent=2,
                             sort_keys=True))
        elif fmt == "json":
            print(json.dumps({
                "findings": [], "new": [], "stale_baseline": [],
                "counts": {"total": 0, "new": 0, "baselined": 0,
                           "stale_baseline": 0},
            }, indent=2, sort_keys=True))
        else:
            print(f"zoolint: {detail}; 0 finding(s), 0 new")
        return 0

    report_only = None
    if args.changed is not None:
        if args.update_baseline:
            # a changed-files slice must not rewrite the baseline for
            # the same reason a --rules slice must not
            print("zoolint: --update-baseline requires a full run "
                  "(drop --changed)", file=sys.stderr)
            return 2
        report_only = _changed_files(args.changed)
        if report_only is None:
            print("zoolint: --changed: git unavailable or bad ref; "
                  "falling back to a full run", file=sys.stderr)
        elif not report_only:
            return _nothing_changed(
                f"no python files changed vs {args.changed}")

    from analytics_zoo_tpu.analysis import all_rules, run_zoolint
    from analytics_zoo_tpu.analysis.baseline import (
        load_baseline, new_findings, stale_entries, write_baseline)

    if args.graph:
        from analytics_zoo_tpu.analysis.callgraph import \
            build_call_graph
        from analytics_zoo_tpu.analysis.core import (
            Project, collect_files)

        paths = args.paths or [os.path.join(REPO, "analytics_zoo_tpu")]
        files, repo_root = collect_files(paths)
        graph = build_call_graph(Project(files, repo_root=repo_root))
        print(json.dumps(graph.to_dict(), indent=2, sort_keys=True))
        return 0

    if args.list_rules:
        for rule, desc in sorted(all_rules().items()):
            print(f"{rule:22s} {desc}")
        return 0

    if args.update_baseline and args.rules:
        # a filtered run sees only a slice of the findings; rewriting
        # the baseline from it would silently drop every grandfathered
        # entry (and rationale) outside the slice
        print("zoolint: --update-baseline requires a full-rule run "
              "(drop --rules)", file=sys.stderr)
        return 2

    paths = args.paths or [os.path.join(REPO, "analytics_zoo_tpu")]
    if report_only is not None:
        # keep only changed files under the lint paths (a changed
        # test/ script outside them is not this run's business)
        roots = [os.path.abspath(p) for p in paths]
        report_only = [f for f in report_only
                       if any(f == r or f.startswith(r + os.sep)
                              for r in roots)]
        if not report_only:
            return _nothing_changed(
                f"no changed python files under {', '.join(paths)}")
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    if rules:
        unknown = set(rules) - set(all_rules())
        if unknown:
            print(f"zoolint: unknown rules: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
    timings = {} if args.profile else None
    findings = run_zoolint(paths, rules=rules, report_only=report_only,
                           timings=timings)
    if timings is not None:
        _print_profile(timings, len(findings))

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        baseline_path = (DEFAULT_BASELINE
                         if os.path.isfile(DEFAULT_BASELINE) else None)
    if args.no_baseline:
        baseline_path = None
    baseline = load_baseline(baseline_path) if baseline_path else {}

    if args.update_baseline:
        out_path = args.baseline or DEFAULT_BASELINE
        n = write_baseline(findings, out_path, baseline)
        print(f"zoolint: baseline written: {out_path} ({n} findings; "
              "fill in a rationale for each new entry)")
        return 0

    fresh = new_findings(findings, baseline)
    # a --changed slice cannot see findings outside its files, so it
    # cannot judge staleness -- only the full run reports it
    stale = (stale_entries(findings, baseline)
             if baseline and report_only is None else [])

    if fmt == "sarif":
        print(json.dumps(_sarif_log(findings, baseline, all_rules()),
                         indent=2, sort_keys=True))
        return 1 if fresh else 0

    if fmt == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "new": [f.to_dict() for f in fresh],
            "stale_baseline": stale,
            "counts": {"total": len(findings), "new": len(fresh),
                       "baselined": len(findings) - len(fresh),
                       "stale_baseline": len(stale)},
        }, indent=2, sort_keys=True))
        return 1 if fresh else 0

    for f in findings:
        mark = "" if f.key() in baseline else " (new)"
        print(f.render() + mark)
    for e in stale:
        print(f"stale baseline entry (finding no longer fires -- run "
              f"--update-baseline): [{e['rule']}] {e['path']}: "
              f"{e['message']}")
    print(f"zoolint: {len(findings)} finding(s), {len(fresh)} new, "
          f"{len(findings) - len(fresh)} baselined, "
          f"{len(stale)} stale baseline entr(y/ies)")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
