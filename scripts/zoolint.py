#!/usr/bin/env python
"""zoolint CLI: run the analytics_zoo_tpu.analysis checkers.

Usage:
    python scripts/zoolint.py [paths ...]          # default: analytics_zoo_tpu
    python scripts/zoolint.py --json analytics_zoo_tpu
    python scripts/zoolint.py --baseline zoolint_baseline.json pkg/
    python scripts/zoolint.py --update-baseline    # grandfather current findings
    python scripts/zoolint.py --list-rules
    python scripts/zoolint.py --rules silent-except,lock-guard pkg/
    python scripts/zoolint.py --changed            # only files changed vs HEAD
    python scripts/zoolint.py --changed origin/main

Exit status: 0 when every finding is baselined (or there are none);
1 when any NEW finding exists; 2 on usage errors. The tier-1 test
``tests/test_zoolint.py`` enforces the same contract in CI.
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, "zoolint_baseline.json")


def _changed_files(ref: str):
    """Absolute paths of .py files changed vs ``ref`` (tracked diff +
    untracked), or None when git itself fails (not a repo, bad ref) --
    the caller falls back to a full run rather than linting nothing."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "-z", ref],
            cwd=REPO, capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard", "-z"],
            cwd=REPO, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0 or untracked.returncode != 0:
        return None
    names = [n for out in (diff.stdout, untracked.stdout)
             for n in out.split("\0") if n]
    return sorted({os.path.join(REPO, n) for n in names
                   if n.endswith(".py") and os.path.isfile(
                       os.path.join(REPO, n))})


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="zoolint",
        description="repo-native static analysis: jit/trace hazards, "
                    "serving concurrency, config-key drift, "
                    "metric/event vocabulary, exception hygiene")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: the "
                         "analytics_zoo_tpu package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline json of grandfathered findings "
                         "(default: zoolint_baseline.json at the repo "
                         "root, when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline; every finding is new")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(rationales for surviving entries are kept)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset to run")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--graph", action="store_true",
                    help="dump deepcheck's resolved call graph as "
                         "JSON (contexts, taint, edges, unresolved "
                         "counts) instead of linting -- the debugging "
                         "surface for 'why did/didn't this propagate'")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="lint only .py files changed vs a git ref "
                         "(default HEAD: working-tree edits + "
                         "untracked). Project-wide ground truth is "
                         "still read from the full tree; findings "
                         "outside the changed files are dropped")
    args = ap.parse_args(argv)

    def _nothing_changed(detail: str) -> int:
        # the pre-push fast path: nothing to lint (none of the heavy
        # imports below ever run). --json consumers still get the
        # documented object shape, not a prose line.
        if args.as_json:
            print(json.dumps({
                "findings": [], "new": [], "stale_baseline": [],
                "counts": {"total": 0, "new": 0, "baselined": 0,
                           "stale_baseline": 0},
            }, indent=2, sort_keys=True))
        else:
            print(f"zoolint: {detail}; 0 finding(s), 0 new")
        return 0

    report_only = None
    if args.changed is not None:
        if args.update_baseline:
            # a changed-files slice must not rewrite the baseline for
            # the same reason a --rules slice must not
            print("zoolint: --update-baseline requires a full run "
                  "(drop --changed)", file=sys.stderr)
            return 2
        report_only = _changed_files(args.changed)
        if report_only is None:
            print("zoolint: --changed: git unavailable or bad ref; "
                  "falling back to a full run", file=sys.stderr)
        elif not report_only:
            return _nothing_changed(
                f"no python files changed vs {args.changed}")

    from analytics_zoo_tpu.analysis import all_rules, run_zoolint
    from analytics_zoo_tpu.analysis.baseline import (
        load_baseline, new_findings, stale_entries, write_baseline)

    if args.graph:
        from analytics_zoo_tpu.analysis.callgraph import \
            build_call_graph
        from analytics_zoo_tpu.analysis.core import (
            Project, collect_files)

        paths = args.paths or [os.path.join(REPO, "analytics_zoo_tpu")]
        files, repo_root = collect_files(paths)
        graph = build_call_graph(Project(files, repo_root=repo_root))
        print(json.dumps(graph.to_dict(), indent=2, sort_keys=True))
        return 0

    if args.list_rules:
        for rule, desc in sorted(all_rules().items()):
            print(f"{rule:22s} {desc}")
        return 0

    if args.update_baseline and args.rules:
        # a filtered run sees only a slice of the findings; rewriting
        # the baseline from it would silently drop every grandfathered
        # entry (and rationale) outside the slice
        print("zoolint: --update-baseline requires a full-rule run "
              "(drop --rules)", file=sys.stderr)
        return 2

    paths = args.paths or [os.path.join(REPO, "analytics_zoo_tpu")]
    if report_only is not None:
        # keep only changed files under the lint paths (a changed
        # test/ script outside them is not this run's business)
        roots = [os.path.abspath(p) for p in paths]
        report_only = [f for f in report_only
                       if any(f == r or f.startswith(r + os.sep)
                              for r in roots)]
        if not report_only:
            return _nothing_changed(
                f"no changed python files under {', '.join(paths)}")
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    if rules:
        unknown = set(rules) - set(all_rules())
        if unknown:
            print(f"zoolint: unknown rules: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
    findings = run_zoolint(paths, rules=rules, report_only=report_only)

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        baseline_path = (DEFAULT_BASELINE
                         if os.path.isfile(DEFAULT_BASELINE) else None)
    if args.no_baseline:
        baseline_path = None
    baseline = load_baseline(baseline_path) if baseline_path else {}

    if args.update_baseline:
        out_path = args.baseline or DEFAULT_BASELINE
        n = write_baseline(findings, out_path, baseline)
        print(f"zoolint: baseline written: {out_path} ({n} findings; "
              "fill in a rationale for each new entry)")
        return 0

    fresh = new_findings(findings, baseline)
    # a --changed slice cannot see findings outside its files, so it
    # cannot judge staleness -- only the full run reports it
    stale = (stale_entries(findings, baseline)
             if baseline and report_only is None else [])

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "new": [f.to_dict() for f in fresh],
            "stale_baseline": stale,
            "counts": {"total": len(findings), "new": len(fresh),
                       "baselined": len(findings) - len(fresh),
                       "stale_baseline": len(stale)},
        }, indent=2, sort_keys=True))
        return 1 if fresh else 0

    for f in findings:
        mark = "" if f.key() in baseline else " (new)"
        print(f.render() + mark)
    for e in stale:
        print(f"stale baseline entry (finding no longer fires -- run "
              f"--update-baseline): [{e['rule']}] {e['path']}: "
              f"{e['message']}")
    print(f"zoolint: {len(findings)} finding(s), {len(fresh)} new, "
          f"{len(findings) - len(fresh)} baselined, "
          f"{len(stale)} stale baseline entr(y/ies)")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
