#!/usr/bin/env python
"""Gradient-accumulation A/B for BERT SQuAD fine-tune (r5 target:
bert_mfu >= 0.40 recorded).

One process, interleaved round-robin windows over configs -- the chip's
speed swings ~±25%/hour, so only windows measured side by side compare.
Each window runs the SAME token count (48*16*384) through the full
Estimator.fit loop.

Usage: python scripts/perf_bert_accum.py [rounds]
"""
import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

BERT_VOCAB, SEQ = 30522, 384
TOKENS = 48 * 16  # samples per window (x SEQ tokens)
PEAK = 197e12


def build(batch, accum):
    from analytics_zoo_tpu.common.config import get_config
    from analytics_zoo_tpu.models.text.bert_squad import BERTSQuAD

    get_config().set("zoo.train.log_every_n_steps", 100000)
    rng = np.random.RandomState(0)
    n = TOKENS
    x = {"input_ids": rng.randint(0, BERT_VOCAB, (n, SEQ)
                                  ).astype(np.int32)}
    y = np.stack([rng.randint(0, SEQ, n), rng.randint(0, SEQ, n)],
                 axis=1).astype(np.int32)
    model = BERTSQuAD(vocab=BERT_VOCAB, dtype="bfloat16")
    if accum > 1:
        model.compile(grad_accum_steps=accum)
    model.fit((x, y), batch_size=batch, epochs=1)  # compile epoch
    return model, x, y


def window(model, x, y, batch):
    est = model.estimator
    t0 = time.perf_counter()
    model.fit((x, y), batch_size=batch, epochs=est.epoch + 1)
    dt = time.perf_counter() - t0
    return TOKENS * SEQ / dt  # tokens/sec


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 6
    import jax  # noqa: F401  (device init before timing)

    from analytics_zoo_tpu.models.text.bert_squad import BERTSQuAD

    cfgs = [("b48", 48, 1), ("b96a2", 96, 2), ("b192a4", 192, 4)]
    models = {}
    for name, batch, accum in cfgs:
        print(f"building {name} ...", flush=True)
        for attempt in range(3):
            try:
                models[name] = build(batch, accum)
                break
            except Exception as e:  # tunnel remote-compile hiccups
                print(f"  build {name} attempt {attempt}: {e}",
                      flush=True)
                time.sleep(10.0)
        else:
            print(f"  skipping {name}")
            cfgs = [c for c in cfgs if c[0] != name]

    if "b48" not in models:
        print("baseline b48 never built; aborting", file=sys.stderr)
        sys.exit(1)
    # flops/token: same formula as bench.py measure_bert
    m0 = models["b48"][0]
    import jax as _j

    p_dense = sum(
        int(l.size) for p, l in _j.tree_util.tree_flatten_with_path(
            m0.estimator.variables["params"])[0]
        if "embed" not in "/".join(str(q) for q in p).lower())
    c = m0._config
    fpt = 6 * p_dense + 12 * c["n_block"] * c["hidden_size"] * SEQ

    results = {name: [] for name, _, _ in cfgs}
    for r in range(rounds):
        for name, batch, accum in cfgs:
            tps = window(models[name][0], models[name][1],
                         models[name][2], batch)
            mfu = tps * fpt / PEAK
            results[name].append(mfu)
            print(f"round {r} {name}: {mfu:.4f}", flush=True)
    out = {}
    for name in results:
        s = sorted(results[name])
        out[name] = {"best": round(s[-1], 4),
                     "median": round(s[len(s) // 2], 4)}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
