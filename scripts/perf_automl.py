#!/usr/bin/env python
"""Vectorized AutoML A/B (ISSUE-13).

Runs the SAME zouwu time-series search (fixed LSTM architecture, an
lr grid -- one shape-compatible cohort) through two executors:

- **vectorized**: every trial is a lane of ONE vmapped population --
  the whole sweep is a handful of XLA dispatches;
- **process**: the reference shape, one trial per spawn-pool worker
  (the pool also replays the sequential per-trial semantics, so its
  rewards double as the parity baseline).

Headline: trials/sec each way + the speedup. Gates (exit nonzero on
failure):

- **parity**: per-trial rewards match across executors to float
  tolerance (same sampled configs by seed; a population lane replays
  the solo Estimator trajectory by construction);
- **one-cohort**: the vectorized run dispatched exactly one cohort
  (fixed arch + lr-only variation must not split);
- **no fallback**: no trial escaped to the sequential rescue path.

Prints ONE JSON line (the perf_serving_pipeline.py convention).
CPU-rig caveats in BENCH_NOTES.md: absolute trials/sec is hardware-
dependent; the parity gates and the vectorized-vs-pool ratio are the
committed signal (AUTOML_r01.json).
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def make_data(n):
    import pandas as pd

    rng = np.random.RandomState(7)
    dt = pd.date_range("2020-01-01", periods=n, freq="1h")
    value = (np.sin(np.arange(n) * 2 * np.pi / 24)
             + 0.1 * rng.randn(n)).astype(np.float32)
    df = pd.DataFrame({"datetime": dt, "value": value})
    spec = {"future_seq_len": 1, "dt_col": "datetime",
            "target_col": ["value"], "extra_features_col": None,
            "drop_missing": True}
    return {"spec": spec, "train_df": df.iloc[:int(n * 0.8)],
            "validation_df": df.iloc[int(n * 0.75):]}


def make_space(trials, epochs):
    from analytics_zoo_tpu.automl.space import Grid

    lrs = list(np.geomspace(3e-4, 0.3, trials).astype(float))
    return {"model": "LSTM", "lstm_1_units": 16, "lstm_2_units": 8,
            "dropout_1": 0.2, "dropout_2": 0.2, "lr": Grid(lrs),
            "batch_size": 32, "epochs": epochs,
            "selected_features": ["hour"], "past_seq_len": 6}


def run_search(executor, space, data, workers):
    from analytics_zoo_tpu.automl.predictor import time_sequence_trial
    from analytics_zoo_tpu.automl.search import SearchEngine

    eng = SearchEngine(executor=executor, max_workers=workers)
    eng.compile(data, time_sequence_trial, search_space=dict(space),
                metric="mse", seed=0)
    t0 = time.perf_counter()
    eng.run()
    return eng, time.perf_counter() - t0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--rows", type=int, default=200)
    ap.add_argument("--workers", type=int,
                    default=min(4, os.cpu_count() or 1))
    ap.add_argument("--tol", type=float, default=1e-5,
                    help="per-trial |mse_vec - mse_pool| parity gate")
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized: 8 trials, 1 epoch")
    args = ap.parse_args()
    if args.smoke:
        args.trials, args.epochs, args.rows = 8, 1, 160

    from analytics_zoo_tpu.obs.events import get_event_log
    from analytics_zoo_tpu.obs.metrics import get_registry

    data = make_data(args.rows)
    space = make_space(args.trials, args.epochs)

    vec, vec_s = run_search("vectorized", space, data, args.workers)
    pool, pool_s = run_search("process", space, data, args.workers)

    assert [t.config["lr"] for t in vec.trials] == \
        [t.config["lr"] for t in pool.trials], "config plans diverged"
    errors = sum(1 for t in vec.trials + pool.trials
                 if t.error is not None)
    diffs = [abs(a.reward - b.reward)
             for a, b in zip(vec.trials, pool.trials)
             if a.error is None and b.error is None]
    max_diff = max(diffs) if diffs else float("inf")
    cohorts = len({t.extras.get("cohort") for t in vec.trials
                   if t.extras.get("cohort") is not None})
    vec_paths = get_registry().snapshot().get(
        "zoo_automl_vectorized_trials_total", {}).get("values", {})
    fallbacks = int(vec_paths.get("path=fallback", 0))
    train_compiles = len(
        [e for e in get_event_log().tail(type="compile")
         if e.get("fields", {}).get("fn") == "population.train_step"])

    ok = (errors == 0 and max_diff <= args.tol and cohorts == 1
          and fallbacks == 0)
    line = {
        "mode": "perf_automl",
        "trials": args.trials,
        "epochs": args.epochs,
        "rows": args.rows,
        "vectorized_s": round(vec_s, 3),
        "pool_s": round(pool_s, 3),
        "pool_workers": args.workers,
        "vectorized_trials_per_s": round(args.trials / vec_s, 3),
        "pool_trials_per_s": round(args.trials / pool_s, 3),
        "speedup": round(pool_s / vec_s, 2) if vec_s else None,
        "cohorts": cohorts,
        "train_step_compiles": train_compiles,
        "reward_max_abs_diff": max_diff,
        "parity_tol": args.tol,
        "trial_errors": errors,
        "fallback_trials": fallbacks,
        "best_lr": {"vectorized":
                    vec.get_best_trials(1)[0].config["lr"],
                    "pool": pool.get_best_trials(1)[0].config["lr"]},
        "ok": ok,
    }
    print(json.dumps(line))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
