"""SSD object-detection predict pipeline
(ref: pyzoo/zoo/examples/objectdetection/predict.py): detect() on a
batch of images and draw the boxes.
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse

import numpy as np

from analytics_zoo_tpu.models import ObjectDetector
from analytics_zoo_tpu.models.image.object_detection import visualize

LABELS = {1: "cat", 2: "dog", 3: "bird"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default=None,
                    help="optional path to save a visualization png")
    args = ap.parse_args()

    det = ObjectDetector(class_num=3, image_size=128,
                         label_map=LABELS)
    rng = np.random.RandomState(0)
    images = rng.uniform(0, 255, (4, 128, 128, 3)).astype(np.float32)
    results = det.detect(images / 255.0, score_threshold=0.3, top_k=5)
    for i, dets in enumerate(results):
        pretty = [(det.label_of(c), round(s, 3)) for c, s, _ in dets]
        print(f"image {i}: {pretty}")
    # structural bar: detections are (class, score, box) with scores
    # in [0, 1], at most top_k per image, boxes inside the image
    for dets in results:
        assert len(dets) <= 5
        for c, s, box in dets:
            assert 0.0 <= s <= 1.0
            assert det.label_of(c) in LABELS.values()
            x0, y0, x1, y1 = box
            assert x0 <= x1 and y0 <= y1
            assert -1 <= x0 and x1 <= 129 and -1 <= y0 and y1 <= 129

    if args.out:
        from PIL import Image

        drawn = visualize(images[0], results[0], LABELS)
        Image.fromarray(drawn).save(args.out)
        print("saved", args.out)


if __name__ == "__main__":
    main()
