"""QA ranking with KNRM over Relations
(ref: pyzoo/zoo/examples/qaranker/qa_ranker.py): question/answer
corpora -> relation pairs -> pairwise rank_hinge training -> NDCG-style
check that positives outrank negatives.
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse

import numpy as np

from analytics_zoo_tpu.feature import Relation, TextSet
from analytics_zoo_tpu.feature.text import (
    from_relation_lists, from_relation_pairs)
from analytics_zoo_tpu.models import KNRM

Q_LEN, A_LEN = 6, 10


def build_corpora(n_q, seed=0):
    rng = np.random.RandomState(seed)
    topics = ["jax", "tpu", "mesh", "shard", "kernel", "compile"]
    questions, answers, relations = [], [], []
    for i in range(n_q):
        topic = topics[rng.randint(len(topics))]
        questions.append((f"q{i}", f"what is {topic} and how to use it"))
        answers.append((f"a{i}_pos",
                        f"{topic} is used like this {topic} example"))
        off_topic = topics[rng.randint(len(topics))]
        answers.append((f"a{i}_neg",
                        f"unrelated text about {off_topic} cooking"))
        relations.append(Relation(f"q{i}", f"a{i}_pos", 1))
        relations.append(Relation(f"q{i}", f"a{i}_neg", 0))
    q_set = TextSet.from_texts([t for _, t in questions])
    for f, (uri, _) in zip(q_set.features, questions):
        f.uri = uri
    a_set = TextSet.from_texts([t for _, t in answers])
    for f, (uri, _) in zip(a_set.features, answers):
        f.uri = uri
    q_set.tokenize().word2idx().shape_sequence(len=Q_LEN)\
         .generate_sample()
    a_set.set_word_index(q_set.get_word_index())
    a_set.tokenize().word2idx(existing_map=q_set.get_word_index())\
         .shape_sequence(len=A_LEN).generate_sample()
    return q_set, a_set, relations


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    n_q = 32 if args.quick else 256
    epochs = 5 if args.quick else 20

    q_set, a_set, relations = build_corpora(n_q)
    pairs = from_relation_pairs(relations, q_set, a_set)
    vocab = max(max(q_set.get_word_index().values()),
                max(a_set.get_word_index().values()))
    model = KNRM(text1_length=Q_LEN, text2_length=A_LEN, vocab=vocab,
                 embed_dim=16)
    model.fit(pairs, batch_size=16, epochs=epochs)

    # ranking evaluation: positive should outscore negative per query
    lists = from_relation_lists(relations, q_set, a_set)
    wins = 0
    for x, y in lists:
        scores = np.asarray(model.predict(x, batch_size=8)).ravel()
        wins += int(scores[np.argmax(y)] > scores[np.argmin(y)])
    acc = wins / len(lists)
    print(f"pairwise ranking accuracy: {acc:.3f}")
    # quality bar: on-topic answers share tokens with their question,
    # so a trained KNRM must rank positives over negatives (this is
    # NDCG@1 on one-positive/one-negative lists)
    assert acc >= 0.75, f"qa ranking stopped learning: {acc:.3f}"


if __name__ == "__main__":
    main()
