"""DataFrame-native training with NNFrames
(ref: pyzoo/zoo/examples/nnframes + the dogs-vs-cats transfer-learning
app): NNClassifier.fit(df) -> NNClassifierModel.transform(df).
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse

import numpy as np
import pandas as pd

from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras.layers import Dense
from analytics_zoo_tpu.nnframes import NNClassifier, SeqToTensor


def make_df(n, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] - 0.25 * x[:, 2] > 0).astype(np.int64)
    return pd.DataFrame({"features": [row for row in x], "label": y})


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    n = 512 if args.quick else 8192
    epochs = 5 if args.quick else 20

    df = make_df(n)
    train, test = df.iloc[:int(0.9 * n)], df.iloc[int(0.9 * n):]
    clf = (NNClassifier(
        Sequential([Dense(32, activation="relu"), Dense(2)]),
        feature_preprocessing=SeqToTensor([8]))
        .setBatchSize(64).setMaxEpoch(epochs).setLearningRate(1e-2))
    model = clf.fit(train)
    out = model.transform(test)
    acc = (out["prediction"].values == test["label"].values).mean()
    print(f"test accuracy: {acc:.3f}")
    # quality bar: the synthetic classes are separable; a working
    # DataFrame fit/transform pipeline must crack them
    assert acc >= 0.85, f"nnframes classifier degraded: {acc:.3f}"


if __name__ == "__main__":
    main()
