"""Long-context + pipeline parallelism on a device mesh (new TPU-first
capability; the reference has neither -- SURVEY.md section 5): ring
attention inside a Transformer forward, and a pipeline-parallel train
step. Runs on an 8-device virtual CPU mesh anywhere.
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse
import os


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--devices", type=int, default=8)
    args = ap.parse_args()

    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags +
            f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    import optax

    from analytics_zoo_tpu.common.context import (
        init_zoo_context, stop_orca_context)
    from analytics_zoo_tpu.keras.layers.transformer import (
        TransformerModule)
    from analytics_zoo_tpu.parallel import create_mesh
    from analytics_zoo_tpu.parallel.pipeline import (
        pipeline_apply, pipeline_train_step)

    n = args.devices
    rng = np.random.RandomState(0)

    # --- ring attention inside a model forward: sequence sharded over
    # the mesh's seq axis; attention is exact at any length
    init_zoo_context(mesh_shape={"seq": n})
    try:
        seq_len = 16 * n
        ids = rng.randint(0, 64, (2, seq_len)).astype(np.int32)
        model = TransformerModule(vocab=64, seq_len=seq_len,
                                  hidden_size=32, n_head=4, n_block=2,
                                  seq_axis="seq")
        variables = model.init(jax.random.PRNGKey(0), ids)
        out = jax.jit(model.apply)(variables, ids)
        print(f"ring attention over seq={seq_len} on {n} devices:",
              out.shape)
        # the causal stack auto-routes through the ZIGZAG schedule
        # (~2x less attention compute); prove exactness vs dense here
        from analytics_zoo_tpu.parallel.ring_attention import (
            ring_attention, zigzag_ring_attention)

        mesh = create_mesh({"seq": n})
        q = jnp.asarray(rng.randn(1, seq_len, 4, 8), jnp.float32)
        zig = zigzag_ring_attention(q, q, q, mesh, axis_name="seq")
        contig = ring_attention(q, q, q, mesh, axis_name="seq",
                                causal=True)
        err = float(jnp.abs(zig - contig).max())
        print(f"zigzag == contiguous causal ring: max err {err:.2e}")
        assert err < 1e-4
    finally:
        stop_orca_context()

    # --- pipeline parallelism: one stage per device, trained end to end
    mesh = create_mesh({"pipe": n})
    dim = 16
    ws = jnp.asarray(rng.randn(n, dim, dim) * 0.3, jnp.float32)
    mbs = jnp.asarray(rng.randn(4, 8, dim), jnp.float32)
    targets = jnp.tanh(jnp.asarray(rng.randn(4, 8, dim), jnp.float32))

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    tx = optax.adam(1e-2)
    step = pipeline_train_step(
        stage_fn, lambda o, t: jnp.mean((o - t) ** 2), tx, mesh)
    opt = tx.init(ws)
    steps = 20 if args.quick else 100
    first = last = None
    for _ in range(steps):
        ws, opt, loss = step(ws, opt, mbs, targets)
        first = float(loss) if first is None else first
        last = float(loss)
    print(f"pipeline train over {n} stages: loss {first:.4f} -> "
          f"{last:.4f}")
    out = pipeline_apply(stage_fn, ws, mbs, mesh)
    print("pipeline forward:", out.shape)


if __name__ == "__main__":
    main()
