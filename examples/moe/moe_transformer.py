"""Mixture-of-experts language model with expert parallelism.

New capability relative to the reference (data-parallel only — no
expert parallelism anywhere in analytics-zoo): a small causal LM whose
FFN band is a routed expert mixture, trained through the Estimator
with the load-balance aux loss reaching the optimizer, on an
(optionally) dp x ep device mesh with either EP layout:

- broadcast (exact, shards expert memory), or
- all_to_all dispatch (capacity buffers, shards compute too).

Run: python examples/moe/moe_transformer.py [--quick]
     [--layout {broadcast,dispatch}]
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse

import numpy as np

VOCAB, SEQ, HIDDEN = 64, 16, 32


def _force_devices(n: int) -> None:
    """Virtual CPU devices so the dp x ep mesh exists anywhere (must
    run before the first jax backend use)."""
    flags = _os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        _os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()


def lm_data(n, seed=0):
    """Next-token task with structure: even tokens are followed by
    token+1, odd tokens by token-1 (mod vocab) -- learnable quickly."""
    rng = np.random.RandomState(seed)
    x = np.zeros((n, SEQ), np.int32)
    x[:, 0] = rng.randint(0, VOCAB, n)
    for t in range(1, SEQ):
        prev = x[:, t - 1]
        x[:, t] = np.where(prev % 2 == 0, prev + 1, prev - 1) % VOCAB
    y = np.roll(x, -1, axis=1)
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--layout", default="broadcast",
                    choices=["broadcast", "dispatch"])
    ap.add_argument("--devices", type=int, default=8)
    args = ap.parse_args()
    n = 256 if args.quick else 4096
    # dispatch drops overflow tokens, so it needs a few more epochs
    # than broadcast to cross the same loss bar
    epochs = 14 if args.quick else 30
    _force_devices(args.devices)

    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.common.context import (
        init_zoo_context, stop_orca_context)
    from analytics_zoo_tpu.keras.layers import MoETransformerBlock
    from analytics_zoo_tpu.learn.estimator import Estimator

    n_dev = len(jax.devices())
    ep = 2 if n_dev % 2 == 0 else 1
    mesh_shape = ({"data": n_dev // ep, "expert": ep}
                  if ep > 1 else {"data": n_dev})
    init_zoo_context(mesh_shape=mesh_shape)
    try:
        class MoELM(nn.Module):
            @nn.compact
            def __call__(self, ids, train: bool = False):
                h = nn.Embed(VOCAB, HIDDEN)(ids.astype(jnp.int32))
                h = MoETransformerBlock(
                    hidden_size=HIDDEN, n_head=2,
                    intermediate_size=64, n_experts=4, top_k=2,
                    causal=True, hidden_dropout=0.0, attn_dropout=0.0,
                    expert_axis="expert" if ep > 1 else None,
                    layout=args.layout, capacity_factor=2.0,
                )(h, train=train)
                return nn.Dense(VOCAB)(h)

        def token_ce(preds, labels):
            logp = jax.nn.log_softmax(
                preds.reshape(-1, VOCAB).astype(jnp.float32))
            flat = labels.reshape(-1).astype(jnp.int32)
            return -jnp.mean(logp[jnp.arange(flat.size), flat])

        x, y = lm_data(n)
        est = Estimator(MoELM(), loss=token_ce, optimizer="adam",
                        seed=0)
        hist = est.fit((x, y), batch_size=64, epochs=epochs)
        drop = hist[-1]["loss"] / max(hist[0]["loss"], 1e-9)
        print(f"mesh {mesh_shape} layout={args.layout} "
              f"loss {hist[0]['loss']:.3f} -> {hist[-1]['loss']:.3f}")
        # quality bar: the deterministic successor rule must be
        # learnable fast; a broken router/dispatch stalls the loss
        assert drop < 0.5, f"MoE LM stopped learning: ratio {drop:.2f}"
    finally:
        stop_orca_context()


if __name__ == "__main__":
    main()
