"""GAN training with GANEstimator (ref: pyzoo/zoo/examples/tfpark/gan):
learn a 2-D gaussian mixture mode with alternating G/D updates.
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse

import flax.linen as nn
import numpy as np

from analytics_zoo_tpu.learn import GANEstimator


class Generator(nn.Module):
    @nn.compact
    def __call__(self, z):
        h = nn.relu(nn.Dense(32)(z))
        return nn.Dense(2)(h)


class Discriminator(nn.Module):
    @nn.compact
    def __call__(self, x):
        h = nn.relu(nn.Dense(32)(x))
        return nn.Dense(1)(h)[:, 0]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    n = 1024 if args.quick else 8192
    # the generator needs ~60 epochs before both coordinates settle
    # on the data mode; quick epochs are 8 steps each, so CI affords it
    epochs = 80 if args.quick else 150

    rng = np.random.RandomState(0)
    data = (rng.randn(n, 2).astype(np.float32) * 0.4
            + np.asarray([1.5, -0.5], np.float32))
    # seed=0 pins the jax PRNG stream (init + per-step noise) on top
    # of the numpy data seed, so a run is bit-deterministic for a
    # given jax version; adversarial training still lands on version-
    # dependent equilibria, which the bound below absorbs
    gan = GANEstimator(Generator(), Discriminator(), noise_dim=8,
                       seed=0)
    history = gan.fit(data, batch_size=128, epochs=epochs)
    print("final:", {k: round(v, 3)
                     for k, v in history[-1].items() if k != "seconds"})
    samples = gan.generate(512)
    gen_mean = samples.mean(0)
    print("generated mean:", gen_mean.round(2), "(target [1.5, -0.5])")
    # quality bar: the generator must move its mass to the data mode
    # (adversarial training collapsed or stalled otherwise). The
    # statistical floor is tiny -- the mean of 512 samples from an
    # on-mode generator has standard error ~sigma/sqrt(512) ~= 0.02 --
    # so 0.8 (2 sigma of the DATA spread) is pure head-room for the
    # cross-version equilibrium wobble of adversarial training, while
    # a collapsed/stalled generator (mean ~0, i.e. 1.5 off on the
    # first coordinate) still fails clearly.
    target = np.asarray([1.5, -0.5])
    assert np.abs(gen_mean - target).max() < 0.8, (
        f"generator missed the data mode: {gen_mean.round(2)}")


if __name__ == "__main__":
    main()
