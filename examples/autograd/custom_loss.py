"""Training with an autograd-built custom loss.

The analog of the reference's autograd examples
(ref: pyzoo/zoo/examples/autograd/custom.py + customloss.py — losses
assembled from Variable math and compiled into the optimizer): here
the same ``A.*`` ops build an asymmetric regression loss (under-
predictions cost 4x more than over-predictions, the classic inventory
objective), and the fitted model's bias demonstrates the loss took
effect — it over-predicts relative to an MSE fit.

Run: python examples/autograd/custom_loss.py [--quick]
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse

import numpy as np

from analytics_zoo_tpu import autograd as A
from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras.layers import Dense


def make_data(n, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    y = (x @ np.asarray([1.0, -2.0, 0.5, 3.0], np.float32)
         + 0.1 * rng.randn(n)).astype(np.float32)[:, None]
    return x, y


def asymmetric_loss(y_pred, y_true):
    """Under-prediction (y_pred < y_true) costs 4x over-prediction."""
    diff = y_pred - y_true
    return A.mean(A.maximum(-4.0 * diff, diff), axis=0)


def fit(loss, x, y, epochs):
    model = Sequential([Dense(16, activation="relu"), Dense(1)])
    model.compile(optimizer="adam", loss=loss)
    model.fit(x, y, batch_size=64, nb_epoch=epochs)
    return np.asarray(model.predict(x, batch_size=256))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    n = 2000 if args.quick else 20000
    # quick needs ~1400 adam steps to pass the MAE bar on every jax
    # line we run (at 15 epochs jax 0.4.x numerics were still
    # mid-transit: MAE 0.70; 45 epochs lands at 0.16, ~3x under the
    # 0.5 bound, for ~3s of extra CPU)
    epochs = 45 if args.quick else 40

    x, y = make_data(n)
    preds_asym = fit(A.CustomLoss(asymmetric_loss), x, y, epochs)
    preds_mse = fit("mse", x, y, epochs)

    bias_asym = float(np.mean(preds_asym - y))
    bias_mse = float(np.mean(preds_mse - y))
    mae = float(np.mean(np.abs(preds_asym - y)))
    print(f"mean bias: asymmetric {bias_asym:+.3f} vs mse "
          f"{bias_mse:+.3f}; asymmetric MAE {mae:.3f}")
    # quality bars: the custom loss must (a) actually fit the signal
    # and (b) shift predictions upward relative to the symmetric fit
    # (that shift IS the custom objective working)
    assert mae < 0.5, f"custom-loss fit failed: MAE {mae:.3f}"
    assert bias_asym > bias_mse + 0.05, (
        f"asymmetric loss did not bias predictions: "
        f"{bias_asym:+.3f} vs {bias_mse:+.3f}")


if __name__ == "__main__":
    main()
