"""Image augmentation chains, 2-D and detection-aware.

The analog of apps/image-augmentation (+ image-augmentation-3d): run a
composable op chain over an ImageSet, and a detection chain that keeps
bounding boxes consistent through expand/flip/crop/resize.
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse

import numpy as np

from analytics_zoo_tpu.feature.image import (
    ChainedImageProcessing, ImageAspectScale, ImageBrightness,
    ImageCenterCrop, ImageColorJitter, ImageExpand, ImageFeature,
    ImageHFlip, ImageRandomTransformer, ImageResize, ImageSet)
from analytics_zoo_tpu.feature.image3d import Crop3D, Rotate3D


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    n = 8 if args.quick else 64
    rng = np.random.RandomState(0)

    # --- classification chain over an ImageSet
    images = rng.rand(n, 48, 64, 3).astype(np.float32) * 255
    chain = ChainedImageProcessing([
        ImageResize(40, 56),
        ImageRandomTransformer(ImageHFlip(), prob=0.5, seed=1),
        ImageBrightness(-16, 16, seed=2),
        ImageColorJitter(seed=3),
        ImageCenterCrop(32, 48),
    ])
    out = ImageSet.from_arrays(images).transform(chain)
    shapes = {f.image.shape for f in out.features}
    print(f"classification chain: {n} images -> shapes {shapes}")
    # bar: every op ran -- the chain must land on the crop size and
    # keep pixel values in range (a broken op silently passes neither)
    assert shapes == {(32, 48, 3)}, shapes
    assert all(0 <= f.image.min() and f.image.max() <= 255
               for f in out.features)

    # --- detection chain: boxes follow every geometric op
    feat = ImageFeature(images[0], bboxes=[[10, 8, 30, 28]],
                        bbox_labels=[1])
    det_chain = ChainedImageProcessing([
        ImageExpand(max_expand_ratio=2.0, seed=4),
        ImageHFlip(),
        ImageAspectScale(min_size=48, max_size=120),
    ])
    feat = det_chain.transform(feat)
    print(f"detection chain: image {feat.image.shape}, "
          f"box {np.round(feat.bboxes[0], 1).tolist()} "
          f"(label {feat.bbox_labels[0]})")

    # --- 3-D chain (the image-augmentation-3d app)
    vol = rng.rand(24, 24, 24).astype(np.float32)
    v = Crop3D((2, 2, 2), (20, 20, 20)).apply_image(vol)
    v = Rotate3D(np.pi / 8, axis="z").apply_image(v)
    print(f"3d chain: volume {vol.shape} -> {v.shape}")


if __name__ == "__main__":
    main()
