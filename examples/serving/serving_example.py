"""Streaming model serving end-to-end
(ref: Cluster Serving -- ClusterServing.scala + client.py +
FrontEndApp.scala): queue clients + micro-batching worker + HTTP
/predict + /metrics.
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse
import json
import urllib.request

import flax.linen as nn
import numpy as np

from analytics_zoo_tpu.inference import InferenceModel
from analytics_zoo_tpu.serving import (
    HttpFrontend, InputQueue, OutputQueue, ServingWorker)


class Net(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.Dense(3)(nn.relu(nn.Dense(16)(x)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--requests", type=int, default=32)
    args = ap.parse_args()

    import jax

    net = Net()
    variables = net.init(jax.random.PRNGKey(0),
                         np.zeros((1, 4), np.float32))
    model = InferenceModel()
    model.load_flax(net, variables)

    in_q, out_q = InputQueue(maxlen=1024), OutputQueue()
    worker = ServingWorker(model, in_q, out_q, batch_size=8,
                           timeout_ms=5).start()

    # --- queue-client path (InputQueue/OutputQueue, client.py parity)
    rng = np.random.RandomState(0)
    first = None
    for i in range(args.requests):
        x_i = rng.randn(4).astype(np.float32)
        if first is None:
            first = x_i
        in_q.enqueue(f"req-{i}", input=x_i)
    got = {}
    while len(got) < args.requests:
        uri, tensors = out_q.dequeue(timeout=10)
        got[uri] = tensors
    print(f"queue path: {len(got)} responses, "
          f"output shape {got['req-0']['output'].shape}")
    # quality bar: a served response must match the model called
    # directly -- the data plane may batch and pad, never alter
    direct = np.asarray(model.predict(first[None]))
    np.testing.assert_allclose(
        np.asarray(got["req-0"]["output"]), direct[0],
        rtol=1e-4, atol=1e-5)

    # --- HTTP path (/predict + /metrics, FrontEndApp parity)
    frontend = HttpFrontend(in_q, out_q, worker=worker).start()
    payload = json.dumps(
        {"inputs": {"input": rng.randn(4).astype(
            np.float32).tolist()}}).encode()
    req = urllib.request.Request(
        frontend.address + "/predict", data=payload,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=20) as resp:
        print("http /predict:", json.loads(resp.read()).keys())
    with urllib.request.urlopen(frontend.address + "/metrics.json",
                                timeout=20) as resp:
        metrics = json.loads(resp.read())
        print("http /metrics.json keys:",
              sorted(metrics)[:4], "...")
    # Prometheus text exposition (the scrape surface; obs registry)
    with urllib.request.urlopen(frontend.address + "/metrics",
                                timeout=20) as resp:
        text = resp.read().decode()
        print("http /metrics:",
              sum(1 for ln in text.splitlines()
                  if ln.startswith("zoo_")), "series lines")
    frontend.stop()
    worker.stop()


if __name__ == "__main__":
    main()
