"""Image similarity search: embed with a trained classifier, rank a
gallery by cosine similarity.

The analog of apps/image-similarity (the reference extracts deep
features with a pretrained model and ranks by distance): train a small
classifier on synthetic clusters, use its logits as embeddings, and
check nearest-gallery retrieval returns the query's cluster.
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse

import numpy as np

from analytics_zoo_tpu.models.image.classifier import ImageClassifier


def synthetic_gallery(n_per_class, classes, size=32, seed=0):
    rng = np.random.RandomState(seed)
    xs, ys = [], []
    for c in range(classes):
        imgs = rng.rand(n_per_class, size, size, 3).astype(
            np.float32) * 0.2
        cx = 6 + (c % 3) * 9
        cy = 6 + (c // 3) * 9
        imgs[:, cy:cy + 6, cx:cx + 6, c % 3] = 1.0
        xs.append(imgs)
        ys.append(np.full(n_per_class, c, np.int32))
    return np.concatenate(xs), np.concatenate(ys)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    per = 24 if args.quick else 128
    # the embedding needs ~10 epochs before retrieval is reliable
    epochs = 12 if args.quick else 20

    x, y = synthetic_gallery(per, classes=6)
    model = ImageClassifier(class_num=6, backbone="resnet18",
                            image_size=32)
    model.fit((x, y), batch_size=48, epochs=epochs)

    # gallery embeddings = logits (class-discriminative deep features)
    emb = np.asarray(model.predict(x, batch_size=48))
    emb = emb / np.linalg.norm(emb, axis=1, keepdims=True)

    # fresh queries, one per class
    qx, qy = synthetic_gallery(2, classes=6, seed=7)
    qe = np.asarray(model.predict(qx, batch_size=48))
    qe = qe / np.linalg.norm(qe, axis=1, keepdims=True)

    sims = qe @ emb.T                       # [Q, gallery]
    top1 = y[np.argmax(sims, axis=1)]
    acc = float(np.mean(top1 == qy))
    print(f"top-1 retrieval accuracy over {len(qy)} queries: {acc:.2f}")
    # quality bar: distinct patch locations per class make retrieval
    # easy for a trained embedding; below 0.8 it stopped learning
    assert acc >= 0.8, f"similarity retrieval degraded: {acc:.2f}"
    best = np.argmax(sims[0])
    print(f"query 0 (class {qy[0]}) -> gallery item {best} "
          f"(class {y[best]}, cosine {sims[0, best]:.3f})")


if __name__ == "__main__":
    main()
