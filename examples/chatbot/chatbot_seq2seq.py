"""Chatbot: Seq2seq trained on a toy token-level dialogue task.

The analog of the reference's chatbot example (ref: zoo/.../examples/
chatbot -- a Seq2seq encoder/decoder trained on dialogue pairs, greedy
inference for replies). Synthetic "language": replies reverse the
request tokens and append an end marker -- learnable by a small
encoder/decoder and easy to verify exactly.
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse

import numpy as np

from analytics_zoo_tpu.models import Seq2seq

PAD, START, END = 0, 1, 2
FIRST_WORD = 3


def dialogue_pairs(n, vocab, seq_len, seed=0):
    """Request: random tokens; reply: the reversed request + END."""
    rng = np.random.RandomState(seed)
    src = rng.randint(FIRST_WORD, vocab, (n, seq_len)).astype(np.int32)
    reply = src[:, ::-1]
    tgt_in = np.concatenate([np.full((n, 1), START, np.int32),
                             reply[:, :-1]], axis=1)
    tgt_out = np.concatenate([reply[:, :-1],
                              np.full((n, 1), END, np.int32)], axis=1)
    return src, tgt_in, tgt_out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    n = 1024 if args.quick else 8192
    # the reversal task needs ~25 epochs before the loss curve bends;
    # post-compile epochs are cheap enough to keep quick mode honest
    epochs = 30 if args.quick else 40
    vocab, seq_len = 20, 6

    src, tgt_in, tgt_out = dialogue_pairs(n, vocab, seq_len)
    bot = Seq2seq(vocab=vocab, embed_dim=32, hidden_sizes=(64,),
                  max_len=seq_len)
    hist = bot.fit(({"src": src, "tgt_in": tgt_in}, tgt_out),
                   batch_size=128, epochs=epochs)
    # quality bar: token-level cross-entropy over the reversal task
    # must fall steeply across the run (exact-match replies need the
    # longer non-quick schedule; the learning signal must not)
    drop = hist[-1]["loss"] / max(hist[0]["loss"], 1e-9)
    print(f"loss {hist[0]['loss']:.3f} -> {hist[-1]['loss']:.3f}")
    assert drop < 0.5, (
        f"seq2seq stopped learning: loss ratio {drop:.2f}")

    # chat: greedy replies for fresh requests
    q, _, want = dialogue_pairs(4, vocab, seq_len, seed=99)
    replies = bot.infer(q, start_id=START, max_len=seq_len)
    exact = float(np.mean(np.all(replies == want, axis=1)))
    for i in range(2):
        print(f"user: {q[i].tolist()}  bot: {replies[i].tolist()}")
    print(f"exact-reply rate on 4 fresh requests: {exact:.2f}")


if __name__ == "__main__":
    main()
