"""Sentiment analysis with an embedding + bidirectional-LSTM classifier.

The analog of the reference's sentiment-analysis app
(ref: apps/sentiment-analysis/sentiment.ipynb — word embeddings into
recurrent encoders over movie-review text): TextSet preprocessing into
a Keras ``Sequential`` of Embedding → Bidirectional(LSTM) → Dense,
trained and evaluated through the Keras engine (a different surface
from examples/textclassification, which uses the TextClassifier zoo
model).

Run: python examples/sentiment/sentiment_analysis.py [--quick]
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse

import numpy as np

from analytics_zoo_tpu.feature import TextSet
from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras.layers import (
    Bidirectional, Dense, Embedding, LSTM)

SEQ_LEN = 16

GOOD = ["an uplifting heartfelt triumph with radiant performances",
        "gorgeous photography and a tender generous script",
        "joyful inventive storytelling that rewards every minute"]
BAD = ["a tedious shallow slog with lifeless dialogue",
       "clumsy pacing and a grating charmless script",
       "derivative plodding mess that squanders its premise"]


def reviews(n_per_class, seed=0):
    rng = np.random.RandomState(seed)
    texts, labels = [], []
    for label, bank in [(1, GOOD), (0, BAD)]:
        for _ in range(n_per_class):
            texts.append(bank[rng.randint(len(bank))])
            labels.append(label)
    return texts, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    n = 120 if args.quick else 1200
    epochs = 6 if args.quick else 20

    texts, labels = reviews(n)
    ts = (TextSet.from_texts(texts, labels)
          .tokenize().normalize().word2idx()
          .shape_sequence(len=SEQ_LEN).generate_sample())
    train, val = ts.random_split(0.8)
    xt, yt = train.to_arrays()
    xv, yv = val.to_arrays()
    vocab = len(ts.get_word_index()) + 1

    model = Sequential([
        Embedding(vocab, 32),
        Bidirectional(LSTM(32)),
        Dense(2),
    ])
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(xt, yt, batch_size=32, nb_epoch=epochs)
    res = model.evaluate(xv, yv, batch_size=32)
    print("validation:", res)
    # quality bar: the polarity banks share no content words, so a
    # working embed+BiLSTM encoder must separate them
    assert res["accuracy"] >= 0.9, (
        f"sentiment classifier stopped learning: {res['accuracy']:.3f}")


if __name__ == "__main__":
    main()
