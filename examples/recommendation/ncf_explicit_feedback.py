"""NCF explicit-feedback recommendation (north-star workload #1).

The analog of apps/recommendation-ncf/ncf-explicit-feedback.ipynb:
train NeuralCF on (user, item) -> rating 1..5, evaluate, and emit
top-N recommendations per user.
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse

import numpy as np

from analytics_zoo_tpu.models import NeuralCF


def synthetic_ratings(n_users, n_items, n, seed=0):
    """MovieLens-shaped synthetic data: latent affinity -> 1..5 stars."""
    rng = np.random.RandomState(seed)
    u_lat = rng.randn(n_users + 1, 4)
    i_lat = rng.randn(n_items + 1, 4)
    users = rng.randint(1, n_users + 1, n)
    items = rng.randint(1, n_items + 1, n)
    score = (u_lat[users] * i_lat[items]).sum(1)
    ratings = np.clip(np.digitize(score, [-2, -0.5, 0.5, 2]) + 1, 1, 5)
    x = np.stack([users, items], 1).astype(np.int32)
    return x, ratings.astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--users", type=int, default=200)
    ap.add_argument("--items", type=int, default=100)
    args = ap.parse_args()
    n = 20_000 if args.quick else 200_000
    epochs = 40 if args.quick else 25
    bar = 0.55 if args.quick else 0.65  # 5-class; random = 0.20

    x, y = synthetic_ratings(args.users, args.items, n)
    cut = int(0.9 * n)
    model = NeuralCF(args.users, args.items, class_num=5)
    # the dataset fits in device memory: one compiled program per epoch
    model.fit((x[:cut], y[:cut]), batch_size=512, epochs=epochs,
              device_cache=True)
    res = model.evaluate((x[cut:], y[cut:]), batch_size=1024)
    print("validation:", res)
    assert res["accuracy"] >= bar, (
        f"quality bar missed: accuracy {res['accuracy']:.3f} < {bar}")
    print(f"quality bar met: accuracy {res['accuracy']:.3f} >= {bar}")

    # top-5 recommendations for one user (Recommender API parity)
    user = 7
    cand = np.stack([np.full(args.items, user),
                     np.arange(1, args.items + 1)], 1).astype(np.int32)
    logits = np.asarray(model.predict(cand, batch_size=1024))
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    expected = (probs * np.arange(1, 6)).sum(-1)
    top = np.argsort(-expected)[:5] + 1
    print(f"top-5 items for user {user}: {top.tolist()}")


if __name__ == "__main__":
    main()
