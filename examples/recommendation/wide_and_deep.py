"""Wide & Deep recommendation (ref workload #2:
apps/recommendation-wide-n-deep/wide_n_deep.ipynb): joint wide
(memorization) + deep (generalization) model over categorical and
continuous features.
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse

import numpy as np

from analytics_zoo_tpu.models import ColumnFeatureInfo, WideAndDeep


WIDE_DIMS = [20, 20]  # one-hot width per wide column (values 1..19)


def synthetic_tabular(n, seed=0):
    rng = np.random.RandomState(seed)
    wide = rng.randint(1, 20, (n, 2)).astype(np.int32)
    embed = rng.randint(0, 10, (n, 2)).astype(np.int32)
    cont = rng.randn(n, 3).astype(np.float32)
    y = ((wide[:, 0] > 10).astype(int) + (cont[:, 0] > 0) + 1
         ).astype(np.int32)  # ratings 1..3
    # the wide tensor holds indices into ONE concatenated one-hot
    # space, so each column's ids are shifted by the widths of the
    # columns before it (the reference assembles wide features the
    # same way, ref: WideAndDeep feature engineering getWideTensor);
    # without the offset, columns alias each other's table rows
    offsets = np.cumsum([0] + WIDE_DIMS[:-1]).astype(np.int32)
    return ({"wide": wide + offsets[None, :], "embed": embed,
             "continuous": cont}, y)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--model-type", default="wide_n_deep",
                    choices=["wide_n_deep", "wide", "deep"])
    args = ap.parse_args()
    n = 10_000 if args.quick else 100_000
    # post-compile epochs cost ~40 ms each at this scale; the model
    # needs ~12 to crack the label rule, so quick mode can afford them
    epochs = 15 if args.quick else 20

    # wide columns take values 1..19, so their one-hot/cross buckets
    # need 20 slots -- undersized dims would alias ids above 9 and
    # erase the (wide > 10) half of the label signal
    info = ColumnFeatureInfo(
        wide_base_cols=["a", "b"], wide_base_dims=WIDE_DIMS,
        embed_cols=["c", "d"], embed_in_dims=[10, 10],
        embed_out_dims=[8, 8], continuous_cols=["x", "y", "z"])
    x, y = synthetic_tabular(n)
    cut = int(0.9 * n)
    model = WideAndDeep(args.model_type, class_num=3,
                        column_info=info)
    model.fit(({k: v[:cut] for k, v in x.items()}, y[:cut]),
              batch_size=512, epochs=epochs)
    res = model.evaluate(({k: v[cut:] for k, v in x.items()}, y[cut:]),
                         batch_size=512)
    print("validation:", res)
    # quality bar: the label is a deterministic function of one wide
    # and one continuous column; a joint wide+deep model must crack it
    bar = 0.80 if args.model_type == "wide_n_deep" else 0.55
    assert res["accuracy"] >= bar, (
        f"wide&deep stopped learning: accuracy {res['accuracy']:.3f} "
        f"< {bar}")


if __name__ == "__main__":
    main()
