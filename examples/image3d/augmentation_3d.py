"""3-D volumetric image augmentation (medical-imaging preprocessing).

The analog of the reference's image-augmentation-3d app
(ref: apps/image-augmentation-3d/image-augmentation-3d.ipynb — crop /
rotate / affine chains over CT-like volumes through the image3d
feature ops): builds a synthetic volume with a bright ellipsoid
"lesion", runs the 3-D op chain, and checks the geometry actually did
what it claims (shapes, determinism, and that rotation moves the
lesion's center of mass the right way).

Run: python examples/image3d/augmentation_3d.py [--quick]
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse

import numpy as np

from analytics_zoo_tpu.feature import (
    AffineTransform3D, CenterCrop3D, Crop3D, RandomCrop3D, Rotate3D)

DIMS = (24, 32, 32)


def volume(seed=0):
    """Noise volume with a bright off-center ellipsoid."""
    rng = np.random.RandomState(seed)
    vol = 0.05 * rng.rand(*DIMS).astype(np.float32)
    z, y, x = np.meshgrid(*[np.arange(d) for d in DIMS], indexing="ij")
    lesion = (((z - 12) / 4) ** 2 + ((y - 10) / 5) ** 2
              + ((x - 22) / 5) ** 2) < 1.0
    vol[lesion] = 1.0
    return vol


def center_of_mass(vol):
    w = vol / vol.sum()
    grids = np.meshgrid(*[np.arange(d) for d in vol.shape],
                        indexing="ij")
    return np.asarray([(g * w).sum() for g in grids])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.parse_args()

    vol = volume()

    crop = Crop3D(start=(4, 4, 4), patch=(16, 24, 24)).apply_image(vol)
    assert crop.shape == (16, 24, 24)
    center = CenterCrop3D(patch=(16, 16, 16)).apply_image(vol)
    assert center.shape == (16, 16, 16)
    r1 = RandomCrop3D(patch=(8, 8, 8), seed=7).apply_image(vol)
    r2 = RandomCrop3D(patch=(8, 8, 8), seed=7).apply_image(vol)
    np.testing.assert_array_equal(r1, r2)  # seeded => reproducible

    # rotate the (h, w) plane a quarter turn: the lesion's x-offset
    # from center must become a y-offset (geometry, not just shapes)
    rot = Rotate3D(angle=np.pi / 2, axis="z").apply_image(vol)
    com0 = center_of_mass(vol) - (np.asarray(DIMS) - 1) / 2
    com1 = center_of_mass(rot) - (np.asarray(DIMS) - 1) / 2
    print(f"lesion offset before {com0.round(1)} after {com1.round(1)}")
    assert abs(com1[1] - com0[2]) < 2.0 or \
        abs(com1[1] + com0[2]) < 2.0, "rotation moved the lesion wrong"
    assert abs(com1[0] - com0[0]) < 1.0  # depth axis untouched

    # shear + shift via the raw affine
    sheared = AffineTransform3D(
        np.asarray([[1, 0.2, 0], [0, 1, 0], [0, 0, 1]]),
        translation=(1.0, 0.0, 0.0)).apply_image(vol)
    assert sheared.shape == vol.shape
    assert 0.0 < sheared.max() <= 1.0 + 1e-5  # trilinear stays in range

    print("3-D augmentation chain: crop/center/random-crop/rotate/"
          "affine all verified")


if __name__ == "__main__":
    main()
