"""Inception-v1 distributed training on synthetic ImageNet-shaped data.

The reference's flagship distributed-training workload (ref: zoo/src/
main/scala/com/intel/analytics/zoo/examples/inception/Train.scala --
Inception-v1 over Spark executors with the BigDL allreduce engine).
Here the same model trains through the SPMD Estimator: the batch
shards over the mesh's data axis and XLA inserts the gradient
allreduce. Synthetic data stands in for ImageNet (this environment
ships no dataset); to train on real folders, load them with
``ImageSet.read`` and feed the arrays in place of ``synthetic_imagenet``.
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.abspath(_os.path.join(
    _os.path.dirname(__file__), "..", "..")))

import argparse

import numpy as np

from analytics_zoo_tpu.models.image.classifier import ImageClassifier


def synthetic_imagenet(n, classes, size, seed=0):
    """Class-correlated gradients + noise (stands in for ImageNet)."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n).astype(np.int32)
    ramp = np.linspace(0, 1, size, dtype=np.float32)
    x = rng.rand(n, size, size, 3).astype(np.float32) * 0.3
    for c in range(classes):
        idx = y == c
        x[idx, :, :, c % 3] += ramp[None, None, :] * ((c % 5) + 1) / 5.0
    return np.clip(x, 0, 1), y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--classes", type=int, default=1000)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=2)
    args = ap.parse_args()
    if args.quick:  # CI footprint
        args.classes, args.image_size = 8, 64
        args.batch_size, args.epochs = 32, 2
        n = 256
    else:
        n = 8 * args.batch_size

    x, y = synthetic_imagenet(n, args.classes, args.image_size)
    cut = int(0.875 * n)
    model = ImageClassifier(class_num=args.classes,
                            backbone="inception-v1",
                            image_size=args.image_size)
    hist = model.fit((x[:cut], y[:cut]), batch_size=args.batch_size,
                     epochs=args.epochs)
    res = model.evaluate((x[cut:], y[cut:]), batch_size=args.batch_size)
    print(f"epochs: {[round(h['loss'], 4) for h in hist]}")
    print(f"validation: {res}")
    assert hist[-1]["loss"] < hist[0]["loss"] * 1.05, hist


if __name__ == "__main__":
    main()
